"""Durability for the batched MultiNode engine: a segmented record log of
per-round state DELTAS plus periodic full checkpoints.

The reference persists one WAL per member (wal/wal.go) because one process
hosts one consensus instance. The engine hosts G groups x P slots in one
process, so durability batches ALL groups' changes from one kernel round
into ONE record and ONE fsync — the round is the natural commit unit (the
moral upgrade of the reference's batched Save, wal/wal.go:459-487).

Round-record payload (little-endian, numpy-packed column arrays):
    u32 round
    hs    deltas: n * (g:u32, p:u16, term:u32, vote:u16, commit:u32)
    last  deltas: n * (g:u32, p:u16, last:u32)
    ring  deltas: n * (g:u32, p:u16, index:u32, term:u32)
    entry payloads: n * (g:u32, index:u32, term:u32, len:u32, bytes)
    conf  changes: n * (g:u32, slot:u16, op:u8)

Framing per record: type:u32 crc:u32 len:u64 payload — crc is the rolling
zlib.crc32 over all payloads in the segment (seeded by the CRC record at the
segment head), the same mid-file-flip detection scheme as etcd_tpu/wal/wal.py
(reference wal/wal.go:60). A torn tail (crash mid-append) truncates replay at
the last whole, checksummed record; the engine then appends into a NEW
segment, never rewriting history.

Checkpoints are full-state JSON files written atomically (tmp+rename+fsync);
segments strictly older than the newest checkpoint's round are purged after
the checkpoint lands (reference snapshot-then-ReleaseLockTo sequencing,
etcdserver/storage.go:55-73).
"""
from __future__ import annotations

import base64
import json
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from etcd_tpu.utils.fileutil import fsync_dir, touch_dir_all
from etcd_tpu.utils import metrics

_HDR = struct.Struct("<IIQ")  # type, crc, len

REC_CRC = 1       # segment head: payload = u32 seed crc
REC_ROUND = 2     # one kernel round's deltas

CONF_ADD = 0
CONF_REMOVE = 1

_U32 = np.dtype("<u4")
_U16 = np.dtype("<u2")
_U8 = np.dtype("u1")


def _seg_name(seq: int, round_no: int) -> str:
    return f"engine-{seq:016x}-{round_no:016x}.wal"


def _parse_seg(name: str) -> Tuple[int, int]:
    stem = name[len("engine-"):-len(".wal")]
    a, b = stem.split("-")
    return int(a, 16), int(b, 16)


def _ckpt_name(round_no: int) -> str:
    return f"checkpoint-{round_no:016x}.json"


@dataclass
class RoundRecord:
    """One kernel round's durable deltas."""

    round_no: int
    # Columns (1-D numpy arrays, equal length per section):
    hs_g: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    hs_p: np.ndarray = field(default_factory=lambda: np.empty(0, _U16))
    hs_term: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    hs_vote: np.ndarray = field(default_factory=lambda: np.empty(0, _U16))
    hs_commit: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    last_g: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    last_p: np.ndarray = field(default_factory=lambda: np.empty(0, _U16))
    last_v: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    ring_g: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    ring_p: np.ndarray = field(default_factory=lambda: np.empty(0, _U16))
    ring_i: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    ring_t: np.ndarray = field(default_factory=lambda: np.empty(0, _U32))
    # (g, index, term, payload) proposals admitted this round:
    entries: List[Tuple[int, int, int, bytes]] = field(default_factory=list)
    # (g, slot, op) membership bit flips applied this round:
    confs: List[Tuple[int, int, int]] = field(default_factory=list)
    # (g, applied_index, store_blob) cross-host snapshot installs received
    # this round (hostengine): the store jumps wholesale to the blob's state
    # at applied_index. The same round's hs/ring/last diffs carry the
    # install's column surgery (mirrors are kept stale through it), so this
    # section records only what the diffs cannot: the state-machine image
    # and the apply cursor. Replayed FIRST within the record.
    snaps: List[Tuple[int, int, bytes]] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (len(self.hs_g) or len(self.last_g) or len(self.ring_g)
                    or self.entries or self.confs or self.snaps)

    def encode(self) -> bytes:
        out = [struct.pack("<I", self.round_no)]

        def cols(*arrs):
            n = len(arrs[0])
            out.append(struct.pack("<I", n))
            for a in arrs:
                out.append(np.ascontiguousarray(a).tobytes())

        cols(self.hs_g.astype(_U32), self.hs_p.astype(_U16),
             self.hs_term.astype(_U32), self.hs_vote.astype(_U16),
             self.hs_commit.astype(_U32))
        cols(self.last_g.astype(_U32), self.last_p.astype(_U16),
             self.last_v.astype(_U32))
        cols(self.ring_g.astype(_U32), self.ring_p.astype(_U16),
             self.ring_i.astype(_U32), self.ring_t.astype(_U32))
        out.append(struct.pack("<I", len(self.entries)))
        for g, i, t, payload in self.entries:
            out.append(struct.pack("<IIII", g, i, t, len(payload)))
            out.append(payload)
        out.append(struct.pack("<I", len(self.confs)))
        for g, slot, op in self.confs:
            out.append(struct.pack("<IHB", g, slot, op))
        # Trailing section, appended only when used: records written before
        # snapshots existed simply end here, and decode treats the missing
        # section as empty (same forward-compat trick a protobuf field
        # addition gives the reference's walpb).
        if self.snaps:
            out.append(struct.pack("<I", len(self.snaps)))
            for g, a, blob in self.snaps:
                out.append(struct.pack("<III", g, a, len(blob)))
                out.append(blob)
        return b"".join(out)

    @staticmethod
    def decode(b: bytes) -> "RoundRecord":
        off = 0

        def u32():
            nonlocal off
            (v,) = struct.unpack_from("<I", b, off)
            off += 4
            return v

        rec = RoundRecord(round_no=u32())

        def cols(dtypes):
            nonlocal off
            n = u32()
            outs = []
            for dt in dtypes:
                nbytes = n * dt.itemsize
                outs.append(np.frombuffer(b, dt, count=n, offset=off).copy())
                off += nbytes
            return outs

        (rec.hs_g, rec.hs_p, rec.hs_term, rec.hs_vote,
         rec.hs_commit) = cols([_U32, _U16, _U32, _U16, _U32])
        rec.last_g, rec.last_p, rec.last_v = cols([_U32, _U16, _U32])
        rec.ring_g, rec.ring_p, rec.ring_i, rec.ring_t = cols(
            [_U32, _U16, _U32, _U32])
        n_ents = u32()
        for _ in range(n_ents):
            g, i, t, ln = struct.unpack_from("<IIII", b, off)
            off += 16
            rec.entries.append((g, i, t, b[off:off + ln]))
            off += ln
        n_confs = u32()
        for _ in range(n_confs):
            g, slot, op = struct.unpack_from("<IHB", b, off)
            off += 7
            rec.confs.append((g, slot, op))
        if off < len(b):
            n_snaps = u32()
            for _ in range(n_snaps):
                g, a, ln = struct.unpack_from("<III", b, off)
                off += 12
                rec.snaps.append((g, a, b[off:off + ln]))
                off += ln
        return rec


class EngineWAL:
    """Append-only segmented log of RoundRecords + checkpoint management."""

    def __init__(self, dirname: str,
                 segment_size: int = 64 * 1024 * 1024,
                 fsync: bool = True) -> None:
        touch_dir_all(dirname)
        self.dir = dirname
        self.segment_size = segment_size
        self.fsync = fsync
        self._f = None
        self._crc = 0
        self._seq = -1
        # Highest round_no held in a WHOLE, checksummed record of this
        # stream (the stream's durable tail), maintained by replay() and
        # the write side. -1 until either has seen a record. The sharded
        # writer (walwriter.WALWriter) takes the min over its streams'
        # tails as the consistent replay boundary.
        self.last_round = -1
        self._pending_round = -1  # appended but not yet sync()ed

    # -- write side ---------------------------------------------------------

    def _open_segment(self, round_no: int) -> None:
        if self._f is not None:
            self._f.close()
        self._seq += 1
        path = os.path.join(self.dir, _seg_name(self._seq, round_no))
        self._f = open(path, "ab")
        self._write(REC_CRC, struct.pack("<I", self._crc))

    def _write(self, rtype: int, payload: bytes) -> None:
        from etcd_tpu import native
        buf, self._crc = native.encode_records([(rtype, payload)], self._crc)
        self._f.write(buf)

    def append_nosync(self, rec: RoundRecord) -> None:
        """Append one round record WITHOUT flushing or fsyncing — the
        group-commit half of the writer compartment: a batch of these
        followed by one sync() makes one fsync cover every queued round
        (the generalization of the reference's batched Save,
        wal/wal.go:459-487). The record is NOT durable until sync()."""
        if self._f is None:
            self._open_segment(rec.round_no)
        self._write(REC_ROUND, rec.encode())
        self._pending_round = max(self._pending_round, rec.round_no)

    def sync(self) -> None:
        """Flush + (optionally) fsync everything appended so far, then
        rotate if the segment is over size. After this returns, every
        append_nosync'd record is durable and last_round reflects it.
        Feeds the reference wal/metrics.go series (fsync latency in µs,
        last index saved — here: last round) alongside the engine's own
        per-shard histograms in walwriter.py."""
        if self._f is None:
            return
        t0 = time.perf_counter()
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        metrics.wal_fsync_durations.observe(
            (time.perf_counter() - t0) * 1e6)
        if self._pending_round >= 0:
            self.last_round = max(self.last_round, self._pending_round)
            self._pending_round = -1
            metrics.wal_last_index_saved.set(self.last_round)
        if self._f.tell() >= self.segment_size:
            self._open_segment(self.last_round + 1)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def append(self, rec: RoundRecord) -> None:
        """Append + (optionally) fsync one round record. MUST complete before
        the next kernel round consumes this round's messages (the batched
        persist-before-send contract, reference raft/doc.go:31-39)."""
        self.append_nosync(rec)
        self.sync()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    # -- read side ----------------------------------------------------------

    def _segments(self) -> List[str]:
        names = [n for n in os.listdir(self.dir)
                 if n.startswith("engine-") and n.endswith(".wal")]
        return sorted(names, key=_parse_seg)

    def replay(self, after_round: int = -1) -> Iterator[RoundRecord]:
        """Yield whole, checksummed round records with round_no > after_round.
        Stops cleanly at a torn tail. Also positions the writer: appends go
        to a FRESH segment after the last good record."""
        from etcd_tpu import native
        max_seq = -1
        for name in self._segments():
            seq, _ = _parse_seg(name)
            max_seq = max(max_seq, seq)
            path = os.path.join(self.dir, name)
            with open(path, "rb") as f:
                data = f.read()
            # Head CRC record seeds the chain (its payload IS the seed, and
            # it chains over itself like every record).
            if len(data) < _HDR.size:
                continue
            rtype, rcrc, ln = _HDR.unpack_from(data, 0)
            if (rtype != REC_CRC or _HDR.size + ln > len(data)):
                continue  # segment without a valid CRC head: corrupt
            payload = data[_HDR.size:_HDR.size + ln]
            (seed,) = struct.unpack("<I", payload)
            crc = zlib.crc32(payload, seed) & 0xFFFFFFFF
            if crc != rcrc:
                continue
            # Verified batch scan of the remainder (C when built).
            recs, crc, _ = native.scan_records(data[_HDR.size + ln:], crc)
            for rt, pl in recs:
                if rt == REC_ROUND:
                    rec = RoundRecord.decode(pl)
                    # Tail tracking covers EVERY whole record, filtered or
                    # not: a stream whose records all predate the filter
                    # is still complete through its tail.
                    self.last_round = max(self.last_round, rec.round_no)
                    if rec.round_no > after_round:
                        yield rec
            self._crc = crc
        self._seq = max_seq

    def cut_after(self, round_no: int) -> int:
        """Physically drop every whole record with round > round_no and
        position the appender at the cut. Returns the number of round
        records dropped.

        This is how the sharded writer reassembles a consistent boundary:
        a crash between the per-range streams' parallel fsyncs leaves
        some streams with whole, checksummed records whose batch never
        became durable on every sibling stream — those rounds were never
        acked (acks gate on the min-over-streams watermark), but they
        MUST NOT survive on disk, or the next crash-restart would replay
        them alongside reused round numbers carrying different content.
        Call after replay() (which positions _seq past every segment)."""
        dropped = 0
        cutting = False
        for name in self._segments():
            path = os.path.join(self.dir, name)
            with open(path, "rb") as f:
                data = f.read()
            # Walk frames exactly like replay: chain the rolling CRC and
            # stop at the first torn/corrupt frame.
            off, crc, cut_off, good_crc = 0, 0, None, None
            while off + _HDR.size <= len(data):
                rtype, rcrc, ln = _HDR.unpack_from(data, off)
                if off + _HDR.size + ln > len(data):
                    break
                payload = data[off + _HDR.size:off + _HDR.size + ln]
                if off == 0:
                    if rtype != REC_CRC:
                        break
                    (seed,) = struct.unpack("<I", payload)
                    crc = zlib.crc32(payload, seed) & 0xFFFFFFFF
                else:
                    crc = zlib.crc32(payload, crc) & 0xFFFFFFFF
                if crc != rcrc:
                    break
                if cut_off is None and rtype == REC_ROUND:
                    (r,) = struct.unpack_from("<I", payload, 0)
                    if r > round_no:
                        cut_off = off   # rounds are append-monotonic:
                        # everything from here on is beyond the boundary
                if cut_off is not None:
                    if rtype == REC_ROUND:
                        dropped += 1
                else:
                    good_crc = crc
                off += _HDR.size + ln
            if cutting:
                os.unlink(path)
                continue
            if cut_off is not None:
                if good_crc is None:
                    # Even the CRC head fell beyond the cut (impossible:
                    # the head is not a round record) — drop the segment.
                    os.unlink(path)
                else:
                    with open(path, "r+b") as f:
                        f.truncate(cut_off)
                        f.flush()
                        os.fsync(f.fileno())
                    self._crc = good_crc
                cutting = True
        if cutting:
            fsync_dir(self.dir)
            self.last_round = min(self.last_round, round_no)
        return dropped

    # -- checkpoints --------------------------------------------------------

    def save_checkpoint(self, round_no: int, state: dict) -> int:
        """Atomically persist a full engine checkpoint, then purge segments
        that predate it (every record they hold is round <= round_no).
        Returns the fallback round segment retention serves — the sharded
        writer purges its per-range streams against the same value."""
        path = os.path.join(self.dir, _ckpt_name(round_no))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(self.dir)
        # Keep the newest older checkpoint as a fallback; purge the rest.
        ckpts = sorted(n for n in os.listdir(self.dir)
                       if n.startswith("checkpoint-") and n.endswith(".json"))
        for name in ckpts[:-2]:
            os.unlink(os.path.join(self.dir, name))
        ckpts = ckpts[-2:]
        # Segment retention must serve the OLDEST retained checkpoint: if
        # the newest one is later unreadable, load_checkpoint falls back to
        # the previous one and needs every round after ITS round — purging
        # up to the newest would silently lose that span.
        fallback_round = int(ckpts[0][len("checkpoint-"):-len(".json")], 16)
        self.purge_segments(fallback_round)
        return fallback_round

    def purge_segments(self, fallback_round: int) -> None:
        """Drop segments every record of which is round <= fallback_round
        (covered by a retained checkpoint). A segment is droppable iff the
        NEXT segment's first round says so — the newest segment always
        stays (it is the append target)."""
        segs = self._segments()
        for i, name in enumerate(segs[:-1]):
            _, nxt_round = _parse_seg(segs[i + 1])
            if nxt_round <= fallback_round + 1:
                os.unlink(os.path.join(self.dir, name))

    def load_checkpoint(self) -> Tuple[int, Optional[dict]]:
        """Newest parseable checkpoint as (round_no, state); (-1, None) if
        none. A corrupt newest checkpoint falls back to the previous one
        (reference snap.Load newest-first with .broken quarantine,
        snap/snapshotter.go:84-143)."""
        ckpts = sorted((n for n in os.listdir(self.dir)
                        if n.startswith("checkpoint-")
                        and n.endswith(".json")), reverse=True)
        for name in ckpts:
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    state = json.load(f)
                return int(name[len("checkpoint-"):-len(".json")], 16), state
            except (ValueError, OSError):
                os.replace(path, path + ".broken")
        return -1, None


def load_terms(dirname: str, groups: int) -> np.ndarray:
    """Final per-group term recorded in one host's engine WAL dir
    (checkpoint base + round-record replay; terms are monotonic, so the
    final value is also the max). The degraded-restart supervisor takes the
    elementwise max of every SURVIVOR's result as the term floor for a host
    restarting with an empty data dir: any vote the dead host ever cast in
    a term above that floor can only have been a vote for itself (a
    candidate's own term is persisted wherever it campaigns), so granting
    fresh votes at floor+1 and up can never double-count toward a quorum
    the old vote already joined."""
    terms = np.zeros(groups, np.int32)
    wal = EngineWAL(dirname)
    try:
        ckpt_round, ckpt = wal.load_checkpoint()
        if ckpt is not None:
            terms = b64_np(ckpt["term"]).astype(np.int32).copy()
        # Streams: the root dir plus any per-range shard streams a
        # sharded writer (walwriter.WALWriter) left behind. Terms are
        # monotonic per group, so the elementwise max across streams IS
        # the final value — no merged round ordering needed, and records
        # beyond the crash boundary only ever raise the floor (safe:
        # this host really did persist that term).
        dirs = [dirname] + [os.path.join(dirname, n)
                            for n in sorted(os.listdir(dirname))
                            if n.startswith("wal-shard-")
                            and os.path.isdir(os.path.join(dirname, n))]
        for d in dirs:
            w = wal if d == dirname else EngineWAL(d)
            try:
                for rec in w.replay(after_round=ckpt_round):
                    for g, t in zip(rec.hs_g, rec.hs_term):
                        if g < groups:
                            terms[g] = max(terms[g], t)
            finally:
                if w is not wal:
                    w.close()
    finally:
        wal.close()
    return terms


def np_b64(a: np.ndarray) -> dict:
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": base64.b64encode(np.ascontiguousarray(a).tobytes()
                                     ).decode()}


def b64_np(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["data"]),
                         np.dtype(d["dtype"])).reshape(d["shape"]).copy()
