"""v3 ops through consensus: the serving half of the v3 MVCC preview.

The reference at this vintage ships the v3 RFC (Documentation/rfc/v3api.md,
v3api.proto: Range/Put/DeleteRange/Txn/Compact) and the embryonic storage/
package, but never wires them into etcdserver. This module closes that gap
the way etcd later did: every v3 mutation is a consensus entry, applied
deterministically to a per-member KVStore, with a **consistent index**
recorded transactionally alongside each apply so WAL replay after a crash
never double-applies (double-apply would fork the revision sequence between
members — the exact bug etcd v3's consistentIndex exists to prevent).

Snapshot catch-up: the member snapshot is a COMPOSITE of the v2 store and
the v3 image (server.py:66-75), so a follower that falls behind the
compacted log receives the v3 keyspace with the install and resumes from
the snapshot's consistent index (tests/test_v3_api.py
test_v3_survives_snapshot_catchup). The reference has no v3 serving at
all, so there is no behavior to diverge from.

Op / response shapes follow the RFC proto messages with the etcd JSON
gateway convention: `key`/`value`/`range_end` are base64 strings.
"""
from __future__ import annotations

import base64
import struct
from typing import Any, Dict, List, Optional

from etcd_tpu.storage import CompactedError, KVStore
from etcd_tpu.storage.kvstore import META_BUCKET

CONSISTENT_INDEX_KEY = b"consistentIndex"
LEASE_BUCKET = b"lease"

# Compare targets / results (v3api.proto Compare).
_TARGETS = ("VERSION", "CREATE", "MOD", "VALUE")
_RESULTS = ("EQUAL", "GREATER", "LESS")


def b64e(b: bytes) -> str:
    return base64.b64encode(b).decode()


def b64d(s: str) -> bytes:
    return base64.b64decode(s)


class V3Error(Exception):
    """Maps to an HTTP error payload at the gateway."""

    def __init__(self, code: int, msg: str) -> None:
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _need_b64(op: Dict[str, Any], field: str, required: bool) -> None:
    v = op.get(field)
    if v is None:
        if required:
            raise V3Error(3, f"missing required field {field!r}")
        return
    if not isinstance(v, str):
        raise V3Error(3, f"field {field!r} must be a base64 string")
    try:
        base64.b64decode(v, validate=True)
    except Exception:
        raise V3Error(3, f"field {field!r} is not valid base64")


def _need_int(op: Dict[str, Any], field: str) -> None:
    v = op.get(field)
    if v is None:
        return
    if isinstance(v, bool) or not isinstance(v, int):
        raise V3Error(3, f"field {field!r} must be an integer")


def _need_uint64(op: Dict[str, Any], field: str) -> None:
    """Bounded int: a replicated id outside uint64 would make the 8-byte
    persistence key raise struct.error during APPLY on every member — a
    poison-pill entry. Reject at validation (gateway AND apply)."""
    _need_int(op, field)
    v = op.get(field)
    if v is not None and not 0 <= v < 1 << 64:
        raise V3Error(3, f"field {field!r} must fit in uint64")


def validate_op(op: Dict[str, Any]) -> None:
    """Structural validation of a v3 op. Runs at the GATEWAY (so malformed
    requests are rejected before they enter the consensus log) and again at
    apply time (so a replicated op can never throw a decode error out of
    the apply thread — it is a pure function of the op dict, hence
    deterministic across members and replays)."""
    t = op.get("type")
    if t in ("put",):
        _need_b64(op, "key", required=True)
        _need_b64(op, "value", required=False)
    elif t in ("range", "deleterange"):
        _need_b64(op, "key", required=True)
        _need_b64(op, "range_end", required=False)
        _need_int(op, "limit")
        _need_int(op, "revision")
    elif t == "compact":
        _need_int(op, "revision")
    elif t == "lease_create":
        _need_int(op, "ttl")
        _need_uint64(op, "lease_id")
        if int(op.get("ttl", 0)) <= 0:
            raise V3Error(3, "lease ttl must be > 0")
    elif t == "lease_revoke":
        _need_uint64(op, "lease_id")
        _need_int(op, "seq")
    elif t == "lease_attach":
        _need_uint64(op, "lease_id")
        _need_b64(op, "key", required=True)
    elif t == "lease_keepalive":
        _need_uint64(op, "lease_id")
    elif t == "lease_txn":
        req = op.get("request")
        if not isinstance(req, dict):
            raise V3Error(3, "lease_txn needs a 'request' TxnRequest")
        validate_op({**req, "type": "txn"})
        for branch in ("success", "failure"):
            for a in _need_list(op, branch):
                if not isinstance(a, dict):
                    raise V3Error(3, "attach entries must be objects")
                _need_int(a, "lease_id")
                _need_b64(a, "key", required=True)
    elif t == "txn":
        for c in _need_list(op, "compare"):
            if not isinstance(c, dict):
                raise V3Error(3, "compare entries must be objects")
            if c.get("target", "VALUE") not in _TARGETS or \
                    c.get("result", "EQUAL") not in _RESULTS:
                raise V3Error(3, f"bad compare {c!r}")
            _need_b64(c, "key", required=True)
            _need_b64(c, "value", required=False)
            for f in ("version", "create_revision", "mod_revision"):
                _need_int(c, f)
        for branch in ("success", "failure"):
            for r in _need_list(op, branch):
                if not isinstance(r, dict) or len(r) != 1:
                    raise V3Error(
                        3, "txn requests must hold exactly one of "
                           "request_put/request_range/request_delete_range")
                kind, p = next(iter(r.items()))
                if kind == "request_put":
                    validate_op({**p, "type": "put"})
                elif kind == "request_range":
                    validate_op({**p, "type": "range"})
                elif kind == "request_delete_range":
                    validate_op({**p, "type": "deleterange"})
                else:
                    raise V3Error(3, f"unknown txn request {kind!r}")
    else:
        raise V3Error(3, f"unknown v3 op type {t!r}")


def _need_list(op: Dict[str, Any], field: str) -> List[Any]:
    v = op.get(field, [])
    if not isinstance(v, list):
        raise V3Error(3, f"field {field!r} must be a list")
    return v


class V3Watcher:
    """One watch stream over [key, range_end) from a start revision.
    Events arrive as (revision, [event_dict]) batches in revision order.
    A watcher whose consumer stalls past the queue bound is CANCELLED
    (etcd closes slow watchers rather than buffer without bound)."""

    QUEUE_BOUND = 1024

    def __init__(self, hub: "V3Applier", key: bytes,
                 end: Optional[bytes]) -> None:
        import queue as _q
        self._hub = hub
        self.key = key
        self.end = end
        self.q: "_q.Queue" = _q.Queue(maxsize=self.QUEUE_BOUND)
        self.cancelled = False

    def matches(self, k: bytes) -> bool:
        if self.end is None:
            return k == self.key
        if self.end == b"\x00":   # etcd whole-keyspace sentinel
            return k >= self.key
        return self.key <= k < self.end

    def next_batch(self, timeout: float = 0.5):
        import queue as _q
        try:
            return self.q.get(timeout=timeout)
        except _q.Empty:
            return None

    def remove(self) -> None:
        self._hub._remove_watcher(self)


class V3Applier:
    """Deterministic v3 op application over one member's KVStore."""

    def __init__(self, path: str) -> None:
        import threading
        self._path = path
        self.kv = KVStore(path)
        # Watch hub (the RFC's WatchRange): _published_rev is the fence
        # between historical replay (read from the backend) and live
        # publishes — a watcher registering mid-apply must not see the
        # in-flight revision twice or miss it.
        self._watch_lock = threading.Lock()
        self._watchers: List[V3Watcher] = []
        # Leases (RFC LeaseCreate/Revoke/Attach/KeepAlive): replicated
        # state carries NO clocks — only a renewal sequence number bumped
        # by create/keepalive. The leader alone maps seq transitions to
        # its own clock and proposes seq-FENCED revokes (the v2 SYNC
        # pattern, reference server.go:667-681): a keepalive that commits
        # after the expiry check bumps the seq, so the stale revoke
        # no-ops deterministically on every member. Cross-member clock
        # skew cannot enter the protocol; leadership changes re-base all
        # deadlines on the new leader's clock (leases extend, never
        # silently shorten — etcd's behavior).
        self._lease_lock = threading.Lock()
        self._load_from_backend()

    def _load_from_backend(self) -> None:
        """(Re)load backend-derived state: consistent index, publish fence,
        lease records. Called at boot and after a snapshot install."""
        import json as _json
        self.consistent_index = 0
        with self.kv.b.batch_tx as tx:
            _, vs = tx.unsafe_range(META_BUCKET, CONSISTENT_INDEX_KEY)
        if vs:
            self.consistent_index = struct.unpack(">Q", vs[0])[0]
        self._published_rev = self.kv.current_rev.main
        with self._lease_lock:
            self.leases = {}
            with self.kv.b.batch_tx as tx:
                tx.unsafe_create_bucket(LEASE_BUCKET)
                lkeys, lvals = tx.unsafe_range(LEASE_BUCKET, b"",
                                               b"\xff" * 9)
            for kb, vb in zip(lkeys, lvals):
                self.leases[struct.unpack(">Q", kb)[0]] = _json.loads(vb)

    # -- snapshot integration (closes the v2-snapshot/v3-keyspace hole) ----

    def snapshot_state(self) -> bytes:
        """A point-in-time image of the ENTIRE v3 backend (sqlite
        serialization after force-committing the pending batch) — embedded
        in the member snapshot so a follower that catches up via
        snapshot-install receives the v3 keyspace at exactly the snapshot
        index (consistent index included: it lives inside the image)."""
        self.kv.b.force_commit()
        with self.kv.b.batch_tx.lock:
            return self.kv.b._conn.serialize()

    def install_snapshot(self, blob: bytes) -> None:
        """Replace this member's whole v3 backend with the leader's image:
        close, atomically swap the db file (dropping sqlite sidecars),
        reopen, rebuild the in-memory index and meta. Open watchers keep
        their registration; their next events come from the installed
        state's revision sequence (mirroring the v2 store's watcher
        behavior across Recovery)."""
        import os
        self.kv.close()
        for suf in ("-wal", "-shm"):
            try:
                os.unlink(self._path + suf)
            except FileNotFoundError:
                pass
        tmp = self._path + ".install"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        self.kv = KVStore(self._path)
        self._load_from_backend()

    def close(self) -> None:
        self.kv.close()

    # -- watch (RFC WatchRange) --------------------------------------------

    def watch(self, key: bytes, end: Optional[bytes], start_rev: int = 0):
        """Register a watcher. Returns (watcher, replay): replay is None,
        or a lazy generator over the historical (start_rev-1, fence]
        events the CALLER must stream before consuming the queue.

        The fence (_published_rev at registration) splits history from
        live: live events land in the queue, history is read lazily from
        the backend in chunks OUTSIDE the lock — replaying under the lock
        (or into the bounded queue before a consumer exists) would block
        the apply thread's _publish, stalling consensus on this member."""
        w = V3Watcher(self, key, end)
        with self._watch_lock:
            if start_rev > 0 and start_rev <= self.kv.compact_main_rev:
                raise V3Error(11, f"required revision {start_rev} has "
                                  "been compacted")
            fence = self._published_rev
            self._watchers.append(w)
        if start_rev <= 0:
            return w, None

        def replay():
            for rev, evs in self._events_between(start_rev - 1, fence):
                # A compaction landing MID-replay scrubs rows ahead of the
                # cursor; silently yielding the gap-ridden remainder would
                # look like a complete history. Cancel like etcd does
                # (watch canceled with the compact revision).
                if start_rev <= self.kv.compact_main_rev:
                    raise V3Error(11, "watch replay overtaken by "
                                      "compaction; re-watch from a live "
                                      "revision")
                mine = [ev for ev in evs
                        if w.matches(b64d(ev["kv"]["key"]))]
                if mine:
                    yield rev, mine
        return w, replay()

    def _remove_watcher(self, w: V3Watcher) -> None:
        with self._watch_lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def _events_between(self, lo: int, hi: int, chunk: int = 4096):
        """Decoded events grouped by main revision in (lo, hi] — read
        from the backend's revision-ordered key bucket (the WAL of the
        MVCC store) in `chunk`-row pages, so a long historical span never
        loads into memory at once or holds the batch-tx lock for its
        whole length. Yields (rev, [event_dict]) in order."""
        if hi <= lo:
            return
        from etcd_tpu.storage.kvstore import DELETE as EV_DELETE
        from etcd_tpu.storage.kvstore import KEY_BUCKET, _decode_event
        from etcd_tpu.storage.revision import (Revision, bytes_to_rev,
                                               rev_to_bytes)
        cursor = rev_to_bytes(Revision(lo + 1, 0))
        end = rev_to_bytes(Revision(hi + 1, 0))
        cur_rev, batch = None, []
        while True:
            with self.kv.b.batch_tx as tx:
                keys, vals = tx.unsafe_range(KEY_BUCKET, cursor, end,
                                             limit=chunk)
            for kb, vb in zip(keys, vals):
                if len(kb) != 17:
                    continue
                rev = bytes_to_rev(kb)
                etype, kv = _decode_event(vb)
                ev = {"type": "DELETE" if etype == EV_DELETE else "PUT",
                      "kv": self._kv_json(kv)}
                if rev.main != cur_rev:
                    if batch:
                        yield cur_rev, batch
                    cur_rev, batch = rev.main, []
                batch.append(ev)
            if len(keys) < chunk:
                break
            last = bytes_to_rev(keys[-1])
            cursor = rev_to_bytes(Revision(last.main, last.sub + 1))
        if batch:
            yield cur_rev, batch

    def _publish(self, lo: int, hi: int) -> None:
        """Fan out the events a just-finished apply produced in (lo, hi]."""
        import queue as _q
        with self._watch_lock:
            if self._watchers:   # no watchers: skip the backend re-read
                dead = []
                for rev, evs in self._events_between(lo, hi):
                    for w in self._watchers:
                        mine = [e for e in evs
                                if w.matches(b64d(e["kv"]["key"]))]
                        if mine:
                            try:
                                w.q.put_nowait((rev, mine))
                            except _q.Full:
                                # Consumer stalled past the bound: cancel
                                # the watcher instead of buffering forever
                                # (its stream loop sees `cancelled`).
                                w.cancelled = True
                                dead.append(w)
                for w in dead:
                    if w in self._watchers:
                        self._watchers.remove(w)
            self._published_rev = max(self._published_rev, hi)

    # -- reads (serializable; linearizable reads ride apply()) --------------

    def range(self, op: Dict[str, Any]) -> Dict[str, Any]:
        key = b64d(op.get("key", ""))
        end = b64d(op["range_end"]) if op.get("range_end") else None
        limit = int(op.get("limit", 0))
        rev = int(op.get("revision", 0))
        try:
            kvs, cur = self.kv.range(key, end, limit=limit, range_rev=rev)
            # `count` is the TOTAL matching the range (ignoring limit) and
            # `more` only true when keys were actually truncated (etcd
            # gateway semantics). The total comes from the in-memory index
            # (no backend value reads), and only when the limit bound.
            total = (self.kv.count(key, end, range_rev=cur)
                     if limit and len(kvs) == limit else len(kvs))
        except CompactedError as e:
            raise V3Error(11, f"required revision {e.args[0]} has been "
                              "compacted")
        return {
            "header": {"revision": cur},
            "kvs": [self._kv_json(kv) for kv in kvs],
            "count": total,
            "more": total > len(kvs),
        }

    @staticmethod
    def _kv_json(kv) -> Dict[str, Any]:
        return {"key": b64e(kv.key), "value": b64e(kv.value),
                "create_revision": kv.create_rev,
                "mod_revision": kv.mod_rev, "version": kv.version}

    # -- the replicated apply ----------------------------------------------

    def apply(self, op: Dict[str, Any], index: int) -> Dict[str, Any]:
        """Apply one committed v3 op at raft entry `index`. Idempotent:
        entries at or below the consistent index were already applied in a
        previous life and are skipped (reference-future consistentIndex
        semantics).

        The whole apply runs inside batch_tx.hold(): the mutation and the
        consistent-index record land in ONE sqlite commit, so a crash can
        never persist one without the other (a split would double-apply on
        replay and fork the revision sequence between members)."""
        if index <= self.consistent_index:
            return {"skipped": True, "header":
                    {"revision": self.kv.current_rev.main}}
        validate_op(op)       # deterministic; malformed ops error, don't
        #                       kill the apply thread
        if op.get("type") == "range":
            # Read-only: replaying a range is harmless, so it needs no
            # consistent-index record — recording one would turn every
            # linearizable read into a durable write on every member.
            return self.range(op)
        rev0 = self.kv.current_rev.main
        with self.kv.atomic() as tx:
            try:
                result = self._dispatch(op)
            except V3Error:
                # Deterministic outcome (a pure function of op + store
                # state): every member and every replay resolves it
                # identically, so the index advances. No mutation has
                # executed when a V3Error is raised (all checks precede
                # writes; txn requests are pre-validated).
                self._record_index(tx, index)
                raise
            except Exception:
                # Environmental (disk I/O, corruption): discard the whole
                # un-committed batch so the timer can't durably commit a
                # half-applied op after the apply thread dies, skip the
                # index record, and let the caller crash the member —
                # restart replays the entry from the last commit boundary.
                self.kv.b.rollback()
                raise
            if self.kv.current_rev.main > rev0:
                self._detach_deleted(rev0, self.kv.current_rev.main)
            self._record_index(tx, index)
        rev1 = self.kv.current_rev.main
        if rev1 > rev0:
            self._publish(rev0, rev1)
        return result

    def _record_index(self, tx, index: int) -> None:
        self.consistent_index = index
        tx.unsafe_put(META_BUCKET, CONSISTENT_INDEX_KEY,
                      struct.pack(">Q", index))

    def _dispatch(self, op: Dict[str, Any]) -> Dict[str, Any]:
        t = op.get("type")
        if t == "put":
            rev = self.kv.put(b64d(op["key"]), b64d(op.get("value", "")))
            return {"header": {"revision": rev}}
        if t == "deleterange":
            end = b64d(op["range_end"]) if op.get("range_end") else None
            n, rev = self.kv.delete_range(b64d(op["key"]), end)
            return {"header": {"revision": rev}, "deleted": n}
        if t == "compact":
            rev = int(op.get("revision", 0))
            try:
                self.kv.compact(rev)
            except CompactedError:
                raise V3Error(11, f"revision {rev} has been compacted")
            except ValueError as e:
                raise V3Error(3, str(e))
            return {"header": {"revision": self.kv.current_rev.main}}
        if t == "txn":
            return self._apply_txn(op)
        if t == "lease_txn":
            return self._apply_lease_txn(op)
        if t.startswith("lease_"):
            return self._apply_lease(t, op)
        raise V3Error(3, f"unknown v3 op type {t!r}")

    def _apply_lease_txn(self, op: Dict[str, Any]) -> Dict[str, Any]:
        """RFC LeaseTnx: a Tnx plus success/failure LeaseAttachRequest
        lists; the winning branch's attaches execute with the txn. Every
        referenced lease is checked BEFORE the txn runs so a bad attach
        cannot abort a txn that already mutated (all-or-nothing)."""
        with self._lease_lock:
            for branch in ("success", "failure"):
                for a in op.get(branch, []):
                    lid = int(a.get("lease_id", 0))
                    if lid not in self.leases:
                        raise V3Error(5, f"lease {lid:x} not found")
        txn_resp = self._apply_txn(op["request"])
        attaches = op.get("success" if txn_resp["succeeded"] else "failure",
                          [])
        attach_responses = []
        for a in attaches:
            attach_responses.append(
                self._apply_lease("lease_attach",
                                  {"lease_id": int(a["lease_id"]),
                                   "key": a["key"]}))
        return {"header": self._hdr(), "response": txn_resp,
                "attach_responses": attach_responses}

    # -- leases -------------------------------------------------------------

    def _persist_lease(self, lid: int, rec: Optional[dict]) -> None:
        import json as _json
        with self.kv.b.batch_tx as tx:
            if rec is None:
                tx.unsafe_delete(LEASE_BUCKET, struct.pack(">Q", lid))
            else:
                tx.unsafe_put(LEASE_BUCKET, struct.pack(">Q", lid),
                              _json.dumps(rec).encode())

    def _apply_lease(self, t: str, op: Dict[str, Any]) -> Dict[str, Any]:
        lid = int(op.get("lease_id", 0))
        with self._lease_lock:
            if t == "lease_create":
                if lid in self.leases:
                    raise V3Error(3, f"lease {lid:x} already exists")
                rec = {"ttl": int(op["ttl"]), "seq": 0, "keys": []}
                self.leases[lid] = rec
                self._persist_lease(lid, rec)
                return {"header": self._hdr(), "lease_id": lid,
                        "ttl": rec["ttl"], "seq": 0}
            rec = self.leases.get(lid)
            if rec is None:
                raise V3Error(5, f"lease {lid:x} not found")
            if t == "lease_keepalive":
                rec["seq"] += 1
                self._persist_lease(lid, rec)
                return {"header": self._hdr(), "lease_id": lid,
                        "ttl": rec["ttl"], "seq": rec["seq"]}
            if t == "lease_attach":
                # Canonicalize at the boundary: b64decode(validate=True)
                # accepts non-canonical encodings (nonzero trailing bits,
                # e.g. 'YR==' == b'a'), and _detach_deleted compares
                # against canonically re-encoded event keys — a verbatim
                # non-canonical attach would never detach on delete, and a
                # later revoke would delete an unrelated re-created key.
                k64 = b64e(b64d(op["key"]))
                if k64 not in rec["keys"]:
                    rec["keys"].append(k64)
                self._persist_lease(lid, rec)
                return {"header": self._hdr(), "lease_id": lid}
            # lease_revoke. The seq fence: an expiry-driven revoke carries
            # the seq the leader observed; a keepalive that committed in
            # between bumped it, so the stale revoke must NOT fire (the
            # client already got a successful renewal ack).
            if "seq" in op and int(op["seq"]) != rec["seq"]:
                return {"header": self._hdr(), "lease_id": lid,
                        "renewed": True}
            # Delete every attached key at ONE revision, then drop the
            # lease (RFC: "All keys attached to the lease will be expired
            # and deleted").
            tid = self.kv.txn_begin()
            try:
                for k64 in rec["keys"]:
                    self.kv.txn_delete_range(tid, b64d(k64))
            finally:
                self.kv.txn_end(tid)
            del self.leases[lid]
            self._persist_lease(lid, None)
            return {"header": self._hdr(), "lease_id": lid}

    def _hdr(self) -> Dict[str, int]:
        return {"revision": self.kv.current_rev.main}

    def lease_seqs(self) -> Dict[int, int]:
        """Snapshot of (lease_id -> renewal seq) for the leader's expiry
        monitor."""
        with self._lease_lock:
            return {lid: rec["seq"] for lid, rec in self.leases.items()}

    def lease_ttl(self, lid: int) -> Optional[int]:
        with self._lease_lock:
            rec = self.leases.get(lid)
            return None if rec is None else rec["ttl"]

    def _detach_deleted(self, lo: int, hi: int) -> None:
        """Detach keys deleted in (lo, hi] from every lease: a later
        revoke must not delete an unrelated key re-created under the same
        name (etcd detaches on delete for the same reason). Runs inside
        the apply's atomic block so the lease-record updates land in the
        same commit."""
        with self._lease_lock:
            if not any(rec["keys"] for rec in self.leases.values()):
                return
            deleted = set()
            for _, evs in self._events_between(lo, hi):
                for ev in evs:
                    if ev["type"] == "DELETE":
                        deleted.add(ev["kv"]["key"])
            if not deleted:
                return
            for lid, rec in self.leases.items():
                kept = [k for k in rec["keys"] if k not in deleted]
                if len(kept) != len(rec["keys"]):
                    rec["keys"] = kept
                    self._persist_lease(lid, rec)

    # -- txn ----------------------------------------------------------------

    def _apply_txn(self, op: Dict[str, Any]) -> Dict[str, Any]:
        succeeded = all(self._check(c) for c in op.get("compare", []))
        reqs: List[Dict[str, Any]] = op.get(
            "success" if succeeded else "failure", [])
        # Atomicity: errors must not abort a txn after it mutated (etcd
        # txns are all-or-nothing). validate_op covers structure pre-txn;
        # EXPLICIT compacted range revisions are checked here because they
        # can fail even after a mutation ran. The remaining case — a
        # head-revision (rr==0) range on a head-compacted store — is safe
        # to catch mid-loop: it can only fire while sub==0, i.e. before
        # ANY mutation executed (a mutation bumps sub, which pushes the
        # resolved read revision past the compaction boundary), so
        # aborting there is atomic and deterministic.
        for r in reqs:
            if "request_range" in r:
                rr = int(r["request_range"].get("revision", 0))
                if 0 < rr <= self.kv.compact_main_rev:
                    raise V3Error(11, f"required revision {rr} has been "
                                      "compacted")
        tid = self.kv.txn_begin()
        responses = []
        try:
            for r in reqs:
                if "request_put" in r:
                    p = r["request_put"]
                    rev = self.kv.txn_put(tid, b64d(p["key"]),
                                          b64d(p.get("value", "")))
                    responses.append(
                        {"response_put": {"header": {"revision": rev}}})
                elif "request_delete_range" in r:
                    p = r["request_delete_range"]
                    end = (b64d(p["range_end"])
                           if p.get("range_end") else None)
                    n, rev = self.kv.txn_delete_range(tid, b64d(p["key"]),
                                                      end)
                    responses.append({"response_delete_range":
                                      {"header": {"revision": rev},
                                       "deleted": n}})
                elif "request_range" in r:
                    p = r["request_range"]
                    end = (b64d(p["range_end"])
                           if p.get("range_end") else None)
                    lim = int(p.get("limit", 0))
                    try:
                        kvs, cur = self.kv.txn_range(
                            tid, b64d(p["key"]), end, limit=lim,
                            range_rev=int(p.get("revision", 0)))
                        total = (self.kv.count(b64d(p["key"]), end,
                                               range_rev=cur)
                                 if lim and len(kvs) == lim else len(kvs))
                    except CompactedError:
                        # Head-compacted store: only reachable with sub==0
                        # (nothing mutated yet — see precheck comment), so
                        # this abort is atomic.
                        raise V3Error(11, "required revision has been "
                                          "compacted")
                    responses.append({"response_range": {
                        "header": {"revision": cur},
                        "kvs": [self._kv_json(kv) for kv in kvs],
                        "count": total,
                        "more": total > len(kvs)}})
                else:
                    raise V3Error(3, f"unknown txn request {r!r}")
        finally:
            self.kv.txn_end(tid)
        return {"header": {"revision": self.kv.current_rev.main},
                "succeeded": succeeded, "responses": responses}

    def _check(self, c: Dict[str, Any]) -> bool:
        target = c.get("target", "VALUE")
        result = c.get("result", "EQUAL")
        if target not in _TARGETS or result not in _RESULTS:
            raise V3Error(3, f"bad compare {c!r}")
        try:
            kvs, _ = self.kv.range(b64d(c["key"]))
        except CompactedError:
            # Head-compacted store: the compare itself reads at a
            # compacted revision. Deterministic -> a V3Error, never an
            # apply-thread fatal.
            raise V3Error(11, "required revision has been compacted")
        if target == "VALUE":
            have: Any = kvs[0].value if kvs else b""
            want: Any = b64d(c.get("value", ""))
        else:
            have = {"VERSION": kvs[0].version if kvs else 0,
                    "CREATE": kvs[0].create_rev if kvs else 0,
                    "MOD": kvs[0].mod_rev if kvs else 0}[target]
            want = int(c.get({"VERSION": "version",
                              "CREATE": "create_revision",
                              "MOD": "mod_revision"}[target], 0))
        if result == "EQUAL":
            return have == want
        if result == "GREATER":
            return have > want
        return have < want
