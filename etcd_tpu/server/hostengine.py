"""HostEngine: the MULTI-HOST MultiEngine — N processes, each owning one
peer-slot column of every Raft group, stepping ONE global SPMD kernel.

Deployment shape (the reference's cluster model re-expressed for a device
mesh): host h contributes one device to a ("groups", "peers") mesh and owns
peer slot h of every group. The consensus hot path — votes, appends,
acks, commit metadata — is the kernel's routed mailbox, which XLA lowers
to an all_to_all across the peers axis: ICI within a slice, DCN between
hosts (SURVEY §2.4). What the reference moves over rafthttp that is NOT
index metadata rides the frame transport (parallel/frames.py): forwarded
client proposals, entry payload fan-out, and payload catch-up pulls.

Durability model (reference per-member WAL, etcdserver/raft.go:112-172):
every host journals ITS OWN slot column's per-round deltas plus every
entry payload it admits or receives to its own EngineWAL, and fsyncs
BEFORE dispatching the next round — the persist-before-send contract
(raft/doc.go:31-39) holds across hosts because round k's outbox is only
delivered by round k+1's collective, which this host cannot enter before
its fsync returns. (The single-host engine's fsync/step overlap is NOT
legal here: peers are separate failure domains.)

Every host applies every group's store (exactly a reference member's state
machine) and acks a client request only after its OWN fsync + apply — so
an acked write is always reconstructable from the acking host's WAL alone,
and Raft's quorum machinery guarantees the cluster converges to include it.

Crash model: a host crash stalls the synchronous collective, so the JOB
restarts (all hosts), each replaying its own WAL — zero acked writes lost.
Availability during a single-host outage is traded for the dense SPMD data
plane; divergence from the reference's per-member liveness is documented
in docs/divergences.md. The restart does NOT require the dead host's
disk: a rank respawned with an EMPTY data dir (supervisor-written term
floor fencing its lost votes — see _load_term_floor) rejoins as an empty
follower and catches up through the cross-host snapshot-install path
(_send_snapshots/_install_snaps, the rafthttp MsgSnap side-channel
analogue, reference peer.go:250-252 + raft.go:246-260/671-713), so a
single host loss — machine AND data — is survivable unattended.

Proposal flow: a client hits ANY host; if the leader slot of the target
group is local it stages directly (per-slot proposal counts are SHARDED
kernel inputs — no cross-host agreement needed, ops/kernel.py
step_routed_slots); otherwise the request forwards to the leader's host
over a PROPOSE frame (nonblocking, bounded, drop = client timeout —
reference peer.go:156-165 semantics).
"""
from __future__ import annotations

import json
import logging
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from etcd_tpu import errors
from etcd_tpu.parallel.frames import FrameTransport
from etcd_tpu.server.engine import (P_MULTI, P_REQ, _pack_entry,
                                    _unpack_multi)
from etcd_tpu.server.enginewal import EngineWAL, RoundRecord, b64_np, np_b64
from etcd_tpu.server.request import (METHOD_DELETE, METHOD_GET, METHOD_POST,
                                     METHOD_PUT, METHOD_QGET, METHOD_SYNC,
                                     Request)
from etcd_tpu.server import obs as obs_mod
from etcd_tpu.store import new_store
from etcd_tpu.store.event import LazyWriteEvent
from etcd_tpu.utils import idutil, metrics
from etcd_tpu.utils.wait import Wait

log = logging.getLogger("etcd_tpu.hostengine")

_LEADER = 2
_MAX_HOPS = 3


@dataclass
class HostEngineConfig:
    groups: int
    peers: int                 # == number of hosts (one slot column each)
    data_dir: str              # THIS host's WAL/checkpoint dir
    host_id: int
    frame_listen: Tuple[str, int]
    frame_peers: Dict[int, Tuple[str, int]] = field(default_factory=dict)
    window: int = 32
    max_ents: int = 8
    election_tick: int = 10
    heartbeat_tick: int = 3
    fsync: bool = True
    checkpoint_rounds: int = 4096
    request_timeout: float = 10.0
    batch_max: int = 4096
    batch_bytes: int = 1 << 20   # reference maxSizePerMsg, raft.go:48
    round_interval: float = 0.0
    stagger: bool = True
    pull_interval: float = 0.25    # payload catch-up request pacing
    # Message hops per collective invocation. MUST remain 1 in
    # multi-host deployments: with hops>1 the leader would quorum-commit
    # on follower acks produced before those hosts journaled the entries
    # (kernel.step_routed_slots_auto's durability constraint) — an
    # acked write could then be lost to a follower-host crash. The
    # latency win here comes from the quiescent fast path alone.
    hops: int = 1
    # Fault injection (tests/chaos, reference rafthttp.Pausable analogue):
    # drop this percentage of outgoing per-peer PAYLOAD fan-out frames,
    # forcing the receiving hosts onto the PULL catch-up path. Seeded for
    # reproducible soaks.
    drop_pay_pct: float = 0.0
    fault_seed: int = 0
    # Cross-host snapshot install (the rafthttp snapshot side-channel,
    # reference peer.go:250-252): per-(group, target) resend holdoff and a
    # per-round cap on shipped images (bounds frame bytes and round time
    # during a mass catch-up, e.g. a host restarting with an empty disk).
    snap_interval: float = 1.0
    snaps_per_round: int = 128
    # Consensus data plane:
    #   "collective" — the kernel state shards over a global N-host mesh
    #     and votes/appends/acks ride an XLA all_to_all (the dense SPMD
    #     plane). One dead host stalls EVERY group until the supervisor
    #     restarts the whole job (~30 s measured): availability is traded
    #     for zero-serialization consensus.
    #   "frames" — every host runs the FULL (G, P) kernel on its own
    #     device, authoritative for its own peer-slot column only, and
    #     the per-round mailbox metadata rides the frame transport like
    #     payloads already do (sparse-encoded per-peer slices). No
    #     collective, no global process group: hosts fail INDEPENDENTLY
    #     exactly like reference members (rafthttp peers, peer.go:87-190)
    #     — a dead host's frames just stop, its groups' leaders re-elect
    #     among the survivors within the election timeout, and quorum
    #     n/2+1 keeps committing throughout (raft.go:323-332 semantics).
    #     The dead host rejoins by simply restarting: probes repair its
    #     lag via appends, or the snapshot-install path ships images.
    #     Cost: each host steps P columns but exports only its own (the
    #     P-1 ghost columns evolve as message-starved candidates and are
    #     never read), and metadata latency is frame-paced rather than
    #     ICI-paced.
    data_plane: str = "collective"


class HostEngine:
    """One host's share of the multi-host MultiEngine."""

    def __init__(self, cfg: HostEngineConfig) -> None:
        import jax
        import jax.numpy as jnp
        import functools
        from etcd_tpu.ops import kernel
        from etcd_tpu.ops.state import KernelConfig, init_state
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from etcd_tpu.parallel.mesh import (mailbox_sharding, shard_state,
                                            state_sharding)

        self._jax, self._jnp = jax, jnp
        self.cfg = cfg
        G, Pn, W = cfg.groups, cfg.peers, cfg.window
        self.kcfg = KernelConfig(
            groups=G, peers=Pn, window=W, max_ents=cfg.max_ents,
            election_tick=cfg.election_tick,
            heartbeat_tick=cfg.heartbeat_tick)

        self._frames_plane = cfg.data_plane == "frames"
        self.my_slot = cfg.host_id
        if self._frames_plane:
            # Local full-(G, P) kernel on this host's own device: no
            # global mesh, no process group — the mailbox rides frames
            # (see HostEngineConfig.data_plane). Several frames-plane
            # engines can even share one process/device (tests do).
            if cfg.hops != 1:
                raise ValueError("frames data plane requires hops=1 "
                                 "(persist-before-send across hosts)")
            self.mesh = None
            self._st_sh = self._mb_sh = self._cnt_sh = None
            self._step_fn = jax.jit(
                functools.partial(kernel.step_routed_slots_auto.__wrapped__,
                                  self.kcfg, hops=1),
                donate_argnums=kernel.donate_safe((0, 1)))
            # Per-sender queues of sparse mailbox frames (bounded: a
            # slower host drops OLDEST — raft retransmits; reference
            # drop-on-full, peer.go:156-165) + our own self-loop slice.
            self._meta_rx: Dict[int, deque] = {}
            self._self_loop: Optional[np.ndarray] = None
        else:
            devs = sorted(jax.devices(), key=lambda d: d.process_index)
            if len(devs) != Pn:
                raise ValueError(
                    f"multi-host engine needs one device per peer slot: "
                    f"{len(devs)} devices for peers={Pn}")
            assert len(jax.local_devices()) == 1, \
                "one device per host expected"
            assert devs[self.my_slot].process_index == \
                jax.process_index(), (
                "host_id must equal jax process index (device ordering)")
            self.mesh = Mesh(np.array(devs).reshape(1, Pn),
                             axis_names=("groups", "peers"))
            self._st_sh = state_sharding(self.mesh)
            self._mb_sh = mailbox_sharding(self.mesh)
            self._cnt_sh = NamedSharding(self.mesh, P("groups", "peers"))
            self._step_fn = jax.jit(
                functools.partial(kernel.step_routed_slots_auto.__wrapped__,
                                  self.kcfg, hops=cfg.hops),
                donate_argnums=kernel.donate_safe((0, 1)),
                out_shardings=(self._st_sh, self._mb_sh))

        self._check_geometry()
        self.wal = EngineWAL(cfg.data_dir, fsync=cfg.fsync)
        self.wait = Wait()
        self.reqid = idutil.Generator(cfg.host_id + 1)
        self._pending: List[deque] = [deque() for _ in range(G)]
        self._dirty: set = set()
        # The read plane (collective plane only; see _quorum_read):
        # parked quorum reads awaiting a leadership confirmation, and
        # ripe ones awaiting the apply cursor. Both under self._lock.
        self._reads: List[deque] = [deque() for _ in range(G)]
        self._read_dirty: set = set()
        self._reads_waiting = 0
        self._ripe: List[deque] = [deque() for _ in range(G)]
        self._ripe_dirty: set = set()
        self._ripe_waiting = 0
        self._staged: Dict[int, List[List[Tuple[int, bytes]]]] = {}
        self._stores: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.round_no = 0
        self.round_ms_ewma = 0.0
        self.acked_requests = 0
        self.failed: Optional[Exception] = None
        self._recent_recs: deque = deque(maxlen=8)

        # Local column mirrors (this host's slot of every group).
        self.l_term = np.zeros(G, np.int32)
        self.l_vote = np.zeros(G, np.int32)
        self.l_commit = np.zeros(G, np.int32)
        self.l_state = np.zeros(G, np.int32)
        self.l_last = np.zeros(G, np.int32)
        self.l_lead = np.zeros(G, np.int32)     # leader slot+1 as we know it
        self.l_ring = np.zeros((G, W), np.int32)
        self.applied = np.zeros(G, np.int64)
        self.payloads: Dict[Tuple[int, int, int], bytes] = {}

        # Inbound frames (filled by transport threads, drained per round).
        self._rx: deque = deque()
        # rid -> forward hop count for requests that arrived via PROPOSE
        # frames (loop protection when leadership views are crossed).
        self._hops: Dict[int, int] = {}
        self._fresh_payloads: List[Tuple[int, int, int, bytes]] = []
        self._missing: Dict[Tuple[int, int, int], float] = {}
        self._last_pull = 0.0
        self.unreachable: Dict[int, int] = {}
        import random as _random
        self._fault_rng = (_random.Random(cfg.fault_seed)
                           if cfg.drop_pay_pct > 0 else None)
        self.pay_frames_dropped = 0
        self.pulls_sent = 0
        self.payloads_pulled = 0
        # Cross-host snapshot install state: staged inbound installs
        # (g -> newest (a, term, lead, ring_row, store_blob)), records to
        # journal this round, per-(g, target) send holdoff, counters.
        self._pending_snaps: Dict[int, Tuple[int, int, int, np.ndarray,
                                             bytes]] = {}
        self._snap_recs: List[Tuple[int, int, bytes]] = []
        self._snap_sent: Dict[Tuple[int, int], float] = {}
        self._hist: Dict[Tuple[int, int], int] = {}
        self.snaps_sent = 0
        self.snaps_installed = 0

        self.frames = FrameTransport(
            cfg.host_id, cfg.frame_listen, cfg.frame_peers,
            on_frame=self._on_frame,
            report_unreachable=self._report_unreachable)

        ckpt_round, ckpt = self.wal.load_checkpoint()
        recs = list(self.wal.replay(after_round=ckpt_round))
        base = init_state(self.kcfg, stagger=cfg.stagger)
        floor = self._load_term_floor() if ckpt is None else None
        if ckpt is not None or recs or floor is not None:
            self._restore(base, ckpt_round, ckpt, recs, floor)
        elif self._frames_plane:
            self.st = base
        else:
            self.st = shard_state(base, self.mesh)
        inbox0 = jnp.zeros((G, Pn, Pn, self.kcfg.fields), jnp.int32)
        self.inbox = (inbox0 if self._frames_plane
                      else jax.device_put(inbox0, self._mb_sh))

    # ------------------------------------------------------------------
    # boot / restore
    # ------------------------------------------------------------------

    def _check_geometry(self) -> None:
        import os
        from etcd_tpu.utils.fileutil import touch_dir_all
        touch_dir_all(self.cfg.data_dir)
        path = os.path.join(self.cfg.data_dir, "geometry.json")
        want = {"groups": self.cfg.groups, "peers": self.cfg.peers,
                "window": self.cfg.window, "host": self.cfg.host_id}
        if os.path.exists(path):
            with open(path) as f:
                have = json.load(f)
            if have != want:
                raise ValueError(
                    f"host-engine data dir {self.cfg.data_dir} was "
                    f"initialized with {have}, refusing {want}")
        else:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(want, f)
            os.replace(tmp, path)

    def _global_col(self, name: str, base_field, local_col: np.ndarray):
        """Assemble a state array where THIS host's column holds restored
        local data; every host calls this for its own column. (Frames
        plane: the other columns keep base values — they are local
        ghosts, never exported.)"""
        jax = self._jax
        base_np = np.asarray(base_field)
        if self._frames_plane:
            blk = base_np.copy()
            blk[:, self.my_slot] = local_col
            return self._jnp.asarray(blk)
        sh = getattr(self._st_sh, name)

        def cb(index):
            blk = base_np[index].copy()
            blk[:, 0] = local_col
            return blk

        return jax.make_array_from_callback(base_np.shape, sh, cb)

    def _load_term_floor(self) -> Optional[np.ndarray]:
        """Per-group term floor written by the degraded-restart supervisor
        into an EMPTY data dir (this host's disk was lost with the host):
        the elementwise max of every survivor's recorded terms, PLUS ONE.
        Booting at the floor with a clear vote fences the lost vote
        records: the earliest term this host can now grant at is the
        floor, and no pre-crash election can have completed at any term
        >= floor — completion needs a durable grant on a survivor (round
        records fsync term+log diffs atomically), and all survivors'
        durable terms are <= floor-1. The +1 (vs the elementwise max)
        closes the boundary race where one survivor durably recorded an
        election won at exactly max(survivor terms) with the dead host's
        lost grant while a lagging survivor still reads one term lower
        and would re-campaign at that same term. Ignored once a
        checkpoint exists (the checkpoint carries full term state
        recorded while the floor was in effect)."""
        import os
        path = os.path.join(self.cfg.data_dir, "term_floor.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            floor = np.asarray(json.load(f)["term"], np.int32)
        if floor.shape != (self.cfg.groups,):
            raise ValueError(
                f"term_floor.json has {floor.shape[0]} groups, "
                f"engine has {self.cfg.groups}")
        log.info("host %d: booting with a term floor (max %d) from the "
                 "degraded-restart supervisor", self.my_slot,
                 int(floor.max(initial=0)))
        return floor

    def _restore(self, base, ckpt_round: int, ckpt: Optional[dict],
                 recs: List[RoundRecord],
                 floor: Optional[np.ndarray] = None) -> None:
        """Rebuild THIS host's column from its checkpoint + WAL replay;
        every slot restarts as a follower (reference RestartNode)."""
        from etcd_tpu.parallel.mesh import shard_state
        G, W = self.cfg.groups, self.cfg.window

        if floor is not None:
            # Base for diff replay: WAL records after a floor boot were
            # diffs against floor-initialized mirrors.
            self.l_term = floor.copy()
        if ckpt is not None:
            self.l_term = b64_np(ckpt["term"]).astype(np.int32)
            self.l_vote = b64_np(ckpt["vote"]).astype(np.int32)
            self.l_commit = b64_np(ckpt["commit"]).astype(np.int32)
            self.l_last = b64_np(ckpt["last"]).astype(np.int32)
            self.l_ring = b64_np(ckpt["ring"]).astype(np.int32)
            self.applied = b64_np(ckpt["applied"]).astype(np.int64)
            for g_s, blob in ckpt["stores"].items():
                st = new_store(namespaces=("/0", "/1"))
                st.recovery(blob.encode())
                self._stores[int(g_s)] = st
            import base64 as _b64
            for g, i, t, b64p in ckpt["payloads"]:
                self.payloads[(g, i, t)] = _b64.b64decode(b64p)

        # Our column's log-term history (ring window is finite; the
        # committed-but-unapplied span can reach further back).
        slot_log: Dict[int, Dict[int, int]] = {}

        def _log_set(g, i, t):
            slot_log.setdefault(int(g), {})[int(i)] = int(t)

        if ckpt is not None:
            for g in range(G):
                lastv = int(self.l_last[g])
                for w in range(W):
                    i = lastv - ((lastv - w) % W)
                    if i >= 1:
                        _log_set(g, i, self.l_ring[g, w])

        last_round = ckpt_round
        for rec in recs:
            last_round = max(last_round, rec.round_no)
            # Snapshot installs first: the same record's hs/ring/last diffs
            # were computed AFTER the install surgery and land on top.
            for g, a, blob in rec.snaps:
                s = new_store(namespaces=("/0", "/1"))
                s.recovery(blob)
                self._stores[int(g)] = s
                self.applied[int(g)] = a
            for g, t_, v_, c_ in zip(rec.hs_g, rec.hs_term, rec.hs_vote,
                                     rec.hs_commit):
                self.l_term[g] = t_
                self.l_vote[g] = v_
                self.l_commit[g] = c_
            for g, i, t in zip(rec.ring_g, rec.ring_i, rec.ring_t):
                self.l_ring[g, int(i) % W] = t
                _log_set(g, i, t)
            for g, new in zip(rec.last_g, rec.last_v):
                prev = int(self.l_last[g])
                self.l_last[g] = new
                for i in range(max(prev + 1, int(new) - W + 1),
                               int(new) + 1):
                    _log_set(g, i, self.l_ring[g, i % W])
            for g, i, t, payload in rec.entries:
                self.payloads[(g, i, t)] = payload
        self.round_no = last_round + 1

        hist: Dict[Tuple[int, int], int] = {}
        for g, entries in slot_log.items():
            c = int(self.l_commit[g])
            lastv = int(self.l_last[g])
            for i, t in entries.items():
                if t > 0 and i <= c and i <= lastv:
                    hist[(g, i)] = t
        self._apply_committed(trigger=False, hist=hist)
        self._gc_payloads()

        st = (base if self._frames_plane
              else shard_state(base, self.mesh))
        self.st = st._replace(
            term=self._global_col("term", base.term, self.l_term),
            vote=self._global_col("vote", base.vote, self.l_vote),
            commit=self._global_col("commit", base.commit, self.l_commit),
            last_index=self._global_col("last_index", base.last_index,
                                        self.l_last),
            log_term=self._global_col("log_term", base.log_term,
                                      self.l_ring),
        )
        # Terms of committed-but-not-yet-applied entries that are (or may
        # fall) below the device ring window: the live apply path resolves
        # from here when the ring has moved on (see _apply_committed).
        # Restore seeds it from the WAL's full ring-diff history; without
        # it, a host restoring with applied < commit — an acked entry's
        # payload lives on the ACKING host and must be pulled — jammed
        # forever once the window passed the stalled span ("no term for
        # committed entry", found by the stale-disk snapshot test).
        # >= applied (not >): the no-op check for the NEXT entry needs the
        # term of the last applied one (see _maybe_noop).
        self._hist = {k: t for k, t in hist.items()
                      if k[1] >= int(self.applied[k[0]])}
        if ckpt is not None:
            for g_s, i_s, t_s in ckpt.get("hist", []):
                if int(i_s) >= int(self.applied[int(g_s)]):
                    self._hist[(int(g_s), int(i_s))] = int(t_s)
        self.l_state = np.zeros(G, np.int32)
        self.l_lead = np.zeros(G, np.int32)

    # ------------------------------------------------------------------
    # frames
    # ------------------------------------------------------------------

    def _report_unreachable(self, h: int) -> None:
        self.unreachable[h] = self.unreachable.get(h, 0) + 1

    def _on_frame(self, frm: int, header: dict, blob: bytes) -> None:
        t = header.get("t")
        if t == "meta":
            # Frames-plane mailbox column from peer `frm`: one frame per
            # sender round, consumed one per local round (the dense
            # mailbox holds ONE message per (g, to, from) slot). Bounded
            # backlog drops OLDEST — raft's retransmission machinery
            # (heartbeats, probes) repairs exactly like a dropped packet.
            q = self._meta_rx.get(frm)
            if q is None:
                q = self._meta_rx.setdefault(frm, deque(maxlen=16))
            q.append(blob)
            return
        if t == "pull":
            # Answer immediately from the payload store. Runs on the
            # transport rx thread while the engine thread may GC the
            # dict: snapshot each value with ONE .get per key (GIL-atomic)
            # so a concurrent delete skips that key instead of raising
            # out of the whole response.
            haves = []
            for w in header.get("wants", []):
                key = tuple(w)
                p = self.payloads.get(key)
                if p is not None:
                    haves.append((*key, p))
            if haves:
                # Tagged as a pull RESPONSE so the receiver's repair
                # counter stays exact (a late ordinary fan-out clearing a
                # _missing marker is not a pull repair).
                self.frames.send(frm, {"t": "pay", "pull": 1},
                                 _pack_payloads(haves))
            return
        self._rx.append((frm, header, blob))

    def _drain_frames(self) -> None:
        G = self.cfg.groups
        while self._rx:
            try:
                frm, header, blob = self._rx.popleft()
            except IndexError:
                return
            # One malformed/hostile frame must never kill the engine loop
            # (it would stall the whole job's collective): validate, log,
            # drop.
            try:
                t = header.get("t")
                if t == "prop":
                    g = int(header["g"])
                    if not 0 <= g < G:
                        raise ValueError(f"group {g} out of range")
                    hops = int(header.get("hops", 0))
                    if hops >= _MAX_HOPS:
                        log.warning("dropping proposal for group %d: hop "
                                    "limit (leadership view unsettled)", g)
                        continue
                    items = _unpack_items(blob)
                    with self._lock:
                        for rid, _ in items:
                            self._hops[rid] = hops
                        self._pending[g].extend(items)
                        self._dirty.add(g)
                elif t == "pay":
                    is_pull_resp = bool(header.get("pull"))
                    for g, i, tt, payload in _unpack_payloads(blob):
                        if not 0 <= g < G:
                            raise ValueError(f"group {g} out of range")
                        key = (g, i, tt)
                        if key not in self.payloads:
                            self.payloads[key] = payload
                            self._fresh_payloads.append((g, i, tt, payload))
                        if (self._missing.pop(key, None) is not None
                                and is_pull_resp):
                            self.payloads_pulled += 1
                elif t == "snap":
                    for g, a, t_s, lead, row, image in _unpack_snaps(
                            blob, self.cfg.window):
                        if not 0 <= g < G:
                            raise ValueError(f"group {g} out of range")
                        cur = self._pending_snaps.get(g)
                        if cur is None or (t_s, a) > (cur[1], cur[0]):
                            self._pending_snaps[g] = (a, t_s, lead, row,
                                                      image)
            except Exception:  # noqa: BLE001 — drop the frame, keep serving
                log.exception("bad frame from host %d dropped", frm)

    # ------------------------------------------------------------------
    # cross-host snapshot install (the rafthttp snapshot side-channel)
    # ------------------------------------------------------------------

    def _local(self, arr) -> np.ndarray:
        """This host's peer-slot column of a state array, shape
        (G, 1, ...): the addressable shard on the collective plane, a
        plain device slice on the frames plane."""
        if self._frames_plane:
            my = self.my_slot
            return np.asarray(arr[:, my:my + 1])
        return np.asarray(list(arr.addressable_shards)[0].data)

    def _set_local(self, name: str, block: np.ndarray):
        """New array for state field `name` whose LOCAL column (our peer
        slot) is `block` — shape (G, 1, ...). Purely local: on the
        collective plane every process only ever materializes its own
        shards, so no collective is involved (same pattern as the
        need_host clearing); on the frames plane it is an at[].set."""
        if self._frames_plane:
            arr = getattr(self.st, name)
            return arr.at[:, self.my_slot].set(
                self._jnp.asarray(block[:, 0]))
        jax = self._jax
        sh = getattr(self._st_sh, name)
        gshape = (block.shape[0], self.cfg.peers) + block.shape[2:]
        blk = np.ascontiguousarray(block)
        return jax.make_array_from_callback(gshape, sh, lambda idx: blk)

    def _install_snaps(self) -> None:
        """Receive half of the cross-host MsgSnap flow (reference
        raft.go:671-713 restore; single-host twin _service_need_host):
        surgically move OUR column of each staged group to the shipped
        image — term/ring/last/commit jump to the install point, the store
        is recovered wholesale, and the apply cursor follows. Runs BEFORE
        the round's collective so the step already sees the new state; the
        same round's WAL record carries both the store image (rec.snaps)
        and, via the stale l_* mirrors, the column surgery — fsynced in
        phase 5 before anything is acked on top."""
        G, Pn, W = self.cfg.groups, self.cfg.peers, self.cfg.window
        st = self.st
        local = self._local
        term = local(st.term).copy()         # (G, 1)
        vote = local(st.vote).copy()
        commit = local(st.commit).copy()
        last = local(st.last_index).copy()
        ring = local(st.log_term).copy()     # (G, 1, W)
        state = local(st.state).copy()
        lead = local(st.lead).copy()
        elapsed = local(st.elapsed).copy()
        touched = False
        for g, (a, t_s, lead_slot, row, image) in \
                self._pending_snaps.items():
            # Stale or duplicate: we are not actually behind the image, or
            # the sender's term has been superseded — drop (the reference's
            # restore ignores snapshots at-or-below commit, raft.go:676).
            if a <= int(commit[g, 0]) or t_s < int(term[g, 0]):
                continue
            # Recover the store FIRST: a corrupt image (truncated frame, a
            # buggy peer) must reject this group's install wholesale, not
            # kill the engine loop with the column already surgered — the
            # malformed-frame invariant from _drain_frames extends here.
            s = new_store(namespaces=("/0", "/1"))
            try:
                s.recovery(image)
            except Exception:  # noqa: BLE001 — reject the image, keep going
                log.exception("host %d: rejecting corrupt snapshot image "
                              "g=%d index=%d from slot %d", self.my_slot,
                              g, a, lead_slot)
                continue
            if t_s > int(term[g, 0]):
                vote[g, 0] = 0
            term[g, 0] = t_s
            ring[g, 0, :] = row
            last[g, 0] = a
            commit[g, 0] = a
            state[g, 0] = 0
            lead[g, 0] = lead_slot + 1
            elapsed[g, 0] = 0
            self._stores[g] = s
            self.applied[g] = a
            # The apply cursor jumped: pending pulls for entries at or
            # below the install point can never be answered (they fell
            # below every window — that is WHY a snapshot was needed) and
            # would otherwise occupy the pull budget forever.
            for k in [k for k in self._missing if k[0] == g and k[1] <= a]:
                del self._missing[k]
            for k in [k for k in self._hist if k[0] == g and k[1] < a]:
                del self._hist[k]
            self._snap_recs.append((g, a, image))
            self.snaps_installed += 1
            touched = True
            log.info("host %d: installed snapshot g=%d index=%d term=%d "
                     "from slot %d", self.my_slot, g, a, t_s, lead_slot)
        self._pending_snaps.clear()
        if not touched:
            return
        # l_* mirrors deliberately stay PRE-surgery: phase 4's diff against
        # them journals the install's term/vote/commit/last/ring changes.
        self.st = st._replace(
            term=self._set_local("term", term),
            vote=self._set_local("vote", vote),
            commit=self._set_local("commit", commit),
            last_index=self._set_local("last_index", last),
            log_term=self._set_local("log_term", ring),
            state=self._set_local("state", state),
            lead=self._set_local("lead", lead),
            elapsed=self._set_local("elapsed", elapsed))

    def _send_snapshots(self, flagged: np.ndarray, st):
        """Leader half of the cross-host MsgSnap flow (reference
        raft.go:246-260 sendAppend->MsgSnap + the rafthttp pipeline
        side-channel, peer.go:250-252): for each flagged group we lead,
        ship (store image @ our apply cursor a, ring row masked above a,
        term/lead metadata) to every slot whose needed entries fell below
        our ring window, then optimistically probe at a+1. `match` is NOT
        advanced — quorum commit only ever rides real acks — so a lost
        frame or a dead receiver just re-fires need_snap after the
        holdoff: self-healing without a ReportSnapshot protocol. Returns
        the (possibly progress-surgered) state."""
        W = self.cfg.window
        Pn = self.cfg.peers
        now = time.time()
        local = self._local
        nxt = local(st.next).copy()          # (G, 1, P)
        by_host: Dict[int, List[Tuple[int, int, int, int, np.ndarray,
                                      bytes]]] = {}
        surgery = []
        budget = self.cfg.snaps_per_round
        for g in flagged:
            g = int(g)
            if budget <= 0:
                break
            if self.l_state[g] != _LEADER:
                continue
            a = int(self.applied[g])
            lastv = int(self.l_last[g])
            # The probe after install sends from a+1, whose previous-entry
            # term (index a) must still be in OUR ring: if our applier is
            # further behind than the window reaches back, retry next
            # holdoff once it catches up.
            if a < 1 or a <= lastv - W:
                continue
            row = image = None
            for f in range(Pn):
                if f == self.my_slot or budget <= 0:
                    continue
                if int(nxt[g, 0, f]) > lastv - W:
                    continue                   # reachable by appends
                if now - self._snap_sent.get((g, f), 0.0) \
                        < self.cfg.snap_interval:
                    continue
                if image is None:
                    image = self.store(g).save()
                    row = self.l_ring[g].copy()
                    for w in range(W):
                        if lastv - ((lastv - w) % W) > a:
                            row[w] = 0
                self._snap_sent[(g, f)] = now
                by_host.setdefault(f, []).append(
                    (g, a, int(self.l_term[g]), self.my_slot, row, image))
                surgery.append((g, f, a))
                budget -= 1
                self.snaps_sent += 1
        for f, snaps in by_host.items():
            self.frames.send(f, {"t": "snap"}, _pack_snaps(snaps))
        if not surgery:
            return st
        prs = local(st.pr_state).copy()      # (G, 1, P)
        pau = local(st.paused).copy()
        age = local(st.ack_age).copy()
        for g, f, a in surgery:
            nxt[g, 0, f] = a + 1
            prs[g, 0, f] = 0                 # PR_PROBE
            pau[g, 0, f] = False
            age[g, 0, f] = 0
        log.info("host %d: sent %d snapshot installs (%d groups flagged)",
                 self.my_slot, len(surgery), len(flagged))
        return st._replace(
            next=self._set_local("next", nxt),
            pr_state=self._set_local("pr_state", prs),
            paused=self._set_local("paused", pau),
            ack_age=self._set_local("ack_age", age))

    # ------------------------------------------------------------------
    # public API (same shape as MultiEngine where it makes sense)
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"host-engine-{self.my_slot}")
        self._thread.start()

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=15)
        self._fail_parked_reads("engine stopped")
        self.frames.stop()
        self.wal.close()

    def store(self, g: int):
        s = self._stores.get(g)
        if s is None:
            with self._lock:
                s = self._stores.get(g)
                if s is None:
                    s = self._stores[g] = new_store(namespaces=("/0", "/1"))
        return s

    def leader_slot(self, g: int) -> int:
        if self.l_state[g] == _LEADER:
            return self.my_slot
        return int(self.l_lead[g]) - 1   # -1 when unknown

    def wait_leaders(self, timeout: float = 60.0, groups=None) -> bool:
        deadline = time.monotonic() + timeout
        gs = range(self.cfg.groups) if groups is None else groups
        while time.monotonic() < deadline:
            if all(self.leader_slot(g) >= 0 for g in gs):
                return True
            time.sleep(0.01)
        return False

    def tenant_active(self, g: int) -> bool:
        return 0 <= g < self.cfg.groups

    def tenants(self) -> List[int]:
        return list(range(self.cfg.groups))

    def create_tenant(self, *a, **kw):
        raise errors.EtcdError(errors.ECODE_NOT_FILE,
                               cause="tenant lifecycle is single-host-"
                                     "engine only (multi-host pool is "
                                     "fixed at boot)")

    remove_tenant = create_tenant

    def conf_change(self, *a, **kw):
        raise errors.EtcdError(errors.ECODE_NOT_FILE,
                               cause="per-group membership is the peers "
                                     "mesh axis in multi-host mode")

    @property
    def tenant_gen(self) -> np.ndarray:
        # Fixed pool: slots are never recycled, so every tenant stays at
        # lifecycle generation 0 (the TenantAPI cache key). Cached — this
        # sits on the per-request path.
        gen = getattr(self, "_tenant_gen0", None)
        if gen is None:
            gen = self._tenant_gen0 = np.zeros(self.cfg.groups, np.int64)
        return gen

    @property
    def h_commit(self) -> np.ndarray:
        return self.l_commit[:, None]

    @property
    def h_term(self) -> np.ndarray:
        return self.l_term[:, None]

    @property
    def h_mask(self) -> np.ndarray:
        return np.ones((self.cfg.groups, self.cfg.peers), bool)

    def status(self, g: int) -> dict:
        return {"group": g, "lead": self.leader_slot(g),
                "term": int(self.l_term[g]),
                "commit": int(self.l_commit[g]),
                "applied": int(self.applied[g]),
                "host": self.my_slot,
                "active_slots": list(range(self.cfg.peers))}

    def do(self, g: int, r: Request, timeout: Optional[float] = None) -> Any:
        """Serve one request against group g from THIS host (reads local;
        writes ride consensus and ack after LOCAL fsync+apply)."""
        if r.method == METHOD_GET:
            if r.quorum:
                if (not r.wait and not self._frames_plane
                        and self.l_state[g] == _LEADER):
                    # Zero-append read plane, collective plane only: the
                    # SPMD round is globally synchronous, so leadership
                    # confirmation needs no extra messages (see
                    # _confirm_reads). Frames-plane hosts and non-leader
                    # columns keep the QGET forward path below.
                    return self._quorum_read(g, r, timeout)
                r = Request(**{**r.__dict__, "method": METHOD_QGET})
            elif r.wait:
                return self.store(g).watch(r.path, r.recursive, r.stream,
                                           r.since)
            else:
                return self.store(g).get(r.path, r.recursive, r.sorted)
        if r.method not in (METHOD_PUT, METHOD_POST, METHOD_DELETE,
                            METHOD_QGET, METHOD_SYNC):
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"bad method {r.method}")
        if r.id == 0:
            r = Request(**{**r.__dict__, "id": self.reqid.next()})
        q = self.wait.register(r.id)
        payload = bytes([P_REQ]) + r.encode()
        with self._lock:
            self._pending[g].append((r.id, payload))
            self._dirty.add(g)
        import queue as _q
        t0 = time.perf_counter()
        metrics.propose_pending.inc()
        try:
            result = q.get(timeout=timeout or self.cfg.request_timeout)
        except _q.Empty:
            self.wait.cancel(r.id)
            metrics.propose_failed.inc()
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="request timed out",
                                   index=int(self.applied[g]))
        finally:
            metrics.propose_pending.dec()
        metrics.propose_durations.observe(
            (time.perf_counter() - t0) * 1000.0)
        if isinstance(result, errors.EtcdError):
            raise result
        if type(result) is LazyWriteEvent:
            # Waiter woken with raw C descriptors: materialize the Event
            # here on the serving thread (see MultiEngine.do).
            return result.resolve()
        return result

    # ------------------------------------------------------------------
    # the read plane (collective plane; see MultiEngine._quorum_read)
    # ------------------------------------------------------------------

    def _quorum_read(self, g: int, r: Request,
                     timeout: Optional[float] = None) -> Any:
        """Linearizable GET without a log entry: park the read, confirm
        leadership at the next round's readback, serve from the local
        store once the apply cursor reaches the captured commit index.
        Quorum reads leave etcd_server_proposal_* (nothing is proposed)
        and meter the read_index_* families."""
        if r.id == 0:
            r = Request(**{**r.__dict__, "id": self.reqid.next()})
        q = self.wait.register(r.id)
        import queue as _q
        t0 = time.perf_counter()
        obs_mod.read_index_parked.inc()
        with self._lock:
            self._reads[g].append((r.id, r))
            self._read_dirty.add(g)
            self._reads_waiting += 1
        try:
            result = q.get(timeout=timeout or self.cfg.request_timeout)
        except _q.Empty:
            self.wait.cancel(r.id)
            obs_mod.read_index_failed.inc()
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="quorum read timed out",
                                   index=int(self.applied[g]))
        finally:
            obs_mod.read_index_parked.dec()
        obs_mod.read_index_durations.observe(
            (time.perf_counter() - t0) * 1000.0)
        if isinstance(result, errors.EtcdError):
            raise result
        return result

    def _confirm_reads(self, read_take: Dict[int, int], state, term,
                       commit, last, ring) -> None:
        """Collective-plane ReadIndex confirmation, against the arrays
        just read back. Soundness: the SPMD collective is globally
        synchronous and lossless (the mailbox transpose is one
        all_to_all inside the program), so a column still reading LEADER
        after round k proves no higher-term leader has committed
        anything through round k — its campaign traffic would have
        reached every column (including ours, flipping us to follower)
        at least one full round before its first possible own-term
        commit. The leader must additionally hold its own-term entry
        committed (the reference ReadIndex precondition, raft §8): a
        fresh leader's commit mirror may still lag writes the previous
        leader acked. Deposed columns FAIL their parked reads — the
        client retries through the forward path; nothing is ever served
        at a stale index."""
        W = self.cfg.window
        failed: List[Tuple[int, int]] = []
        confirmed = 0
        with self._lock:
            for g, take in read_take.items():
                dq = self._reads[g]
                take = min(take, len(dq))
                c = int(commit[g])
                own_term = (state[g] == _LEADER and c >= 1
                            and c > int(last[g]) - W
                            and int(ring[g, c % W]) == int(term[g]))
                if own_term:
                    confirmed += 1
                    for _ in range(take):
                        self._ripe[g].append(dq.popleft() + (c,))
                    if take:
                        self._ripe_dirty.add(g)
                        self._ripe_waiting += take
                        self._reads_waiting -= take
                elif state[g] != _LEADER:
                    for _ in range(take):
                        rid, _r = dq.popleft()
                        failed.append((rid, g))
                    self._reads_waiting -= take
                # else: leader, own-term entry not committed yet — the
                # parked reads retry at the next round's readback.
                if not dq:
                    self._read_dirty.discard(g)
        obs_mod.read_index_confirms.observe(confirmed)
        for rid, g in failed:
            self.wait.trigger(rid, errors.EtcdError(
                errors.ECODE_RAFT_INTERNAL,
                cause="leadership lost during quorum read",
                index=int(self.applied[g])))

    def _serve_ripe_reads(self) -> None:
        """Serve every ripe read whose group's apply cursor reached its
        read index (the in-round apply just ran, so this is usually the
        same round that confirmed)."""
        served: List[Tuple[int, Request, int]] = []
        with self._lock:
            for g in list(self._ripe_dirty):
                dq = self._ripe[g]
                a = int(self.applied[g])
                while dq and dq[0][2] <= a:
                    rid, r, _ri = dq.popleft()
                    served.append((rid, r, g))
                if not dq:
                    self._ripe_dirty.discard(g)
            self._ripe_waiting -= len(served)
        # Same read coalescing as MultiEngine._serve_ripe_reads: one
        # get per distinct (group, path, recursive, sorted) serves the
        # whole pass linearizably.
        memo: Dict[Tuple[int, str, bool, bool], Any] = {}
        for rid, r, g in served:
            k = (g, r.path, r.recursive, r.sorted)
            result = memo.get(k)
            if result is None:
                try:
                    result = self.store(g).get(r.path, r.recursive,
                                               r.sorted)
                except errors.EtcdError as err:
                    result = err
                memo[k] = result
            self.wait.trigger(rid, result)
        if served:
            obs_mod.read_index_served.inc(len(served))

    def _fail_parked_reads(self, why: str) -> None:
        rids: List[int] = []
        with self._lock:
            for g in self._read_dirty:
                rids.extend(rid for rid, _r in self._reads[g])
                self._reads[g].clear()
            for g in self._ripe_dirty:
                rids.extend(rid for rid, _r, _i in self._ripe[g])
                self._ripe[g].clear()
            self._read_dirty.clear()
            self._ripe_dirty.clear()
            self._reads_waiting = 0
            self._ripe_waiting = 0
        for rid in rids:
            self.wait.trigger(rid, errors.EtcdError(
                errors.ECODE_RAFT_INTERNAL, cause=why))

    # ------------------------------------------------------------------
    # the round
    # ------------------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop_ev.is_set():
                self.run_round()
                if self.cfg.round_interval:
                    time.sleep(self.cfg.round_interval)
        except Exception as e:  # noqa: BLE001
            self.failed = e
            self._stop_ev.set()
            log.exception("host-engine %d loop failed", self.my_slot)
            raise

    def run_round(self) -> None:
        t_round = time.perf_counter()
        jax, jnp = self._jax, self._jnp
        G, Pn, W, E = (self.cfg.groups, self.cfg.peers, self.cfg.window,
                       self.cfg.max_ents)
        B = self.cfg.batch_max

        # -- 1. frames in; stage local, forward remote --------------------
        self._drain_frames()
        if self._pending_snaps:
            self._install_snaps()
        cnt_local = np.zeros(G, np.int32)
        self._staged.clear()
        forwards: List[Tuple[int, int, List[Tuple[int, bytes]]]] = []
        with self._lock:
            for g in list(self._dirty):
                dq = self._pending[g]
                if not dq:
                    self._dirty.discard(g)
                    continue
                if self.l_state[g] == _LEADER:
                    ents: List[List[Tuple[int, bytes]]] = []
                    while dq and len(ents) < E:
                        cur: List[Tuple[int, bytes]] = []
                        nbytes = 0
                        while (dq and len(cur) < B
                               and nbytes < self.cfg.batch_bytes
                               and dq[0][1] and dq[0][1][0] == P_REQ):
                            nbytes += len(dq[0][1])
                            cur.append(dq.popleft())
                        if not cur:
                            dq.popleft()   # drop non-REQ junk defensively
                            continue
                        ents.append(cur)
                    if not dq:
                        self._dirty.discard(g)
                    if ents:
                        for e in ents:
                            for rid, _ in e:
                                self._hops.pop(rid, None)
                        self._staged[g] = ents
                        cnt_local[g] = len(ents)
                elif self.l_lead[g] > 0:
                    lead_host = int(self.l_lead[g]) - 1
                    items = list(dq)
                    dq.clear()
                    self._dirty.discard(g)
                    forwards.append((lead_host, g, items))
                # else: no known leader — leave queued, client may time out
        for lead_host, g, items in forwards:
            # Hop count = 1 past the furthest-travelled item in the batch
            # (items that originated here count 0); _drain_frames drops at
            # the limit, so crossed leadership views can't ping-pong
            # forever.
            hops = 1 + max((self._hops.pop(rid, 0) for rid, _ in items),
                           default=0)
            self.frames.send(lead_host, {"t": "prop", "g": g, "hops": hops},
                             _pack_items(items))

        # -- 1b. read plane: pin which parked quorum reads this round's
        # confirmation covers (reads parking after dispatch could
        # postdate writes acked above the commit index this round
        # captures — they wait for their own round; see
        # MultiEngine.run_round).
        read_take: Optional[Dict[int, int]] = None
        if self._reads_waiting:
            with self._lock:
                if self._reads_waiting:
                    read_take = {g: len(self._reads[g])
                                 for g in self._read_dirty
                                 if self._reads[g]}

        # -- 2. the consensus round: global SPMD collective, or the local
        # full-(G, P) kernel with the mailbox riding frames ---------------
        routed_my = None
        if self._frames_plane:
            my = self.my_slot
            F = self.kcfg.fields
            inbox_np = np.zeros((G, Pn, Pn, F), np.int32)
            if self._self_loop is not None:
                inbox_np[:, my, my] = self._self_loop
            for j, q in list(self._meta_rx.items()):
                # Normally one frame per sender round. When a backlog
                # built up (transient stall on our side), drain up to 4
                # per round — newer frames overwrite overlapping group
                # rows (those rows are dropped packets; raft's
                # heartbeat/probe machinery retransmits), so the queue
                # recovers to fresh instead of serving permanently
                # ~maxlen-round-stale mailboxes.
                consumed = 0
                while q and consumed < 4:
                    consumed += 1
                    try:
                        idx, vals = _unpack_meta(q.popleft(), F)
                    except (ValueError, struct.error):
                        log.warning("bad meta frame from host %d dropped",
                                    j)
                        continue
                    ok = idx < G
                    inbox_np[idx[ok], my, j] = vals[ok]
            cnt = np.zeros((G, Pn), np.int32)
            cnt[:, my] = cnt_local
            st, inbox = self._step_fn(self.st, jnp.asarray(inbox_np),
                                      jnp.asarray(cnt), jnp.asarray(True))
            # Our column's sends to every peer column: routed
            # inbox[g, to, from] at from == my. Sliced on device, read
            # once; the rest of the routed mailbox is ghost traffic and
            # never leaves the device — drop the buffer now (the frames
            # plane rebuilds next round's inbox from frames; keeping the
            # (G, P, P, F) array would pin dead device memory all round).
            routed_my = np.asarray(inbox[:, :, my, :])     # (G, P, F)
            self._self_loop = routed_my[:, my, :]
            inbox = None
        else:
            cnt_gp = jax.make_array_from_callback(
                (G, Pn), self._cnt_sh, lambda idx: cnt_local[idx[0], None])
            with self.mesh:
                st, inbox = self._step_fn(self.st, self.inbox, cnt_gp,
                                          jnp.asarray(True))
        self.st = st
        self.inbox = inbox

        # -- 3. read back OUR column --------------------------------------
        local = self._local
        term = local(st.term)[:, 0]
        vote = local(st.vote)[:, 0]
        commit = local(st.commit)[:, 0]
        state = local(st.state)[:, 0]
        last = local(st.last_index)[:, 0]
        lead = local(st.lead)[:, 0]
        ring = local(st.log_term)[:, 0, :]
        need_host = local(st.need_host)[:, 0]

        if need_host.any():
            from etcd_tpu.ops.state import NH_SNAP, NH_VIOLATION
            viol = (need_host & NH_VIOLATION) != 0
            if viol.any():
                raise RuntimeError(
                    f"host {self.my_slot}: consensus safety violation in "
                    f"groups {np.nonzero(viol)[0][:8].tolist()}")
            # NH_SNAP: a target's needed entries fell below our ring
            # window — only possible after a peer host restarted with a
            # stale or empty WAL (the synchronous collective itself loses
            # nothing). Ship store images + probe (leader side of MsgSnap).
            snap_g = np.nonzero((need_host & NH_SNAP) != 0)[0]
            if len(snap_g):
                st = self._send_snapshots(snap_g, st)
            # Consume the flags: the kernel only ORs NH_* bits, so without
            # a write-back one event would re-log every round forever and
            # mask later flags. Each host zeroes ITS column shard (purely
            # local data, no collective — mirrors the single-host
            # _service_need_host clearing). Re-fire is guaranteed while the
            # lag persists (the kernel recomputes need_snap every round).
            st = st._replace(need_host=self._set_local(
                "need_host", np.zeros((G, 1), np.int32)))
            self.st = st

        # -- 4. durable record for OUR column -----------------------------
        my = self.my_slot
        rec = RoundRecord(round_no=self.round_no)
        chg = ((term != self.l_term) | (vote != self.l_vote)
               | (commit != self.l_commit))
        gi = np.nonzero(chg)[0]
        rec.hs_g = gi.astype(np.uint32)
        rec.hs_p = np.full(len(gi), my, np.uint16)
        rec.hs_term = term[gi].astype(np.uint32)
        rec.hs_vote = vote[gi].astype(np.uint16)
        rec.hs_commit = commit[gi].astype(np.uint32)

        gi = np.nonzero(last != self.l_last)[0]
        rec.last_g = gi.astype(np.uint32)
        rec.last_p = np.full(len(gi), my, np.uint16)
        rec.last_v = last[gi].astype(np.uint32)

        gi, wi = np.nonzero(ring != self.l_ring)
        lastv = last[gi]
        absi = lastv - ((lastv - wi) % W)
        keep = absi >= 1
        rec.ring_g = gi[keep].astype(np.uint32)
        rec.ring_p = np.full(int(keep.sum()), my, np.uint16)
        rec.ring_i = absi[keep].astype(np.uint32)
        rec.ring_t = ring[gi[keep], wi[keep]].astype(np.uint32)

        # Admission for locally staged proposals.
        fresh_frames: List[Tuple[int, int, int, bytes]] = []
        requeue: List[Tuple[int, List[Tuple[int, bytes]]]] = []
        for g, ents in self._staged.items():
            admitted = 0
            if state[g] == _LEADER and term[g] == self.l_term[g]:
                admitted = int(last[g] - self.l_last[g])
            t = int(term[g])
            for j, items in enumerate(ents):
                if j < admitted:
                    i = int(self.l_last[g]) + 1 + j
                    payload = _pack_entry(items)
                    self.payloads[(g, i, t)] = payload
                    rec.entries.append((g, i, t, payload))
                    fresh_frames.append((g, i, t, payload))
                else:
                    requeue.append((g, [it for e in ents[j:] for it in e]))
                    break
        with self._lock:
            for g, rest in requeue:
                self._pending[g].extendleft(reversed(rest))
                self._dirty.add(g)
        # Payloads learned from peers this round are journaled too: an ack
        # we later issue from their application must survive OUR restart.
        rec.entries.extend(self._fresh_payloads)
        # Snapshot installs received this round: the store image + cursor
        # ride the same record (and fsync) as the column surgery's diffs.
        if self._snap_recs:
            rec.snaps = self._snap_recs
            self._snap_recs = []

        self.l_term, self.l_vote, self.l_commit = term, vote, commit
        self.l_state, self.l_last, self.l_ring = state, last, ring
        self.l_lead = lead

        # -- 4b. read plane: confirm the snapshotted reads against this
        # round's readback (ripens them at the captured commit index;
        # deposed columns fail theirs).
        if read_take:
            self._confirm_reads(read_take, state, term, commit, last,
                                ring)

        # -- 5. persist BEFORE the next dispatch (cross-host contract) ----
        if not rec.is_empty():
            self.wal.append(rec)
            self._recent_recs.append(rec)

        # -- 6a. frames plane: ship this round's mailbox column AFTER the
        # fsync above — the persist-before-send contract (doc.go:31-39)
        # holds per-host exactly like the reference's Ready ordering.
        # Sparse per-peer encoding: only groups with a live message.
        if routed_my is not None:
            for h in range(Pn):
                if h == my:
                    continue
                msgs = routed_my[:, h, :]
                idx = np.nonzero(msgs.any(axis=1))[0]
                if len(idx):
                    self.frames.send(h, {"t": "meta"},
                                     _pack_meta(idx, msgs[idx]))

        # -- 6. fan out fresh local admissions ----------------------------
        if fresh_frames:
            blob = _pack_payloads(fresh_frames)
            if self._fault_rng is None:
                self.frames.broadcast({"t": "pay"}, blob)
            else:
                # Seeded per-peer drops: the receiver's apply cursor
                # stalls on the missing payload and repairs via PULL.
                for h in self.frames.peers:
                    if self._fault_rng.random() * 100 >= \
                            self.cfg.drop_pay_pct:
                        self.frames.send(h, {"t": "pay"}, blob)
                    else:
                        self.pay_frames_dropped += 1
        self._fresh_payloads = []

        # -- 7. apply + ack locally ---------------------------------------
        self._apply_committed(trigger=True)
        if self._ripe_waiting:
            self._serve_ripe_reads()
        self._request_pulls()

        self.round_no += 1
        ms = (time.perf_counter() - t_round) * 1000.0
        self.round_ms_ewma = (ms if self.round_ms_ewma == 0.0 else
                              self.round_ms_ewma
                              + 0.05 * (ms - self.round_ms_ewma))
        if self.round_no % self.cfg.checkpoint_rounds == 0:
            self._checkpoint()
            self._gc_payloads()

    # ------------------------------------------------------------------
    # apply
    # ------------------------------------------------------------------

    def _apply_committed(self, trigger: bool, hist=None) -> None:
        W = self.cfg.window
        changed = np.nonzero(self.l_commit > self.applied)[0]
        now = time.time()
        for g in changed:
            g = int(g)
            lo, hi = int(self.applied[g]), int(self.l_commit[g])
            done = lo
            for i in range(lo + 1, hi + 1):
                t = 0
                if i > self.l_last[g] - W:
                    t = int(self.l_ring[g, i % W])
                if t == 0:
                    t = self._hist.get((g, i), 0)
                if t == 0 and hist is not None:
                    t = hist.get((g, i), 0)
                if t == 0:
                    log.error("host %d: no term for committed entry "
                              "g=%d i=%d", self.my_slot, g, i)
                    break
                key = (g, i, t)
                payload = self.payloads.get(key)
                if payload is None:
                    # Leader no-ops never ship payloads; real entries that
                    # haven't arrived yet stall the cursor until a pull
                    # repairs them. Heuristic: a no-op is index == the
                    # first entry of its term from OUR ring; safer to stall
                    # briefly and pull — peers answer no-op pulls with
                    # nothing, and _maybe_noop resolves them.
                    if self._maybe_noop(g, i, t):
                        done = i
                        continue
                    self._missing.setdefault(key, now)
                    # The stall can outlive the ring window (live traffic
                    # keeps moving last_index): remember every term of the
                    # committed span that is STILL resolvable now — plus
                    # i-1's, which _maybe_noop(i) will need — so the retry
                    # after the pull repairs the payload can never lose
                    # them (the jam the stale-disk test found). In the
                    # live path only the ring can resolve, so clamp the
                    # rescan to the window instead of walking a possibly
                    # huge backlog every stalled round.
                    if hist is not None:
                        start = max(i - 1, 1)
                    else:
                        start = max(i - 1, int(self.l_last[g]) - W + 1, 1)
                    for j in range(start, hi + 1):
                        if (g, j) not in self._hist:
                            tj = 0
                            if j > self.l_last[g] - W:
                                tj = int(self.l_ring[g, j % W])
                            if tj == 0 and hist is not None:
                                tj = hist.get((g, j), 0)
                            if tj:
                                self._hist[(g, j)] = tj
                    break
                if payload[0] == P_REQ:
                    r = Request.decode(payload[1:])
                    try:
                        result = self._apply_request(g, r)
                    except errors.EtcdError as err:
                        result = err
                    if trigger:
                        if r.method != METHOD_SYNC:
                            self.acked_requests += 1
                        self.wait.trigger(r.id, result)
                elif payload[0] == P_MULTI:
                    # Batched fast path (see MultiEngine._apply_committed):
                    # in multi-host mode MOST requests have no local waiter
                    # — the proposing host acks its client; the other N-1
                    # hosts apply the same entries purely for state — so
                    # runs of unconditional PUTs collapse into one
                    # GIL-atomic C call per run.
                    st = self.store(g)
                    many = getattr(st, "set_applied_many", None)
                    fp: List[str] = []
                    fv: List[str] = []
                    fneed: List[int] = []
                    frids: List[int] = []
                    is_reg = self.wait.is_registered
                    for blob in _unpack_multi(payload):
                        r = Request.decode(blob)
                        if (many is not None and r.method == METHOD_PUT
                                and not r.dir and not r.refresh
                                and r.prev_exist is None
                                and not r.prev_index and not r.prev_value
                                and r.expiration is None):
                            if is_reg(r.id):
                                # Locally-proposed waiter-held PUTs ride
                                # the batch: the waiter is woken with the
                                # raw descriptors (LazyWriteEvent; see
                                # MultiEngine._flush_many).
                                fneed.append(len(fp))
                                frids.append(r.id)
                            fp.append(r.path)
                            fv.append(r.val or "")
                            continue
                        if fp:
                            self._flush_many(st, fp, fv, fneed, frids,
                                             trigger)
                            fp, fv, fneed, frids = [], [], [], []
                        try:
                            result = self._apply_request(g, r)
                        except errors.EtcdError as err:
                            result = err
                        if trigger:
                            if r.method != METHOD_SYNC:
                                self.acked_requests += 1
                            self.wait.trigger(r.id, result)
                    if fp:
                        self._flush_many(st, fp, fv, fneed, frids,
                                         trigger)
                done = i
            self.applied[g] = done
            if self._hist:
                # Keep `done` itself: _maybe_noop(done + 1) reads its term.
                for j in range(lo + 1, done):
                    self._hist.pop((g, j), None)

    def _maybe_noop(self, g: int, i: int, t: int) -> bool:
        """True if entry (g, i, term t) is a leader no-op: it is the FIRST
        entry of term t in our log (leaders append exactly one payload-less
        entry, at the start of their term — kernel _append_noop_and_lead).
        The previous entry's term resolves from the ring, falling back to
        the retained-history map when it dropped below the window — a
        term-boundary no-op below the window otherwise reads as a missing
        payload and jams the apply cursor with unanswerable pulls (found
        by the stale-disk snapshot test)."""
        W = self.cfg.window
        if i == 1:
            return True
        prev_t = 0
        if i - 1 > self.l_last[g] - W:
            prev_t = int(self.l_ring[g, (i - 1) % W])
        if prev_t == 0:
            prev_t = self._hist.get((g, i - 1), 0)
        return prev_t != 0 and prev_t < t

    def _flush_many(self, st, fp: List[str], fv: List[str],
                    fneed: List[int], frids: List[int],
                    trigger: bool) -> None:
        """One batched run of plain-file PUTs; need-listed waiters are
        woken with raw descriptors (see MultiEngine._flush_many)."""
        if not fneed:
            st.set_applied_many(fp, fv)
            if trigger:
                self.acked_requests += len(fp)
            return
        now = st.clock()
        _, descs = st.set_applied_many(fp, fv, need=fneed)
        if trigger:
            self.acked_requests += len(fp)
            for (pos, nd, pd, idx), rid in zip(descs, frids):
                if nd is None:
                    code, cause = pd
                    res: Any = errors.EtcdError(code, cause=cause,
                                                index=idx)
                else:
                    res = LazyWriteEvent(nd, pd, idx, now)
                self.wait.trigger(rid, res)

    def _apply_request(self, g: int, r: Request):
        st = self.store(g)
        exp = r.expiration
        if r.method == METHOD_POST:
            return st.create(r.path, is_dir=r.dir, value=r.val, unique=True,
                             expire_time=exp)
        if r.method == METHOD_PUT:
            if r.refresh:
                return st.update(r.path, None, exp, refresh=True)
            if r.prev_exist is not None:
                if r.prev_exist:
                    if r.prev_index or r.prev_value:
                        return st.compare_and_swap(r.path, r.prev_value,
                                                   r.prev_index, r.val, exp)
                    return st.update(r.path, r.val, exp)
                return st.create(r.path, is_dir=r.dir, value=r.val,
                                 expire_time=exp)
            if r.prev_index or r.prev_value:
                return st.compare_and_swap(r.path, r.prev_value,
                                           r.prev_index, r.val, exp)
            if not r.dir:
                # see engine._apply_request: lazy-event fast path
                if self.wait.is_registered(r.id):
                    lazy = getattr(st, "set_applied_lazy", None)
                    if lazy is not None:
                        return lazy(r.path, r.val, exp)
                    return st.set_applied(r.path, r.val, exp, True)
                return st.set_applied(r.path, r.val, exp, False)
            return st.set(r.path, is_dir=r.dir, value=r.val, expire_time=exp)
        if r.method == METHOD_DELETE:
            if r.prev_index or r.prev_value:
                return st.compare_and_delete(r.path, r.prev_value,
                                             r.prev_index)
            return st.delete(r.path, is_dir=r.dir, recursive=r.recursive)
        if r.method == METHOD_QGET:
            return st.get(r.path, r.recursive, r.sorted)
        if r.method == METHOD_SYNC:
            st.delete_expired_keys(r.time)
            return None
        raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                               cause=f"bad method {r.method}")

    def _request_pulls(self) -> None:
        if not self._missing:
            return
        now = time.time()
        if now - self._last_pull < self.cfg.pull_interval:
            return
        self._last_pull = now
        wants = [list(k) for k, t0 in self._missing.items()
                 if now - t0 >= self.cfg.pull_interval / 2]
        if wants:
            self.pulls_sent += 1
            self.frames.broadcast({"t": "pull", "wants": wants[:512]})

    # ------------------------------------------------------------------
    # checkpoint
    # ------------------------------------------------------------------

    def _checkpoint(self) -> None:
        import base64 as _b64
        state = {
            "round": self.round_no - 1,
            "term": np_b64(self.l_term), "vote": np_b64(self.l_vote),
            "commit": np_b64(self.l_commit), "last": np_b64(self.l_last),
            "ring": np_b64(self.l_ring),
            "applied": np_b64(self.applied),
            "stores": {str(g): s.save().decode()
                       for g, s in self._stores.items()},
            "payloads": [
                (g, i, t, _b64.b64encode(p).decode())
                for (g, i, t), p in self.payloads.items()
                if i > self.applied[g]],
            # Terms of committed-but-unapplied entries below the ring
            # window (see _hist): recs before this checkpoint get purged,
            # taking their ring diffs with them, so a stalled span's terms
            # must ride the checkpoint itself. >= applied, not >: the
            # no-op check for entry applied+1 reads applied's term, and
            # after the purge the checkpoint is its only source.
            "hist": [(g, i, t) for (g, i), t in self._hist.items()
                     if i >= self.applied[g]],
        }
        self.wal.save_checkpoint(self.round_no - 1, state)

    def _gc_payloads(self) -> None:
        """Drop applied payloads — EXCEPT the trailing ring window: a
        peer host that crashed before receiving a payload repairs it via
        PULL after restart, and OUR applied cursor says nothing about how
        far behind that peer's cursor is. Any index still resolvable from
        the device ring (i > last - W) must stay answerable; a peer
        lagging beyond the ring is the documented cross-host snapshot
        case, not a pull. (Dropping by local `applied` alone left a
        restarted peer's group stuck forever: it pulled an index nobody
        retained — found by the supervisor recovery test.)"""
        W = self.cfg.window
        dead = [k for k in self.payloads
                if k[1] <= self.applied[k[0]]
                and k[1] <= self.l_last[k[0]] - W]
        for k in dead:
            del self.payloads[k]
        # Snapshot-send holdoffs are only meaningful for ~snap_interval;
        # prune stale ones so a mass catch-up doesn't leave G*P tombstones.
        cutoff = time.time() - 60.0
        for k in [k for k, t0 in self._snap_sent.items() if t0 < cutoff]:
            del self._snap_sent[k]
        # Stale retained-term entries: the per-pass prune keeps each
        # pass's boundary entries, which fall below `applied` once later
        # passes move on — sweep them here (checkpoint cadence).
        for k in [k for k in self._hist
                  if k[1] < self.applied[k[0]]]:
            del self._hist[k]


# ---------------------------------------------------------------------------
# frame payload packing
# ---------------------------------------------------------------------------

def _pack_meta(idx: np.ndarray, vals: np.ndarray) -> bytes:
    """Sparse mailbox column frame: u32 count, then group indices (u32)
    and per-group message fields (i32 x F). Only groups carrying a live
    message are shipped — the quiescent steady state is a handful of
    heartbeat rows, not G."""
    return (struct.pack("<I", len(idx))
            + np.ascontiguousarray(idx.astype("<u4")).tobytes()
            + np.ascontiguousarray(vals.astype("<i4")).tobytes())


def _unpack_meta(blob: bytes, fields: int) -> Tuple[np.ndarray, np.ndarray]:
    (n,) = struct.unpack_from("<I", blob, 0)
    need = 4 + 4 * n + 4 * n * fields
    if len(blob) != need:
        raise ValueError(f"meta frame length {len(blob)} != {need}")
    idx = np.frombuffer(blob, "<u4", n, 4).astype(np.int64)
    vals = np.frombuffer(blob, "<i4", n * fields,
                         4 + 4 * n).reshape(n, fields)
    return idx, vals


def _pack_items(items: List[Tuple[int, bytes]]) -> bytes:
    out = [struct.pack("<I", len(items))]
    for rid, payload in items:
        out.append(struct.pack("<QI", rid, len(payload)))
        out.append(payload)
    return b"".join(out)


def _unpack_items(blob: bytes) -> List[Tuple[int, bytes]]:
    (n,) = struct.unpack_from("<I", blob, 0)
    off = 4
    out = []
    for _ in range(n):
        rid, ln = struct.unpack_from("<QI", blob, off)
        off += 12
        out.append((rid, blob[off:off + ln]))
        off += ln
    return out


def _pack_payloads(entries: List[Tuple[int, int, int, bytes]]) -> bytes:
    out = [struct.pack("<I", len(entries))]
    for g, i, t, payload in entries:
        out.append(struct.pack("<IIII", g, i, t, len(payload)))
        out.append(payload)
    return b"".join(out)


def _unpack_payloads(blob: bytes) -> List[Tuple[int, int, int, bytes]]:
    (n,) = struct.unpack_from("<I", blob, 0)
    off = 4
    out = []
    for _ in range(n):
        g, i, t, ln = struct.unpack_from("<IIII", blob, off)
        off += 16
        out.append((g, i, t, blob[off:off + ln]))
        off += ln
    return out


def _pack_snaps(snaps: List[Tuple[int, int, int, int, np.ndarray,
                                  bytes]]) -> bytes:
    """(g, install_index, term, lead_slot, ring_row[W], store_image)."""
    out = [struct.pack("<I", len(snaps))]
    for g, a, t, lead, row, image in snaps:
        out.append(struct.pack("<IIIH", g, a, t, lead))
        out.append(np.ascontiguousarray(row.astype("<i4")).tobytes())
        out.append(struct.pack("<I", len(image)))
        out.append(image)
    return b"".join(out)


def _unpack_snaps(blob: bytes, window: int
                  ) -> List[Tuple[int, int, int, int, np.ndarray, bytes]]:
    (n,) = struct.unpack_from("<I", blob, 0)
    off = 4
    out = []
    for _ in range(n):
        g, a, t, lead = struct.unpack_from("<IIIH", blob, off)
        off += 14
        row = np.frombuffer(blob, "<i4", count=window,
                            offset=off).astype(np.int32)
        off += 4 * window
        (ln,) = struct.unpack_from("<I", blob, off)
        off += 4
        if off + ln > len(blob):
            # A silently truncated store image must fail HERE, inside the
            # drain-time per-frame try, not later in the install path.
            raise ValueError(f"snap frame truncated: image needs {ln} "
                             f"bytes, {len(blob) - off} remain")
        out.append((g, a, t, lead, row, blob[off:off + ln]))
        off += ln
    return out
