from etcd_tpu.server.request import Request
from etcd_tpu.server.cluster import Cluster, Member
from etcd_tpu.server.server import EtcdServer, ServerConfig

__all__ = ["Request", "Cluster", "Member", "EtcdServer", "ServerConfig"]
