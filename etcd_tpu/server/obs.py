"""Pipeline observability plane: per-compartment metrics, a round
flight recorder, and sampled end-to-end proposal traces.

The compartment pipeline (round loop -> WAL writer shards -> applier
shards -> ack gate) was observable only as cumulative phase_s sums that
bench.py scrapes post-hoc. This module gives each stage the live
queue+latency view "Scaling Replicated State Machines with
Compartmentalization" (PAPERS.md) assumes — the reference ships the
same shape as etcdserver/wal/snap/rafthttp metrics.go behind /metrics.

Three planes, all built to stay off the round loop's critical path:

  * Prometheus series (module-level, in metrics.REGISTRY): histograms
    for round-loop phases, kernel step time, batch occupancy, per-shard
    WAL fsync latency / group-commit size, per-applier-shard apply
    batches and the ack-gate wait, plus queue-depth and watermark-lag
    gauges and the pool router's per-shard request counts. Exposed by
    the engine HTTP layer at /metrics (etcdhttp/tenants.py) and the
    pool router (scripts/pool_serve.py).

  * FlightRecorder: a fixed ring of per-round stage timestamps
    (submitted -> stepped -> wal-submitted -> durable -> applied ->
    acked). mark() is three list stores — near-zero steady state — and
    the ring dumps as Chrome trace-event JSON (chrome://tracing /
    Perfetto) via SIGUSR2, GET /debug/flight, or automatically when a
    compartment fail-stops.

  * Tracer: one in N proposals (ETCD_TPU_TRACE_EVERY) is followed by
    request id through the HTTP front (engine.do), admission into a
    round batch, the WAL submit, the durability gate, apply and ack —
    an end-to-end span breakdown per sampled proposal. The rid rides
    the durable Request payload, so a SIGKILL'd engine's replay
    re-marks surviving sampled rids as "replayed".

ETCD_TPU_OBS=off disables every engine-side observation (the A/B
switch the instrumentation-overhead gate measures against); the series
still exist, they just stay flat.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from etcd_tpu.utils import metrics

log = logging.getLogger("etcd_tpu.obs")


def obs_enabled() -> bool:
    """The instrumentation master switch (default on). The off side is
    the round-7 baseline the overhead A/B compares against."""
    return os.environ.get("ETCD_TPU_OBS", "on").lower() not in (
        "off", "0", "false", "no")


# -- Prometheus series -------------------------------------------------------
# Module-level so every engine in the process shares one set (the
# registry is idempotent-by-name anyway). Sub-ms phases need finer
# buckets than fsyncs; request-count histograms use count buckets.

_COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096,
                  16384, 65536)

round_phase = metrics.LabeledHistogram(
    "etcd_engine_round_phase_seconds",
    "Wall time of one round-loop phase (stage/dispatch/readback/record/"
    "wal_submit/tail).", ("phase",))
kernel_step = metrics.Histogram(
    "etcd_engine_kernel_step_seconds",
    "Device kernel step wall time per round (dispatch + readback).")
round_batch = metrics.Histogram(
    "etcd_engine_round_batch_requests",
    "Client requests admitted into one round's log entries (batch "
    "occupancy).", buckets=_COUNT_BUCKETS)
rounds_total = metrics.Counter(
    "etcd_engine_rounds_total", "Engine rounds completed.")
acked_total = metrics.Counter(
    "etcd_engine_acked_requests_total",
    "Client requests acked by live rounds (the BENCH acked-writes "
    "counter's Prometheus twin).")

wal_fsync = metrics.LabeledHistogram(
    "etcd_wal_writer_fsync_seconds",
    "WAL writer shard group-commit duration (append batch + one fsync, "
    "measured in the writer thread).", ("shard",))
wal_commit_rounds = metrics.LabeledHistogram(
    "etcd_wal_writer_group_commit_rounds",
    "Round records covered by one WAL writer group commit.",
    ("shard",), buckets=_COUNT_BUCKETS)
wal_queue_depth = metrics.LabeledGauge(
    "etcd_wal_writer_queue_depth",
    "WAL writer shard queue depth observed at submit.", ("shard",))
wal_watermark_lag = metrics.Gauge(
    "etcd_wal_writer_watermark_lag_tickets",
    "Submitted tickets not yet covered by the durability watermark "
    "(min over shards).")

applier_queue_depth = metrics.LabeledGauge(
    "etcd_applier_queue_depth",
    "Applier shard commit-view queue depth observed at enqueue.",
    ("shard",))
applier_batch = metrics.LabeledHistogram(
    "etcd_applier_apply_batch_requests",
    "Client requests applied+acked by one applier-shard pass.",
    ("shard",), buckets=_COUNT_BUCKETS)
ack_gate_wait = metrics.Histogram(
    "etcd_ack_gate_wait_seconds",
    "Time an applier shard waited at the durability gate "
    "(wal.wait_durable) before releasing a pass's acks.")

pool_router_requests = metrics.LabeledCounter(
    "etcd_pool_router_requests_total",
    "Requests the pool router relayed, by owning shard (refused/unknown "
    "route under shard=\"none\").", ("shard",))

# The zero-append read plane (engine._quorum_read): quorum reads leave
# the etcd_server_proposal_* families entirely — they append nothing —
# and meter here instead.
read_index_confirms = metrics.Histogram(
    "etcd_read_index_confirmations_per_round",
    "Groups whose ReadIndex quorum confirmation succeeded in one read "
    "round.", buckets=_COUNT_BUCKETS)
read_index_parked = metrics.Gauge(
    "etcd_read_index_parked_reads",
    "Quorum reads parked on the read plane: awaiting a leadership "
    "confirmation or the apply cursor reaching their read index.")
read_index_durations = metrics.Summary(
    "etcd_read_index_durations_milliseconds",
    "The latency distributions of quorum reads served by the ReadIndex "
    "plane (submit to serve).")
read_index_served = metrics.Counter(
    "etcd_read_index_reads_total",
    "Quorum reads served by the ReadIndex plane (zero log entries, zero "
    "WAL bytes).")
read_index_failed = metrics.Counter(
    "etcd_read_index_failed_total",
    "Quorum reads that timed out before confirmation + apply catch-up.")
read_index_lease = metrics.Counter(
    "etcd_read_index_lease_reads_total",
    "Quorum reads that skipped the confirmation round under a leader "
    "lease (EngineConfig.read_lease_ms).")

# The coalescing ingress tier (server/ingress.py): a stateless front
# process that buffers shallow per-tenant writes inside an adaptive
# window and ships each flush upstream as ONE /tenants/{t}/batch
# request. These families meter the manufactured batch depth (the whole
# point of the tier), why each window closed, how many batches are in
# flight upstream, and the watch fan-out hub. Module-level like the rest
# so the ingress process just imports and observes.
ingress_batch = metrics.Histogram(
    "etcd_ingress_coalesce_batch_requests",
    "Client writes coalesced into one upstream batch flush (the depth "
    "the ingress manufactured from shallow clients).",
    buckets=_COUNT_BUCKETS)
ingress_flush_reason = metrics.LabeledCounter(
    "etcd_ingress_flush_reason_total",
    "Why a coalescing window closed: count (flush_max_requests hit), "
    "bytes (flush_max_bytes hit), or drain (upstream inflight slot "
    "freed with a non-empty buffer).", ("reason",))
ingress_inflight = metrics.Gauge(
    "etcd_ingress_upstream_inflight_batches",
    "Coalesced batches currently in flight to the upstream engine.")
ingress_acked = metrics.Counter(
    "etcd_ingress_acked_requests_total",
    "Client writes acked by the ingress AFTER the upstream batch ack "
    "(never before — an ingress crash cannot lose an acked write).")
ingress_errors = metrics.Counter(
    "etcd_ingress_upstream_errors_total",
    "Client writes failed back because their upstream flush errored "
    "(connection loss, non-200 batch response).")
ingress_ack_ms = metrics.Summary(
    "etcd_ingress_ack_milliseconds",
    "Client-observed write ack latency through the ingress (enqueue "
    "into the coalescing window -> upstream-acked fan-back).")
ingress_hub_watchers = metrics.Gauge(
    "etcd_ingress_hub_watchers",
    "Downstream watchers currently multiplexed onto upstream watch "
    "streams by the fan-out hub.")
ingress_hub_streams = metrics.Gauge(
    "etcd_ingress_hub_streams",
    "Upstream watch streams the hub holds open (one per live "
    "(tenant, prefix, recursive) key).")
ingress_hub_deliveries = metrics.Counter(
    "etcd_ingress_hub_deliveries_total",
    "Events fanned out to downstream watchers by the hub (one upstream "
    "event delivered to N watchers counts N).")
ingress_lease_reads = metrics.Counter(
    "etcd_ingress_lease_reads_total",
    "Quorum GETs the ingress downgraded to plain local GETs under its "
    "read lease (a quorum-confirmed upstream ack within read_lease_ms).")
ingress_slow_clients = metrics.Counter(
    "etcd_ingress_slow_clients_total",
    "Downstream connections dropped because their buffered response "
    "backlog exceeded the per-connection cap (a stalled watcher on a "
    "busy key must not grow ingress memory without bound).")

# The pipelined binary upstream channel (server/batchframe.py): one
# persistent frame connection per lane, up to flush_window flushes in
# flight, demuxed by flush id. These families meter the channel's
# lifecycle (reconnects with capped backoff, JSON-path fallbacks when
# the upstream doesn't speak frames) and its frame traffic.
ingress_upstream_reconnects = metrics.Counter(
    "etcd_ingress_upstream_reconnects_total",
    "Upstream channel (re-)establishment attempts after a failure or a "
    "severed channel; paced by capped exponential backoff so a flapping "
    "engine never spins a lane flusher hot.")
ingress_upstream_fallbacks = metrics.Counter(
    "etcd_ingress_upstream_fallbacks_total",
    "Lanes that fell back from the binary batchframe channel to the "
    "JSON /batch path because the upstream refused the 101 handshake "
    "(e.g. a router that only rewrites /tenants/{t}/batch).")
ingress_upstream_frames = metrics.LabeledCounter(
    "etcd_ingress_upstream_frames_total",
    "Binary frames on the upstream channel by direction (sent = request "
    "frames / one per flush; recv = response frames).", ("direction",))
ingress_upstream_severed = metrics.Counter(
    "etcd_ingress_upstream_severed_flushes_total",
    "In-flight flushes failed back with 503 because their channel died "
    "before their response frame arrived (exactly the registered "
    "flush ids — never a retry, a dead flush MAY have committed).")

# The native (ingresscore.c) hot loop. The *_total counters meter the
# scan/format hot loop regardless of codec; etcd_ingress_native_enabled
# says which implementation is serving (1 = C extension, 0 = the pure-
# Python reference fallback).
ingress_native_enabled = metrics.Gauge(
    "etcd_ingress_native_enabled",
    "1 when the ingresscore C extension serves the HTTP scan/format hot "
    "loop, 0 when the pure-Python fallback does.")
ingress_native_scanned = metrics.Counter(
    "etcd_ingress_native_scanned_requests_total",
    "Client HTTP requests emitted by the read-buffer scanner (one "
    "GIL-releasing C pass per readable event when native is enabled).")
ingress_native_formatted = metrics.Counter(
    "etcd_ingress_native_formatted_responses_total",
    "Client HTTP responses materialized by the batch response formatter "
    "(whole-flush fan-backs format in one call when native is enabled).")


# -- flight recorder ---------------------------------------------------------

# Stage indices into a ring row (row[0] is the round number; stage k's
# timestamp lives at row[1+k]).
SUBMITTED, STEPPED, WAL_SUBMITTED, DURABLE, APPLIED, ACKED = range(6)
STAGE_NAMES = ("submitted", "stepped", "wal_submitted", "durable",
               "applied", "acked")


class FlightRecorder:
    """Fixed ring of per-round stage timestamps.

    mark() is the hot path: slot lookup + two or three list stores, no
    locks, no allocation. Rounds map to slots by round_no % capacity;
    the round loop (the only SUBMITTED writer) resets a slot when it
    reuses it, and late markers from writer/applier threads verify the
    slot still holds their round before writing — a wrapped slot drops
    the stale mark instead of corrupting the new round's row. Lost
    marks under that race are bounded to rounds a full ring apart.
    """

    def __init__(self, capacity: int = 0) -> None:
        cap = capacity or int(os.environ.get("ETCD_TPU_FLIGHT_CAP",
                                             "4096"))
        self.capacity = max(16, cap)
        # row = [round_no, t_submitted, ..., t_acked]; -1 = unset.
        self._ring: List[list] = [[-1] + [0.0] * 6
                                  for _ in range(self.capacity)]
        self.enabled = obs_enabled()
        self.dumps = 0

    def mark(self, round_no: int, stage: int,
             t: Optional[float] = None) -> None:
        if not self.enabled or round_no < 0:
            return
        row = self._ring[round_no % self.capacity]
        if stage == SUBMITTED:
            # The round loop claims the slot: one list rebind keeps the
            # reset a single atomic store (late markers for the evicted
            # round then miss the identity check below and drop out).
            self._ring[round_no % self.capacity] = \
                [round_no, t if t is not None else time.perf_counter(),
                 0.0, 0.0, 0.0, 0.0, 0.0]
            return
        if row[0] != round_no:
            return                      # slot wrapped; drop the late mark
        row[1 + stage] = t if t is not None else time.perf_counter()

    def snapshot(self) -> List[list]:
        """Rows holding at least a SUBMITTED mark, in round order."""
        rows = [list(r) for r in self._ring if r[0] >= 0]
        rows.sort(key=lambda r: r[0])
        return rows

    def to_trace_events(self) -> dict:
        """Chrome trace-event JSON (load in chrome://tracing/Perfetto).

        Each round becomes one tid; every present stage timestamp is an
        instant event, and each consecutive present stage pair becomes a
        complete ("X") span, so the per-round waterfall reads directly.
        """
        rows = self.snapshot()
        events = []
        t0 = min((r[1] for r in rows), default=0.0)

        def us(t):
            return (t - t0) * 1e6

        for row in rows:
            rnd = row[0]
            stamps = [(k, row[1 + k]) for k in range(6)
                      if row[1 + k] > 0.0]
            for k, t in stamps:
                events.append({"name": STAGE_NAMES[k], "ph": "i",
                               "ts": us(t), "pid": 1, "tid": rnd,
                               "s": "t", "args": {"round": rnd}})
            for (ka, ta), (kb, tb) in zip(stamps, stamps[1:]):
                events.append({
                    "name": f"{STAGE_NAMES[ka]}->{STAGE_NAMES[kb]}",
                    "ph": "X", "ts": us(ta), "dur": max(us(tb) - us(ta),
                                                        0.01),
                    "pid": 1, "tid": rnd, "args": {"round": rnd}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, data_dir: str, reason: str) -> Optional[str]:
        """Write the ring as trace-event JSON under <data_dir>/
        diagnostics; never raises (dumping is diagnostics, not a
        failure path of its own)."""
        try:
            ddir = os.path.join(data_dir, "diagnostics")
            os.makedirs(ddir, exist_ok=True)
            self.dumps += 1
            path = os.path.join(
                ddir, f"flight-{reason}-{self.dumps:04d}.trace.json")
            with open(path, "w") as f:
                json.dump(self.to_trace_events(), f)
            log.warning("flight recorder dumped to %s (%s)", path, reason)
            return path
        except Exception:  # noqa: BLE001 — diagnostics must not cascade
            log.exception("flight recorder dump failed (%s)", reason)
            return None


# -- sampled proposal traces -------------------------------------------------

TRACE_STAGES = ("submit", "admitted", "wal_submit", "durable", "applied",
                "acked", "replayed")


class Tracer:
    """Deterministic 1-in-N proposal sampling by request id.

    rid % every == 0 selects a proposal at the HTTP front (engine.do);
    the same predicate re-selects it at every later stage — including a
    restarted process's WAL replay, because the rid rides the durable
    Request payload — so no sampling decision needs to travel. Off
    (every=0) every call is one predicate test.
    """

    MAX_SPANS = 512

    def __init__(self, every: Optional[int] = None) -> None:
        if every is None:
            every = int(os.environ.get("ETCD_TPU_TRACE_EVERY", "0"))
        self.every = max(0, every)
        self._lock = threading.Lock()
        self._spans: Dict[int, dict] = {}

    def sampled(self, rid: int) -> bool:
        return bool(self.every) and rid % self.every == 0

    def mark(self, rid: int, stage: str, **extra) -> None:
        """Record one stage timestamp for a sampled rid. Cold path by
        construction (1 in N); unsampled rids pay one modulo."""
        if not self.sampled(rid):
            return
        t = time.perf_counter()
        with self._lock:
            span = self._spans.get(rid)
            if span is None:
                if len(self._spans) >= self.MAX_SPANS:
                    # Drop the oldest finished span first, else oldest.
                    victim = next(
                        (k for k, s in self._spans.items()
                         if "acked" in s["stages"]
                         or "replayed" in s["stages"]),
                        next(iter(self._spans)))
                    del self._spans[victim]
                span = self._spans[rid] = {"rid": rid, "stages": {}}
            span["stages"][stage] = t
            span.update(extra)

    def spans(self) -> List[dict]:
        with self._lock:
            return [dict(s, stages=dict(s["stages"]))
                    for s in self._spans.values()]

    def dump(self) -> dict:
        """Spans with per-stage deltas (seconds from submit, or from
        the earliest stage seen — replayed spans have no submit)."""
        out = []
        for s in sorted(self.spans(), key=lambda s: s["rid"]):
            stages = s["stages"]
            base = min(stages.values())
            out.append({**{k: v for k, v in s.items() if k != "stages"},
                        "stages": {k: round(v - base, 6)
                                   for k, v in sorted(
                                       stages.items(),
                                       key=lambda kv: kv[1])}})
        return {"every": self.every, "spans": out}


class EngineObs:
    """One engine's bound observability plane: pre-resolved metric
    children for its shard geometry (hot paths index lists instead of
    formatting label keys), the flight recorder, and the tracer.
    `enabled` False (ETCD_TPU_OBS=off) makes the engine skip every
    observation — the series stay registered but flat."""

    def __init__(self, wal_shards: int, applier_shards: int) -> None:
        self.enabled = obs_enabled()
        self.flight = FlightRecorder()
        self.tracer = Tracer()
        self.h_phase = {p: round_phase.labels(p)
                        for p in ("stage", "dispatch", "readback",
                                  "record", "wal_submit", "tail")}
        self.h_step = kernel_step
        self.h_batch = round_batch
        self.h_wal_fsync = [wal_fsync.labels(k)
                            for k in range(wal_shards)]
        self.h_wal_commit = [wal_commit_rounds.labels(k)
                             for k in range(wal_shards)]
        self.g_wal_queue = [wal_queue_depth.labels(k)
                            for k in range(wal_shards)]
        self.g_wal_lag = wal_watermark_lag
        self.g_appl_queue = [applier_queue_depth.labels(k)
                             for k in range(applier_shards)]
        self.h_appl_batch = [applier_batch.labels(k)
                             for k in range(applier_shards)]
        self.h_ack_wait = ack_gate_wait
        self.c_rounds = rounds_total
        self.c_acked = acked_total
        self.h_read_confirms = read_index_confirms
        self.g_read_parked = read_index_parked
        self.s_read_dur = read_index_durations
        self.c_reads_served = read_index_served
        self.c_reads_failed = read_index_failed
        self.c_reads_lease = read_index_lease
