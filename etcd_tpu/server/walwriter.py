"""WAL-writer compartment: the engine's durability stage as its own
pipeline stage, off the round loop's critical path.

PR 6's applier pool left the round loop's serial append+fsync as the
clock — the appliers win precisely by draining UNDER fsync stalls, so
fsync set the period. This module applies the same compartmentalization
(PAPERS.md "Scaling Replicated State Machines with Compartmentalization")
to the log stage itself:

  round loop --submit(rec)--> [per-range writer shard queues]
                                 |  each shard thread drains its queue
                                 |  as ONE batch: append every queued
                                 |  sub-record, then ONE fsync (group
                                 v  commit across rounds)
                       durability watermark (min over shard tails)
                                 |
  applier workers --wait_durable(ticket)--> release acks

The crash-ordering invariant (engine.py header; reference doc.go:31-39)
is preserved by GATING, not ordering: appliers may apply a round's
entries before its record is durable (stores are in-memory and die with
the process anyway), but client acks for that round are withheld until
the writer publishes a durability watermark at or past it. A crash
therefore never leaves an acked write above the replayable boundary.

Sharding (wal_shards=S > 1) splits each RoundRecord by tenant range into
S sub-records appended to S independent segment streams (subdirs
wal-shard-NNNN/), whose fsyncs proceed in parallel on a multi-core box.
Batches are kept in lockstep across streams: a shard with no deltas for
a batch appends an empty marker record at the batch's top round, so
every stream's tail advances with every group commit and the global
durable boundary is simply D = min over streams of the stream tail.
Replay computes D, physically truncates any stream's whole records
beyond it (EngineWAL.cut_after — those rounds lost the cross-stream
commit race and were never acked, but surviving on disk they could
alias reused round numbers after restart), then merges all streams'
records in round order. The S=1 layout is byte-compatible with the
pre-compartment engine WAL (records land in the root dir); upgrading an
existing dir to S>1 freezes the root stream as legacy history and all
new records go to the shard streams — geometry.json pins S thereafter.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from etcd_tpu.server.enginewal import EngineWAL, RoundRecord
from etcd_tpu.server.obs import DURABLE as _FLIGHT_DURABLE

_STATS_WINDOW = 4096   # per-shard rolling sample window for stats()


def shard_dir(root: str, idx: int) -> str:
    return os.path.join(root, f"wal-shard-{idx:04d}")


def split_record(rec: RoundRecord,
                 ranges: List[Tuple[int, int]]
                 ) -> List[Optional[RoundRecord]]:
    """Split one global RoundRecord into per-tenant-range sub-records
    (columns filtered by group id; entries/confs/snaps by their g).
    Ranges with no deltas map to None — the writer coalesces those into
    at most one empty marker per batch. Sub-records replay additively:
    the ranges are disjoint, so applying all of them in any order within
    the round reproduces the global record."""
    out: List[Optional[RoundRecord]] = []
    for lo, hi in ranges:
        sub = RoundRecord(round_no=rec.round_no)
        m = (rec.hs_g >= lo) & (rec.hs_g < hi)
        if m.any():
            sub.hs_g, sub.hs_p = rec.hs_g[m], rec.hs_p[m]
            sub.hs_term, sub.hs_vote = rec.hs_term[m], rec.hs_vote[m]
            sub.hs_commit = rec.hs_commit[m]
        m = (rec.last_g >= lo) & (rec.last_g < hi)
        if m.any():
            sub.last_g, sub.last_p = rec.last_g[m], rec.last_p[m]
            sub.last_v = rec.last_v[m]
        m = (rec.ring_g >= lo) & (rec.ring_g < hi)
        if m.any():
            sub.ring_g, sub.ring_p = rec.ring_g[m], rec.ring_p[m]
            sub.ring_i, sub.ring_t = rec.ring_i[m], rec.ring_t[m]
        sub.entries = [e for e in rec.entries if lo <= e[0] < hi]
        sub.confs = [c for c in rec.confs if lo <= c[0] < hi]
        sub.snaps = [s for s in rec.snaps if lo <= s[0] < hi]
        out.append(None if sub.is_empty() else sub)
    return out


class _WriterShard:
    """One compartment of the writer pool: a thread owning one segment
    stream and the contiguous tenant range [g_lo, g_hi), with its own
    hand-off queue, condition variable, durable-tail publication and
    rolling stats. Streams share no files, so S shards drive S parallel
    fsyncs (each an I/O wait with the GIL released)."""

    __slots__ = ("idx", "g_lo", "g_hi", "wal", "cv", "q", "stop", "exc",
                 "thread", "durable", "fsyncs", "fsync_ms", "batch_sizes")

    def __init__(self, idx: int, g_lo: int, g_hi: int,
                 wal: EngineWAL) -> None:
        self.idx = idx
        self.g_lo = g_lo
        self.g_hi = g_hi
        self.wal = wal
        self.cv = threading.Condition()
        self.q: deque = deque()
        self.stop = False
        self.exc: Optional[Exception] = None
        self.thread: Optional[threading.Thread] = None
        self.durable = 0           # published ticket (guarded by owner._wm)
        self.fsyncs = 0
        self.fsync_ms: deque = deque(maxlen=_STATS_WINDOW)
        self.batch_sizes: deque = deque(maxlen=_STATS_WINDOW)


class WALWriter:
    """The engine's WAL facade: same read/checkpoint surface as
    EngineWAL (replay/load_checkpoint/save_checkpoint/close), with the
    write side compartmentalized behind submit()/wait_durable().

    Synchronous callers (admin surgery, conf rounds, pipeline-off mode)
    use append_sync(), which is submit + wait — the record is durable
    when it returns, exactly the old EngineWAL.append contract."""

    def __init__(self, dirname: str, groups: int, shards: int = 1,
                 segment_size: int = 64 * 1024 * 1024,
                 fsync: bool = True, queue_rounds: int = 64,
                 phase_s: Optional[Dict[str, float]] = None,
                 obs=None) -> None:
        self.dir = dirname
        self.groups = groups
        self.fsync = fsync
        self.queue_rounds = max(1, queue_rounds)
        self.phase_s = phase_s if phase_s is not None else {}
        # Observability plane (obs.EngineObs): per-shard fsync/group-
        # commit histograms, queue-depth + watermark-lag gauges, flight
        # recorder durable marks. None (or disabled) = zero overhead.
        self._obs = obs if (obs is not None and obs.enabled) else None
        S = max(1, min(shards, groups))
        # Root stream: THE stream at S=1 (byte-compatible with the
        # pre-compartment layout), checkpoint store + frozen legacy
        # history at S>1.
        self.root = EngineWAL(dirname, segment_size=segment_size,
                              fsync=fsync)
        per = -(-groups // S)
        ranges = [(min(k * per, groups), min((k + 1) * per, groups))
                  for k in range(S)]
        ranges = [(lo, hi) for lo, hi in ranges if lo < hi]
        if len(ranges) == 1:
            streams = [self.root]
        else:
            streams = [EngineWAL(shard_dir(dirname, k),
                                 segment_size=segment_size, fsync=fsync)
                       for k in range(len(ranges))]
        self.shards = [_WriterShard(k, lo, hi, w)
                       for k, ((lo, hi), w) in enumerate(zip(ranges,
                                                             streams))]
        self._ranges = ranges
        # Watermark: tickets are a monotonic SUBMISSION sequence (not
        # round numbers — an admin record and the round's own record can
        # share a round_no, and a round-numbered watermark would release
        # the second record's acks on the first record's fsync). The
        # published watermark is min over shards of the last completed
        # batch's ticket; waiters block on it. The on-disk replay
        # boundary stays round-based (stream tails), which is what a
        # restart can actually observe.
        self._wm = threading.Condition()
        self._durable = 0
        self._last_ticket = 0
        self._depths: deque = deque(maxlen=_STATS_WINDOW)
        self._submitted = 0
        self._closed = False

    # -- write side ---------------------------------------------------------

    @property
    def ticket(self) -> int:
        """Ticket of the newest submitted record — what a commit view
        carries so ack release can gate on wait_durable(). Commit
        advance always rides a non-empty (hence submitted) record, so
        gating on the last submitted ticket covers every ackable entry;
        empty rounds never move it (nothing new to ack)."""
        return self._last_ticket

    def _ensure_threads(self) -> None:
        for sh in self.shards:
            t = sh.thread
            if t is None or not t.is_alive():
                if sh.exc is not None:
                    continue   # terminally failed: the seams re-raise
                sh.stop = False
                sh.thread = threading.Thread(
                    target=self._writer_loop, args=(sh,), daemon=True,
                    name=f"engine-wal-writer-{sh.idx}")
                sh.thread.start()
        self._closed = False

    def _writer_loop(self, sh: _WriterShard) -> None:
        # Phase key: "wal_fsync" for the single-stream writer (keeps
        # profiles comparable with pre-compartment captures),
        # "wal_fsync[k]" per stream otherwise — one writer thread per
        # key. This is also where the fsync phase time is RECORDED now:
        # it happens here, not in the round loop, so the per-phase
        # profile stays truthful with fsync off the critical path.
        pkey = ("wal_fsync" if len(self.shards) == 1
                else f"wal_fsync[{sh.idx}]")
        sharded = len(self.shards) > 1
        while True:
            with sh.cv:
                while not sh.q and not sh.stop:
                    sh.cv.wait(0.2)
                if not sh.q:
                    return          # stop requested and queue drained
                batch = list(sh.q)
                sh.q.clear()
                sh.cv.notify_all()  # unblock submit() backpressure NOW:
                # the round loop refills while this batch fsyncs
            t0 = time.perf_counter()
            try:
                for _, _, sub in batch:
                    if sub is not None:
                        sh.wal.append_nosync(sub)
                top_ticket, top_round = batch[-1][0], batch[-1][1]
                if sharded and batch[-1][2] is None:
                    # Keep stream tails in lockstep at batch granularity:
                    # an empty marker advances this stream's tail to the
                    # batch's top round so the min-over-streams boundary
                    # never stalls on a range with no deltas. At most one
                    # marker per group commit.
                    sh.wal.append_nosync(RoundRecord(round_no=top_round))
                sh.wal.sync()       # ONE fsync covers the whole batch
            except Exception as e:  # noqa: BLE001 — re-raised at the seam
                if self._obs is not None:
                    # A writer-shard fail-stop kills the whole
                    # durability pipeline: dump the round timeline.
                    self._obs.flight.dump(self.dir,
                                          f"wal-shard-{sh.idx}")
                with sh.cv:
                    sh.exc = e
                    sh.cv.notify_all()
                with self._wm:
                    self._wm.notify_all()   # wake waiters to observe exc
                return
            dt = time.perf_counter() - t0
            self.phase_s[pkey] = self.phase_s.get(pkey, 0.0) + dt
            sh.fsyncs += 1
            sh.fsync_ms.append(dt * 1000.0)
            sh.batch_sizes.append(len(batch))
            ob = self._obs
            if ob is not None:
                ob.h_wal_fsync[sh.idx].observe(dt)
                ob.h_wal_commit[sh.idx].observe(len(batch))
                for _t, rnd, _sub in batch:
                    ob.flight.mark(rnd, _FLIGHT_DURABLE)
            with self._wm:
                sh.durable = top_ticket
                d = min(s.durable for s in self.shards)
                if d > self._durable:
                    self._durable = d
                    self._wm.notify_all()
            if ob is not None:
                ob.g_wal_lag.set(self._last_ticket - self._durable)

    def submit(self, rec: RoundRecord) -> int:
        """Queue one round's record for durability and return its ticket
        (a monotonic submission sequence number). Blocks while any
        shard's queue is at the cap (bounds ack latency: a deeper queue
        means a bigger group commit, not unbounded lag). The caller must
        not ack anything the record covers before wait_durable(ticket)
        returns."""
        self._ensure_threads()
        subs = (split_record(rec, self._ranges)
                if len(self.shards) > 1 else [rec])
        ticket = self._last_ticket + 1
        for sh, sub in zip(self.shards, subs):
            with sh.cv:
                while (len(sh.q) >= self.queue_rounds
                       and sh.exc is None and not sh.stop):
                    sh.cv.wait(0.5)
                if sh.exc is None:
                    sh.q.append((ticket, rec.round_no, sub))
                    self._depths.append(len(sh.q))
                    if self._obs is not None:
                        self._obs.g_wal_queue[sh.idx].set(len(sh.q))
                    sh.cv.notify_all()
        self._raise_exc()
        self._submitted += 1
        self._last_ticket = ticket
        return ticket

    def wait_durable(self, ticket: int) -> None:
        """Block until the published durability watermark covers
        `ticket` (every record submitted at or before it is fsynced on
        every stream). The ack-gating half of the crash-ordering
        invariant."""
        if ticket <= self._durable:   # racy read is safe: monotonic
            return
        with self._wm:
            while self._durable < ticket:
                if any(sh.exc is not None for sh in self.shards):
                    break
                self._wm.wait(0.2)
        self._raise_exc()

    def flush(self) -> None:
        """Barrier: every submitted record durable."""
        self.wait_durable(self._last_ticket)

    def append_sync(self, rec: RoundRecord) -> None:
        """Submit + wait: durable when this returns (the old inline
        EngineWAL.append contract, used by the synchronous paths — admin
        surgery, conf rounds, pipeline-off mode)."""
        self.wait_durable(self.submit(rec))

    def _raise_exc(self) -> None:
        # sh.exc stays set: a failed writer shard is terminally failed
        # (never respawned — a retry would re-append around a hole), so
        # every later seam re-raises.
        for sh in self.shards:
            if sh.exc is not None:
                raise sh.exc

    def close(self) -> None:
        """Drain queues (final group commit per stream), stop the writer
        threads, close the streams. Idempotent; swallows nothing — a
        failed shard's error stays set and the next seam raises it."""
        for sh in self.shards:
            with sh.cv:
                sh.stop = True
                sh.cv.notify_all()
        for sh in self.shards:
            if sh.thread is not None:
                sh.thread.join(timeout=10)
        for sh in self.shards:
            sh.wal.close()
        self.root.close()
        self._closed = True

    # -- read side ----------------------------------------------------------

    def replay(self, after_round: int = -1) -> Iterator[RoundRecord]:
        """Yield whole records with round_no > after_round, merged across
        streams in round order, up to the consistent durable boundary.
        Positions every stream's appender; physically cuts records
        beyond the boundary (see module docstring)."""
        if len(self.shards) == 1:
            yield from self.root.replay(after_round)
            return
        root_recs = list(self.root.replay(after_round))
        per: List[List[RoundRecord]] = []
        for sh in self.shards:
            per.append(list(sh.wal.replay(after_round)))
        # A stream with no surviving records is complete through the
        # checkpoint round (checkpoints flush the writer first and purge
        # only covered segments) — never through less.
        tails = [max(sh.wal.last_round, after_round) for sh in self.shards]
        boundary = min(tails)
        for sh in self.shards:
            if sh.wal.last_round > boundary:
                sh.wal.cut_after(boundary)
        recs = root_recs + [r for rl in per for r in rl
                            if r.round_no <= boundary]
        recs.sort(key=lambda r: r.round_no)
        yield from recs

    def load_checkpoint(self) -> Tuple[int, Optional[dict]]:
        return self.root.load_checkpoint()

    def save_checkpoint(self, round_no: int, state: dict) -> None:
        """Flush the pipeline (checkpoint state must not lead the log —
        a crash right after the checkpoint lands must find every round
        it covers on disk), persist via the root stream, then purge all
        streams against the same fallback round."""
        self.flush()
        fallback = self.root.save_checkpoint(round_no, state)
        if self.shards[0].wal is not self.root:
            for sh in self.shards:
                sh.wal.purge_segments(fallback)

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Rolling writer-compartment profile for bench.py: fsync
        latency percentiles (per group commit, measured IN the writer
        thread), group-commit batch sizes, and the submit-side queue
        depth the round loop observed."""
        fs = [v for sh in self.shards for v in sh.fsync_ms]
        bs = [v for sh in self.shards for v in sh.batch_sizes]
        dep = list(self._depths)

        def pct(a, q):
            return round(float(np.percentile(a, q)), 3) if a else None

        return {
            "wal_shards": len(self.shards),
            "wal_rounds_submitted": self._submitted,
            "wal_group_commits": sum(sh.fsyncs for sh in self.shards),
            "wal_fsync_p50_ms": pct(fs, 50),
            "wal_fsync_p99_ms": pct(fs, 99),
            "wal_group_commit_mean": (round(sum(bs) / len(bs), 2)
                                      if bs else None),
            "wal_group_commit_max": (max(bs) if bs else None),
            "wal_queue_depth_p50": pct(dep, 50),
            "wal_queue_depth_max": (max(dep) if dep else None),
        }
