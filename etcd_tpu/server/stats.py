"""Server / leader statistics served under /v2/stats/*.

Behavioral equivalent of reference etcdserver/stats/: ServerStats with
send/recv package+bandwidth rates over a sliding window of recent requests
(stats/queue.go:33-41 statsQueue), and LeaderStats tracking per-follower
append latency mean/stddev and success/fail counts (stats/leader.go:68-123).
Thread-safe: the transport and the run loop both report into these.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple

_QUEUE_CAP = 200  # reference stats/queue.go queueCapacity


class _RateQueue:
    """Ring of (timestamp, size) samples; rate = totals / time-span
    (reference statsQueue.Rate)."""

    def __init__(self) -> None:
        self._items: List[Tuple[float, int]] = []

    def insert(self, size: int, now: float) -> None:
        self._items.append((now, size))
        if len(self._items) > _QUEUE_CAP:
            self._items.pop(0)

    def rate(self, now: float) -> Tuple[float, float]:
        """(packages/sec, bytes/sec) over the retained window; zero once the
        newest sample is over a minute old (reference queue.go:62-74)."""
        if not self._items:
            return 0.0, 0.0
        first, last = self._items[0][0], self._items[-1][0]
        if now - last > 60.0:
            return 0.0, 0.0
        span = last - first
        if span <= 0:
            return 0.0, 0.0
        n = len(self._items)
        total = sum(sz for _, sz in self._items)
        return n / span, total / span


class ServerStats:
    """Payload of /v2/stats/self (reference stats/server.go)."""

    def __init__(self, name: str, mid: int, clock=time.time) -> None:
        self._lock = threading.Lock()
        self.name = name
        self.id = mid
        self.clock = clock
        self.state = "StateFollower"
        self.start_time = clock()
        self.leader = 0
        self.leader_start = 0.0
        self.recv_append_cnt = 0
        self.send_append_cnt = 0
        self._recvq = _RateQueue()
        self._sendq = _RateQueue()

    def become_leader(self) -> None:
        with self._lock:
            if self.state != "StateLeader":
                self.state = "StateLeader"
                self.leader = self.id
                self.leader_start = self.clock()

    def become_follower(self, leader: int) -> None:
        with self._lock:
            self.state = "StateFollower"
            if leader != self.leader:
                self.leader = leader
                self.leader_start = self.clock()

    def recv_append_req(self, leader: int, size: int) -> None:
        with self._lock:
            self.state = "StateFollower"
            if leader != self.leader:
                self.leader = leader
                self.leader_start = self.clock()
            self.recv_append_cnt += 1
            self._recvq.insert(size, self.clock())

    def send_append_req(self, size: int) -> None:
        with self._lock:
            self.send_append_cnt += 1
            self._sendq.insert(size, self.clock())

    def to_dict(self) -> dict:
        from etcd_tpu.store.event import format_expiration
        with self._lock:
            now = self.clock()
            rpkg, rbw = self._recvq.rate(now)
            spkg, sbw = self._sendq.rate(now)
            d = {
                "name": self.name,
                "id": f"{self.id:x}",
                "state": self.state,
                "startTime": format_expiration(self.start_time),
                "leaderInfo": {
                    "leader": f"{self.leader:x}",
                    "uptime": f"{now - self.leader_start:.6f}s"
                              if self.leader_start else "0s",
                    "startTime": format_expiration(self.leader_start)
                                 if self.leader_start else
                                 format_expiration(self.start_time),
                },
                "recvAppendRequestCnt": self.recv_append_cnt,
                "sendAppendRequestCnt": self.send_append_cnt,
            }
            if rpkg:
                d["recvPkgRate"] = rpkg
                d["recvBandwidthRate"] = rbw
            if spkg:
                d["sendPkgRate"] = spkg
                d["sendBandwidthRate"] = sbw
            return d


class _FollowerStats:
    """Latency + counts for one follower (reference stats/leader.go:68-123);
    streaming mean/stddev via Welford-style accumulation."""

    def __init__(self) -> None:
        self.success = 0
        self.fail = 0
        self.current = 0.0
        self.minimum = math.inf
        self.maximum = 0.0
        self._sum = 0.0
        self._sq_sum = 0.0

    def succ(self, ms: float) -> None:
        self.success += 1
        self.current = ms
        self.minimum = min(self.minimum, ms)
        self.maximum = max(self.maximum, ms)
        self._sum += ms
        self._sq_sum += ms * ms

    def failed(self) -> None:
        self.fail += 1

    def to_dict(self) -> dict:
        n = self.success
        avg = self._sum / n if n else 0.0
        var = self._sq_sum / n - avg * avg if n else 0.0
        return {
            "latency": {
                "current": self.current,
                "average": avg,
                "standardDeviation": math.sqrt(max(var, 0.0)),
                "minimum": 0.0 if self.minimum is math.inf else self.minimum,
                "maximum": self.maximum,
            },
            "counts": {"fail": self.fail, "success": self.success},
        }


class LeaderStats:
    """Payload of /v2/stats/leader (reference stats/leader.go)."""

    def __init__(self, mid: int) -> None:
        self._lock = threading.Lock()
        self.id = mid
        self._followers: Dict[int, _FollowerStats] = {}

    def follower(self, fid: int) -> _FollowerStats:
        with self._lock:
            fs = self._followers.get(fid)
            if fs is None:
                fs = self._followers[fid] = _FollowerStats()
            return fs

    def succ(self, fid: int, ms: float) -> None:
        with self._lock:
            fs = self._followers.setdefault(fid, _FollowerStats())
            fs.succ(ms)

    def failed(self, fid: int) -> None:
        with self._lock:
            fs = self._followers.setdefault(fid, _FollowerStats())
            fs.failed()

    def remove(self, fid: int) -> None:
        with self._lock:
            self._followers.pop(fid, None)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "leader": f"{self.id:x}",
                "followers": {f"{fid:x}": fs.to_dict()
                              for fid, fs in self._followers.items()},
            }
