"""Binary upstream framing between the ingress tier and the engine.

The round-10 ingress shipped every coalesced flush as a JSON POST to
/tenants/{t}/batch over a one-request-at-a-time http.client connection:
encode the whole window as a JSON array, wait for the full response,
decode it, repeat. That hop was the serial clock of the tier — the
engine idled between flushes and every byte crossed two JSON codecs.

This module defines the replacement: a persistent per-lane channel that
HANDSHAKES as HTTP (one POST /tenants/{t}/batchframe answered with
101 Switching Protocols, so it traverses the same listener, router and
auth surface as every other tenant path) and then speaks length-prefixed
binary frames both ways, WINDOWED — up to IngressConfig.flush_window
request frames may be in flight before the first response frame returns,
demultiplexed by flush id.

Wire format (all integers little-endian):

  request frame (ingress -> engine):
      u32  frame_len          bytes after this field
      u64  flush_id           channel-unique; echoes in the response
      u32  auth_len           0 when no slot carries credentials
      .... auth_json          JSON list[str|null], one per slot
      .... payload            P_MULTI blob: 0x02, u32 count,
                              (u32 len, item JSON)* — packed by ONE
                              walcodec.pack_multi call; the engine
                              unpacks it with the same struct walk the
                              WAL replay path uses (engine._unpack_multi)

  response frame (engine -> ingress):
      u32  frame_len
      u64  flush_id
      u32  count              0xFFFFFFFF = frame-level error, then ONE
                              (u32 status, u32 len, body) follows and
                              every rider of the flush receives it
      then count * (u32 status, u32 len, body) — body is the FINAL
      client-facing HTTP response body for that slot, pre-serialized by
      the engine so the ingress fan-back does zero per-request JSON work

The slot payload is the item-dict JSON of the /batch route (NOT an
encoded Request): TTLs must resolve against the ENGINE's clock and
request ids are assigned engine-side, exactly as on the JSON path — the
frame saves the outer array codec, the per-flush connection churn and
the response assembly, not the per-slot schema.
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

UPGRADE_NAME = "etcd-batchframe"
FRAME_ERROR = 0xFFFFFFFF
MAX_FRAME = 64 * 1024 * 1024     # allocation cap; flushes are ~1 MB
# Mirror of server/engine.P_MULTI (the payload tag of a multi-request
# log entry) so the ingress process can pack frames without importing
# the engine; tests/test_do_many.py pins the equality.
P_MULTI = 0x02

_U32 = struct.Struct("<I")
_HDR = struct.Struct("<QI")      # flush_id, auth_len | count
_SLOT = struct.Struct("<II")     # status, body_len


# ---------------------------------------------------------------------------
# handshake
# ---------------------------------------------------------------------------

def handshake_request(tenant: int, host: str) -> bytes:
    return (f"POST /tenants/{tenant}/batchframe HTTP/1.1\r\n"
            f"Host: {host}\r\n"
            f"Upgrade: {UPGRADE_NAME}\r\n"
            f"Connection: Upgrade\r\n"
            f"Content-Length: 0\r\n\r\n").encode()


def handshake_response() -> bytes:
    return (f"HTTP/1.1 101 Switching Protocols\r\n"
            f"Upgrade: {UPGRADE_NAME}\r\n"
            f"Connection: Upgrade\r\n\r\n").encode()


def read_handshake_status(rfile) -> int:
    """Read the engine's handshake reply head; returns the HTTP status
    (101 = channel open; anything else = endpoint absent/refused, the
    caller falls back to the JSON path). Raises OSError on EOF."""
    status = None
    while True:
        line = rfile.readline(8192)
        if not line:
            raise OSError("upstream closed during batchframe handshake")
        if status is None:
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise OSError(f"bad handshake status line {line!r}")
            status = int(parts[1])
        if line in (b"\r\n", b"\n"):
            return status


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------

def pack_request_frame(flush_id: int, auth_json: bytes,
                       payload: bytes) -> bytes:
    body = _HDR.pack(flush_id, len(auth_json))
    return (_U32.pack(len(body) + len(auth_json) + len(payload))
            + body + auth_json + payload)


def pack_response_frame(flush_id: int,
                        slots: List[Tuple[int, bytes]]) -> bytes:
    parts = [_HDR.pack(flush_id, len(slots))]
    for status, body in slots:
        parts.append(_SLOT.pack(status, len(body)))
        parts.append(body)
    blob = b"".join(parts)
    return _U32.pack(len(blob)) + blob


def pack_error_frame(flush_id: int, status: int, body: bytes) -> bytes:
    blob = (_HDR.pack(flush_id, FRAME_ERROR)
            + _SLOT.pack(status, len(body)) + body)
    return _U32.pack(len(blob)) + blob


def _read_exact(rfile, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    out = b""
    while len(out) < n:
        chunk = rfile.read(n - len(out))
        if not chunk:
            if not out:
                return None
            raise OSError("truncated batchframe")
        out += chunk
    return out


def read_request_frame(rfile) -> Optional[Tuple[int, bytes, bytes]]:
    """-> (flush_id, auth_json, payload) or None on clean EOF."""
    hdr = _read_exact(rfile, 4)
    if hdr is None:
        return None
    (ln,) = _U32.unpack(hdr)
    if ln > MAX_FRAME or ln < _HDR.size:
        raise OSError(f"bad batchframe length {ln}")
    blob = _read_exact(rfile, ln)
    if blob is None or len(blob) != ln:
        raise OSError("truncated batchframe")
    flush_id, auth_len = _HDR.unpack_from(blob, 0)
    off = _HDR.size
    if auth_len > ln - off:
        raise OSError("bad batchframe auth length")
    auth_json = blob[off:off + auth_len]
    return flush_id, auth_json, blob[off + auth_len:]


def read_response_frame(rfile
                        ) -> Optional[Tuple[int, Optional[list], tuple]]:
    """-> (flush_id, slots, error) or None on clean EOF; exactly one of
    slots ([(status, body)]) / error ((status, body)) is set."""
    hdr = _read_exact(rfile, 4)
    if hdr is None:
        return None
    (ln,) = _U32.unpack(hdr)
    if ln > MAX_FRAME or ln < _HDR.size:
        raise OSError(f"bad batchframe length {ln}")
    blob = _read_exact(rfile, ln)
    if blob is None or len(blob) != ln:
        raise OSError("truncated batchframe")
    flush_id, count = _HDR.unpack_from(blob, 0)
    off = _HDR.size
    if count == FRAME_ERROR:
        status, blen = _SLOT.unpack_from(blob, off)
        off += _SLOT.size
        return flush_id, None, (status, blob[off:off + blen])
    slots = []
    for _ in range(count):
        if off + _SLOT.size > ln:
            raise OSError("truncated batchframe slot")
        status, blen = _SLOT.unpack_from(blob, off)
        off += _SLOT.size
        if off + blen > ln:
            raise OSError("truncated batchframe slot body")
        slots.append((status, blob[off:off + blen]))
        off += blen
    return flush_id, slots, ()
