"""EtcdServer: the server core wiring consensus to the state machine.

Behavioral equivalent of reference etcdserver/server.go + etcdserver/raft.go:
bootstrap decision tree (new vs restart), the propose→wait→apply pipeline
(Do server.go:519-576, apply server.go:729-820), membership ConfChanges with
validation (server.go:640-662,824-873), snapshot trigger every snap_count
applies (server.go:476-480,876-916), TTL expiry via replicated SYNC
(server.go:667-681), and self-attribute publish (server.go:688-715).

Re-designed for the TPU framework: ONE run-loop thread owns the Node and all
store mutations (the single-writer invariant the reference gets from
node.run/multiNode.run goroutines), fed by a queue that client threads
(HTTP handlers) and the transport post into. The Ready drain follows the
prescribed ordering contract (reference raft/doc.go:28-55): WAL fsync of
{HardState, Entries} BEFORE transport send, apply committed, then advance.
"""
from __future__ import annotations

import json
import logging
import os
import queue
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from etcd_tpu import errors, raftpb
from etcd_tpu.raftpb import (ConfChange, ConfChangeType, ConfState, Entry,
                             EntryType, Message, MessageType, Snapshot,
                             SnapshotMetadata)
from etcd_tpu.raft.core import Config, ProposalDroppedError
from etcd_tpu.raft.node import Node, Peer
from etcd_tpu.raft.storage import CompactedError, MemoryStorage
from etcd_tpu.server import cluster as cl
from etcd_tpu.server.cluster import Cluster, Member, STORE_KEYS_PREFIX
from etcd_tpu.server.request import (METHOD_DELETE, METHOD_GET, METHOD_POST,
                                     METHOD_PUT, METHOD_QGET, METHOD_SYNC,
                                     METHOD_V3,
                                     Request)
from etcd_tpu.server.stats import LeaderStats, ServerStats
from etcd_tpu.server.storage import ServerStorage, read_wal
from etcd_tpu.store.event import LazyWriteEvent
from etcd_tpu.server.transport import Transporter
from etcd_tpu.snap import Snapshotter
from etcd_tpu.store import new_store
from etcd_tpu.utils import idutil, metrics
from etcd_tpu.utils.fileutil import touch_dir_all, purge_files
from etcd_tpu.utils.wait import Wait
from etcd_tpu.wal import WAL, WalSnapshot, wal_exists
from etcd_tpu.wal import wal as wal_mod

log = logging.getLogger("etcd_tpu.server")

DEFAULT_SNAP_COUNT = 10000       # reference server.go:56
CATCH_UP_ENTRIES = 5000          # reference etcdserver/raft.go:38
MAX_WAL_FILES = 5                # reference -max-wals default
MAX_SNAP_FILES = 5

_MEMBER_ATTR_SUFFIX = "/attributes"

# Snapshot payload envelope carrying BOTH state machines. Legacy snapshots
# (and the reference's) are bare v2-store JSON — the magic disambiguates:
# JSON can never start with these bytes. v3's consistent index travels
# inside the sqlite image itself.
_SNAP_MAGIC = b"\x00etcdtpu-snap-v3\x00"
_SNAP_HDR = struct.Struct("<QQ")


def _encode_snap_data(v2: bytes, v3: bytes) -> bytes:
    return _SNAP_MAGIC + _SNAP_HDR.pack(len(v2), len(v3)) + v2 + v3


def _decode_snap_data(data: bytes):
    """-> (v2_json, v3_image_or_None)."""
    if not data.startswith(_SNAP_MAGIC):
        return data, None
    l2, l3 = _SNAP_HDR.unpack_from(data, len(_SNAP_MAGIC))
    off = len(_SNAP_MAGIC) + _SNAP_HDR.size
    return data[off:off + l2], data[off + l2:off + l2 + l3]


@dataclass
class ServerConfig:
    name: str
    data_dir: str
    initial_cluster: Dict[str, Sequence[str]] = field(default_factory=dict)
    cluster_token: str = "etcd-cluster"
    client_urls: Tuple[str, ...] = ()
    snap_count: int = DEFAULT_SNAP_COUNT
    tick_ms: int = 100               # heartbeat interval (reference TickMs)
    election_ticks: int = 10
    heartbeat_ticks: int = 1
    sync_ticks: int = 5              # SYNC every 500ms (reference server.go:300)
    wal_segment_size: int = wal_mod.SEGMENT_SIZE_BYTES
    request_timeout: float = 5.0
    catch_up_entries: int = CATCH_UP_ENTRIES
    # False = join an existing cluster: fetch membership + IDs from the
    # peers in initial_cluster instead of founding (reference
    # server.go:194-217 `!haveWAL && !cfg.NewCluster`).
    new_cluster: bool = True
    # Continuous cluster-version negotiation cadence (reference
    # monitorVersionInterval, server.go:933). Winning leadership forces an
    # immediate round, so the initial negotiation never waits on this.
    version_monitor_interval: float = 5.0
    # Disaster recovery: restart as a one-member cluster, rewriting
    # membership in the log (reference -force-new-cluster,
    # etcdserver/raft.go:266-315).
    force_new_cluster: bool = False

    @property
    def waldir(self) -> str:
        return os.path.join(self.data_dir, "member", "wal")

    @property
    def snapdir(self) -> str:
        return os.path.join(self.data_dir, "member", "snap")


class EtcdServer:
    """One consensus member. Drive with start()/stop(); serve client ops via
    do()/add_member()/remove_member(); feed peer traffic into process()."""

    def __init__(self, cfg: ServerConfig, transport: Transporter,
                 clock=time.time) -> None:
        self.cfg = cfg
        self.clock = clock
        self.transport = transport
        if hasattr(transport, "bind"):
            transport.bind(self)
        # Namespace dirs exist from boot and are write-protected (reference
        # server.go:173 store.New(StoreClusterPrefix, StoreKeysPrefix)).
        self.store = new_store(clock=clock,
                           namespaces=(cl.STORE_CLUSTER_PREFIX,
                                       STORE_KEYS_PREFIX))
        touch_dir_all(cfg.snapdir)
        self.snapshotter = Snapshotter(cfg.snapdir)
        self.raft_storage = MemoryStorage()
        # v3 MVCC preview keyspace (server/v3.py): replicated through the
        # same log; per-member sqlite backend under member/v3.
        from etcd_tpu.server.v3 import V3Applier
        touch_dir_all(os.path.join(cfg.data_dir, "member", "v3"))
        self.v3 = V3Applier(
            os.path.join(cfg.data_dir, "member", "v3", "kv.db"))
        # Set when a LEGACY snapshot (no v3 image) installed past the v3
        # consistent index: the v3 keyspace has a gap and must not serve.
        self.v3_gapped = False
        self._applied = 0
        self._snapi = 0
        self.wait = Wait()
        self._inq: "queue.Queue[Tuple[str, Any]]" = queue.Queue()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._published = False
        self._removed_self = False
        # Set when an environmental apply failure killed the run loop: the
        # member must refuse all service (reads could see forked in-memory
        # state) until restarted — the process-level analogue of the
        # reference's panic-on-backend-error.
        self._fatal = False
        # Leader-local lease bookkeeping: lease_id -> (seq, clock time the
        # LEADER observed that seq). Replicated lease state carries no
        # clocks; only this member's clock decides expiry, re-seeded on
        # every leadership change (leases extend across elections, never
        # silently shorten). _lease_revoke_inflight dedups re-proposals.
        self._was_leader = False
        self._lease_seen: Dict[int, Tuple[int, float]] = {}
        self._lease_revoke_inflight: Dict[int, float] = {}
        self._sync_elapsed = 0
        self.lead_elected_ev = threading.Event()
        self._force_version_ev = threading.Event()  # reference forceVersionC
        self._version_thread: Optional[threading.Thread] = None

        # v0.4 data dirs auto-upgrade on boot (reference upgradeDataDir
        # chain, etcdserver/storage.go:111-132 + server.go:181-187).
        if not wal_exists(cfg.waldir):
            from etcd_tpu.migrate import etcd4 as migrate4
            if migrate4.is_v04_data_dir(cfg.data_dir):
                migrate4.migrate_4_to_2(cfg.data_dir, cfg.name)

        if wal_exists(cfg.waldir):
            if cfg.force_new_cluster:
                self._restart_standalone()
            else:
                self._restart()
        elif cfg.new_cluster:
            self._bootstrap_new()
        else:
            self._bootstrap_join()
        self.reqid = idutil.Generator(self.id & 0xFFFF)
        self.stats = ServerStats(cfg.name, self.id, clock=clock)
        self.lstats = LeaderStats(self.id)

        # Wire known peers into the transport.
        for m in self.cluster.members():
            if m.id != self.id:
                self.transport.add_peer(m.id, m.peer_urls)

    # -- bootstrap ----------------------------------------------------------

    def _bootstrap_new(self) -> None:
        cfg = self.cfg
        self.cluster = Cluster.from_initial(self.store, cfg.initial_cluster,
                                            cfg.cluster_token)
        me = self.cluster.member_by_name(cfg.name)
        if me is None:
            raise ValueError(
                f"member {cfg.name!r} not in initial cluster "
                f"{sorted(cfg.initial_cluster)}")
        if cfg.client_urls:
            me = Member(me.id, me.name, me.peer_urls, tuple(cfg.client_urls))
        self.id = me.id
        metadata = json.dumps({"id": f"{self.id:x}",
                               "clusterId": f"{self.cluster.cluster_id:x}"}
                              ).encode()
        self.wal = WAL.create(cfg.waldir, metadata,
                              segment_size=cfg.wal_segment_size)
        self.storage = ServerStorage(self.wal, self.snapshotter)
        peers = [Peer(id=m.id, context=json.dumps(m.to_dict()).encode())
                 for m in self.cluster.members()]
        self.node = Node.start(
            Config(id=self.id, election_tick=cfg.election_ticks,
                   heartbeat_tick=cfg.heartbeat_ticks,
                   storage=self.raft_storage), peers)

    def _bootstrap_join(self) -> None:
        """Join a running cluster (reference server.go:194-217): the admin
        already proposed this member via the members API; fetch the live
        membership from the other peers, take over their IDs (matched by
        peer URLs), and start with an empty log — history replays from the
        leader (appends or a snapshot)."""
        cfg = self.cfg
        local = Cluster.from_initial(self.store, cfg.initial_cluster,
                                     cfg.cluster_token)
        me = local.member_by_name(cfg.name)
        if me is None:
            raise ValueError(
                f"member {cfg.name!r} not in initial cluster "
                f"{sorted(cfg.initial_cluster)}")
        remote_urls = [u for name, urls in cfg.initial_cluster.items()
                       if name != cfg.name for u in urls]
        cid, existing = cl.get_cluster_from_remote_peers(
            remote_urls,
            tls_context=getattr(self.transport, "tls_context", None))
        cl.validate_cluster_and_assign_ids(local, existing)
        local.cluster_id = cid
        self.cluster = local
        me = self.cluster.member_by_name(cfg.name)
        if cfg.client_urls:
            self.cluster._members[me.id] = Member(
                me.id, me.name, me.peer_urls, tuple(cfg.client_urls))
        self.id = me.id
        metadata = json.dumps({"id": f"{self.id:x}",
                               "clusterId": f"{cid:x}"}).encode()
        self.wal = WAL.create(cfg.waldir, metadata,
                              segment_size=cfg.wal_segment_size)
        self.storage = ServerStorage(self.wal, self.snapshotter)
        # No bootstrap peers: membership arrives from the log
        # (reference startNode(cfg, cl, nil)).
        self.node = Node.start(
            Config(id=self.id, election_tick=cfg.election_ticks,
                   heartbeat_tick=cfg.heartbeat_ticks,
                   storage=self.raft_storage), peers=[])

    def _recover_from_disk(self):
        """Shared restart preamble: snapshot → store/raft-storage recovery,
        cluster from store, WAL replay, identity from WAL metadata. Returns
        (snap, hard_state, entries)."""
        cfg = self.cfg
        snap = self.snapshotter.load_or_none()
        walsnap = WalSnapshot()
        if snap is not None:
            walsnap = WalSnapshot(index=snap.metadata.index,
                                  term=snap.metadata.term)
            v2, v3img = _decode_snap_data(snap.data)
            self.store.recovery(v2)
            # The local v3 backend is usually AT or PAST the snapshot (it
            # persists independently); only install the snapshot's image
            # when the backend is behind it (lost/stale db file) — WAL
            # replay then idempotently reapplies from the image forward.
            if self.v3.consistent_index < snap.metadata.index:
                self._install_v3_from_snap(v3img, snap.metadata.index)
            self.raft_storage.apply_snapshot(snap)
            self._applied = snap.metadata.index
            self._snapi = snap.metadata.index
        self.cluster = Cluster(self.store, cfg.cluster_token)
        self.cluster.recover()
        self.wal, metadata, hs, ents = read_wal(
            cfg.waldir, walsnap, segment_size=cfg.wal_segment_size)
        md = json.loads(metadata.decode())
        self.id = int(md["id"], 16)
        self.cluster.cluster_id = int(md["clusterId"], 16)
        return snap, hs, ents

    def _restart_standalone(self) -> None:
        """-force-new-cluster (reference restartAsStandaloneNode
        etcdserver/raft.go:266-315): drop uncommitted WAL entries, then
        append synthesized ConfChanges that remove every other member (and
        add self if absent) so the survivor forms a quorum of one."""
        cfg = self.cfg
        snap, hs, ents = self._recover_from_disk()

        # Discard uncommitted tail (raft.go:273-279).
        for i, e in enumerate(ents):
            if e.index > hs.commit:
                ents = ents[:i]
                break

        ids = self._member_ids_from_log(snap, ents)
        to_app = self._create_config_change_ents(
            ids, self.id, hs.term, hs.commit)
        ents = list(ents) + to_app
        self.wal.save(raftpb.HardState(), to_app)
        if ents:
            hs = raftpb.replace(hs, commit=ents[-1].index)

        self.storage = ServerStorage(self.wal, self.snapshotter)
        self.raft_storage.set_hard_state(hs)
        self.raft_storage.append(ents)
        self.node = Node.restart(
            Config(id=self.id, election_tick=cfg.election_ticks,
                   heartbeat_tick=cfg.heartbeat_ticks,
                   storage=self.raft_storage))

    @staticmethod
    def _member_ids_from_log(snap: Optional[Snapshot],
                             ents: Sequence[Entry]) -> List[int]:
        """Membership as of the last committed entry (reference getIDs
        etcdserver/raft.go:322-350)."""
        ids = set()
        if snap is not None:
            ids.update(snap.metadata.conf_state.nodes)
        for e in ents:
            if e.type != EntryType.CONF_CHANGE:
                continue
            cc = raftpb.decode_conf_change(e.data)
            if cc.type == ConfChangeType.ADD_NODE:
                ids.add(cc.node_id)
            elif cc.type == ConfChangeType.REMOVE_NODE:
                ids.discard(cc.node_id)
        return sorted(ids)

    def _create_config_change_ents(self, ids: List[int], self_id: int,
                                   term: int, index: int) -> List[Entry]:
        """Synthesized remove-everyone-else (+add-self) entries (reference
        createConfigChangeEnts etcdserver/raft.go:352-402)."""
        ents: List[Entry] = []
        nxt = index + 1
        found = False
        for mid in ids:
            if mid == self_id:
                found = True
                continue
            cc = ConfChange(type=ConfChangeType.REMOVE_NODE, node_id=mid)
            ents.append(Entry(type=EntryType.CONF_CHANGE, term=term,
                              index=nxt,
                              data=raftpb.encode_conf_change(cc)))
            nxt += 1
        if not found:
            me = self.cluster.member(self_id) or Member(
                self_id, self.cfg.name, ("http://localhost:2380",), ())
            cc = ConfChange(type=ConfChangeType.ADD_NODE, node_id=self_id,
                            context=json.dumps(me.to_dict()).encode())
            ents.append(Entry(type=EntryType.CONF_CHANGE, term=term,
                              index=nxt,
                              data=raftpb.encode_conf_change(cc)))
        return ents

    def _restart(self) -> None:
        cfg = self.cfg
        _, hs, ents = self._recover_from_disk()
        self.storage = ServerStorage(self.wal, self.snapshotter)
        self.raft_storage.set_hard_state(hs)
        self.raft_storage.append(ents)
        self.node = Node.restart(
            Config(id=self.id, election_tick=cfg.election_ticks,
                   heartbeat_tick=cfg.heartbeat_ticks,
                   storage=self.raft_storage))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"etcd-{self.cfg.name}")
        self._thread.start()
        self._version_thread = threading.Thread(
            target=self._monitor_versions, daemon=True,
            name=f"etcd-{self.cfg.name}-vermon")
        self._version_thread.start()

    def stop(self) -> None:
        self._stop_ev.set()
        self._inq.put(("noop", None))
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.transport.stop()
        self.storage.close()
        self.v3.close()

    @property
    def stopped(self) -> bool:
        return self._stop_ev.is_set()

    # -- client API ---------------------------------------------------------

    def do(self, r: Request) -> Any:
        """Serve one request (reference Do server.go:519-576): local reads
        from the store; writes (and quorum reads) through consensus."""
        if self._fatal:
            raise errors.EtcdError(
                errors.ECODE_RAFT_INTERNAL,
                cause="member failed (fatal apply error); restart required")
        if r.method == METHOD_GET:
            if r.quorum:
                r = raftpb.replace(r, method=METHOD_QGET)
            elif r.wait:
                return self.store.watch(r.path, r.recursive, r.stream, r.since)
            else:
                return self.store.get(r.path, r.recursive, r.sorted)
        # (Serializable v3 ranges never reach do(): the gateway reads the
        # local kvstore directly; linearizable ones ride the log below and
        # V3Applier.apply serves them without a consistent-index write.)
        if r.method in (METHOD_PUT, METHOD_POST, METHOD_DELETE, METHOD_QGET,
                        METHOD_SYNC, METHOD_V3):
            if r.id == 0:
                r = raftpb.replace(r, id=self.reqid.next())
            q = self.wait.register(r.id)
            self._inq.put(("prop", (r.id, r.encode())))
            # Proposal metrics (reference server.go:523-527,573-575 +
            # etcdserver/metrics.go).
            metrics.propose_pending.inc()
            t0 = time.perf_counter()
            try:
                result = q.get(timeout=self.cfg.request_timeout)
            except queue.Empty:
                self.wait.cancel(r.id)
                metrics.propose_failed.inc()
                raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                       cause="request timed out",
                                       index=self.store.current_index)
            finally:
                metrics.propose_pending.dec()
            # Only committed proposals feed the latency summary (the
            # reference observes after the successful wait; timeouts would
            # pin the quantiles at the deadline).
            metrics.propose_durations.observe(
                (time.perf_counter() - t0) * 1e3)
            if isinstance(result, errors.EtcdError):
                raise result
            if type(result) is LazyWriteEvent:
                # The apply loop woke us with raw C descriptors; build
                # the Event here, off the run-loop thread.
                return result.resolve()
            return result
        raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                               cause=f"bad method {r.method}")

    def process(self, m: Message) -> None:
        """Inbound raft message from the transport (reference
        server.go:387-404): drop traffic from removed members."""
        if self._stop_ev.is_set() or self.cluster.is_id_removed(m.frm):
            return
        self._inq.put(("msg", m))

    def report_unreachable(self, pid: int) -> None:
        """Transport feedback: peer send failed → leader drops the peer to
        probe mode (reference server.go:399, raft.go:575-581). Thread-safe."""
        self._inq.put(("msg", Message(type=MessageType.UNREACHABLE, frm=pid)))

    def report_snapshot(self, pid: int, ok: bool) -> None:
        """Transport feedback on a snapshot send (reference server.go:403)."""
        self._inq.put(("msg", Message(type=MessageType.SNAP_STATUS, frm=pid,
                                      reject=not ok)))

    # -- membership API (reference configure() server.go:640-662) -----------

    def add_member(self, m: Member) -> List[Member]:
        self.cluster.validate_conf_change("add", m.id, m.peer_urls)
        cc = ConfChange(id=self.reqid.next(), type=ConfChangeType.ADD_NODE,
                        node_id=m.id,
                        context=json.dumps(m.to_dict()).encode())
        return self._configure(cc)

    def remove_member(self, mid: int) -> List[Member]:
        self.cluster.validate_conf_change("remove", mid)
        cc = ConfChange(id=self.reqid.next(),
                        type=ConfChangeType.REMOVE_NODE, node_id=mid)
        return self._configure(cc)

    def update_member(self, m: Member) -> List[Member]:
        self.cluster.validate_conf_change("update", m.id, m.peer_urls)
        cc = ConfChange(id=self.reqid.next(),
                        type=ConfChangeType.UPDATE_NODE, node_id=m.id,
                        context=json.dumps(m.to_dict()).encode())
        return self._configure(cc)

    def _configure(self, cc: ConfChange) -> List[Member]:
        q = self.wait.register(cc.id)
        self._inq.put(("confchange", cc))
        try:
            result = q.get(timeout=self.cfg.request_timeout)
        except queue.Empty:
            self.wait.cancel(cc.id)
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="conf change timed out")
        if isinstance(result, errors.EtcdError):
            raise result
        return result

    # -- introspection ------------------------------------------------------

    @property
    def leader_id(self) -> int:
        return self.node.raft.lead

    def is_leader(self) -> bool:
        return self.leader_id == self.id

    @property
    def applied_index(self) -> int:
        return self._applied

    @property
    def commit_index(self) -> int:
        return self.node.raft.raft_log.committed

    @property
    def term(self) -> int:
        return self.node.raft.term

    def raft_status(self) -> dict:
        """Live raft status JSON for /debug/vars (reference
        etcdserver/raft.go:60-66 expvar + raft/status.go:52-67). Served by
        the run-loop thread to avoid torn reads of live raft state."""
        q: "queue.Queue[dict]" = queue.Queue(maxsize=1)
        self._inq.put(("status", q))
        try:
            return q.get(timeout=self.cfg.request_timeout)
        except queue.Empty:
            return {"error": "status request timed out"}

    # -- run loop -----------------------------------------------------------

    def _run(self) -> None:
        tick_s = self.cfg.tick_ms / 1000.0
        next_tick = time.monotonic() + tick_s
        while not self._stop_ev.is_set():
            timeout = max(next_tick - time.monotonic(), 0.0)
            try:
                kind, payload = self._inq.get(timeout=timeout)
            except queue.Empty:
                kind, payload = "tick", None
            if self._stop_ev.is_set():
                break
            if kind == "tick" or time.monotonic() >= next_tick:
                while time.monotonic() >= next_tick:
                    self.node.tick()
                    next_tick += tick_s
                self._on_tick()
            if kind == "msg":
                self.node.step(payload)
            elif kind == "prop":
                rid, data = payload
                try:
                    self.node.propose(data)
                except ProposalDroppedError:
                    self.wait.trigger(rid, errors.EtcdError(
                        errors.ECODE_LEADER_ELECT, cause="no leader"))
            elif kind == "confchange":
                try:
                    self.node.propose_conf_change(payload)
                except ProposalDroppedError:
                    self.wait.trigger(payload.id, errors.EtcdError(
                        errors.ECODE_LEADER_ELECT, cause="no leader"))
            elif kind == "status":
                # Introspection runs on the owning thread so it never tears
                # a mid-apply view (reference routes Status() through
                # node.run the same way, raft/node.go status channel).
                try:
                    payload.put(self.node.status().to_json())
                except Exception as e:
                    payload.put({"error": str(e)})
            self._process_ready()
            if self._removed_self:
                self._stop_ev.set()

    def cluster_version(self) -> str:
        """The negotiated cluster version served at /version. Continuously
        re-decided by the leader as the MIN of all members' server versions
        (reference monitorVersions server.go:933-973 +
        decideClusterVersion cluster_util.go:142-186)."""
        from etcd_tpu import version as ver
        return self.cluster.version() or ver.MIN_CLUSTER_VERSION

    @staticmethod
    def _ver_tuple(v: str):
        return tuple(int(x) for x in v.split(".")[:3])

    def _get_versions(self) -> Dict[int, Optional[str]]:
        """Each member's server version via the peer transport (so TLS
        clusters negotiate over the same mutual-TLS channel); None when
        unreachable (reference getVersions cluster_util.go:118-137). Self
        answers locally."""
        from etcd_tpu import version as ver
        out: Dict[int, Optional[str]] = {}
        for m in self.cluster.members():
            if m.id == self.id:
                out[m.id] = ver.VERSION
            else:
                out[m.id] = self.transport.member_version(m.id, m.peer_urls)
        return out

    def _decide_cluster_version(self) -> Optional[str]:
        """Min server version across members; None if any member's version
        is unknown (reference decideClusterVersion)."""
        vers = self._get_versions()
        decided = None
        for mid, v in vers.items():
            if v is None:
                return None
            try:
                vt = self._ver_tuple(v)
            except ValueError:
                return None
            if decided is None or vt < self._ver_tuple(decided):
                decided = v
        return decided

    def _monitor_versions(self) -> None:
        """reference monitorVersions server.go:933-973: every interval (or
        immediately on winning leadership), the leader re-decides the
        cluster version and proposes an update when it rises — so mixed-
        version clusters settle on the minimum and upgrades roll forward
        only once every member has upgraded."""
        from etcd_tpu import version as ver
        while not self._stop_ev.is_set():
            self._force_version_ev.wait(self.cfg.version_monitor_interval)
            self._force_version_ev.clear()
            if self._stop_ev.is_set():
                return
            if not self.is_leader():
                continue
            v = self._decide_cluster_version()
            if v is not None:
                v = ".".join(str(x) for x in self._ver_tuple(v)[:2]) + ".0"
            cur = self.cluster.version()
            target = None
            if cur is None:
                # 1. decided version if possible, 2. min cluster version.
                target = v or ver.MIN_CLUSTER_VERSION
            elif v is not None and self._ver_tuple(cur) < self._ver_tuple(v):
                target = v
            if target is not None:
                r = Request(id=self.reqid.next(), method=METHOD_PUT,
                            path=cl.CLUSTER_VERSION_KEY, val=target)
                self._inq.put(("prop", (r.id, r.encode())))

    def _on_tick(self) -> None:
        if self.is_leader():
            self.stats.become_leader()
            if not self.lead_elected_ev.is_set():
                self._force_version_ev.set()   # negotiate immediately
            self.lead_elected_ev.set()
            if not self._was_leader:
                # Fresh leadership: base every lease deadline on THIS
                # clock, treating all as just-renewed (grace window).
                self._was_leader = True
                now = self.clock()
                self._lease_seen = {lid: (seq, now) for lid, seq in
                                    self.v3.lease_seqs().items()}
                self._lease_revoke_inflight.clear()
            self._sync_elapsed += 1
            if (self._sync_elapsed >= self.cfg.sync_ticks):
                self._sync_elapsed = 0
                if self.store.has_ttl_keys():
                    r = Request(id=self.reqid.next(), method=METHOD_SYNC,
                                time=self.clock())
                    try:
                        self.node.propose(r.encode())
                    except ProposalDroppedError:
                        pass
                # v3 lease expiry: the leader's clock decides, the log
                # enacts — the v3 analogue of the SYNC above. A lease is
                # expired when ITS SEQ has not changed for > ttl on this
                # leader's clock; the revoke carries that seq as a fence
                # so a concurrently-committed keepalive wins.
                self._check_lease_expiry()
        elif self.leader_id != raftpb.NO_LEADER:
            self._was_leader = False
            self.stats.become_follower(self.leader_id)
            self.lead_elected_ev.set()
        if not self._published and self.leader_id != raftpb.NO_LEADER:
            self._publish()

    def _check_lease_expiry(self) -> None:
        """Leader-only: compare each lease's renewal seq against the last
        observation on this clock; propose ONE fenced revoke per expiry
        (re-proposed only after a cool-off, in case the first is lost)."""
        now = self.clock()
        seqs = self.v3.lease_seqs()
        for lid in list(self._lease_seen):
            if lid not in seqs:
                self._lease_seen.pop(lid, None)
                self._lease_revoke_inflight.pop(lid, None)
        cooloff = max(1.0, 4 * self.cfg.sync_ticks * self.cfg.tick_ms
                      / 1000.0)
        for lid, seq in seqs.items():
            seen = self._lease_seen.get(lid)
            if seen is None or seen[0] != seq:
                self._lease_seen[lid] = (seq, now)   # new or renewed
                continue
            ttl = self.v3.lease_ttl(lid)
            if ttl is None or now - seen[1] <= ttl:
                continue
            last = self._lease_revoke_inflight.get(lid, 0.0)
            if now - last < cooloff:
                continue   # a revoke is already in flight
            self._lease_revoke_inflight[lid] = now
            r = Request(id=self.reqid.next(), method=METHOD_V3,
                        v3={"type": "lease_revoke", "lease_id": lid,
                            "seq": seq})
            try:
                self.node.propose(r.encode())
            except ProposalDroppedError:
                pass

    def _publish(self) -> None:
        """Propose our own attributes (reference publish server.go:688-715);
        retried on later ticks until the apply marks us published."""
        me = self.cluster.member(self.id)
        name = self.cfg.name
        curls = list(self.cfg.client_urls or
                     (me.client_urls if me else ()))
        r = Request(id=self.reqid.next(), method=METHOD_PUT,
                    path=(cl.member_store_key(self.id) + _MEMBER_ATTR_SUFFIX),
                    val=json.dumps({"name": name, "clientURLs": curls},
                                   sort_keys=True))
        try:
            self.node.propose(r.encode())
        except ProposalDroppedError:
            pass

    def _process_ready(self) -> None:
        while True:
            rd = self.node.ready()
            if rd is None:
                return
            # 1. Persist: snapshot file, then WAL {HardState, Entries} fsync
            #    (reference etcdserver/raft.go:139-160, contract doc.go:31-39).
            if not rd.snapshot.is_empty():
                self.storage.save_snap(rd.snapshot)
            self.storage.save(rd.hard_state, list(rd.entries))
            if not rd.snapshot.is_empty():
                self.raft_storage.apply_snapshot(rd.snapshot)
                self._recover_from_snapshot(rd.snapshot)
            if rd.entries:
                self.raft_storage.append(list(rd.entries))
            # 2. Send AFTER persist.
            self.transport.send(rd.messages)
            # 3. Apply committed entries, then acknowledge.
            self._apply_entries(rd.committed_entries)
            self.node.advance()
            self._maybe_snapshot()

    def _recover_from_snapshot(self, snap: Snapshot) -> None:
        """A MsgSnap overtook our log: reset BOTH state machines from the
        leader's snapshot (reference server.go:429-453; the v3 backend
        image rides the same payload)."""
        v2, v3img = _decode_snap_data(snap.data)
        self.store.recovery(v2)
        self._install_v3_from_snap(v3img, snap.metadata.index)
        self.cluster.recover()
        self._applied = snap.metadata.index
        self._snapi = snap.metadata.index
        for m in self.cluster.members():
            if m.id != self.id:
                self.transport.add_peer(m.id, m.peer_urls)

    def _install_v3_from_snap(self, v3img: Optional[bytes],
                              snap_index: int) -> None:
        if v3img is not None:
            self.v3.install_snapshot(v3img)
            self.v3_gapped = False
        elif snap_index > self.v3.consistent_index:
            # Legacy snapshot without a v3 image: entries in
            # (consistent_index, snap_index] are compacted away, so this
            # member's v3 keyspace has silently forked — REFUSE v3 service
            # (incl. serializable reads) instead of serving diverged data.
            self.v3_gapped = True
            log.error("snapshot at index %d outran the v3 backend "
                      "(consistent index %d) and carries no v3 image: "
                      "v3 service DISABLED on this member until resync",
                      snap_index, self.v3.consistent_index)

    def _apply_entries(self, ents: Sequence[Entry]) -> None:
        for e in ents:
            if e.index <= self._applied:
                continue
            if e.type == EntryType.NORMAL:
                self._apply_normal(e)
            elif e.type == EntryType.CONF_CHANGE:
                self._apply_conf_change(e)
            self._applied = e.index

    def _apply_normal(self, e: Entry) -> None:
        if not e.data:
            return  # leader's empty commit marker
        r = Request.decode(e.data)
        try:
            result = self._apply_request(r, e.index)
        except errors.EtcdError as err:
            result = err
        self.wait.trigger(r.id, result)

    def _apply_request(self, r: Request, index: int = 0):
        """Deterministic request→store mapping (reference applyRequest
        server.go:766-820). v3 ops carry the entry index so the v3
        consistent-index can make replay idempotent."""
        from etcd_tpu.server.v3 import V3Error
        if r.method == METHOD_V3:
            try:
                return self.v3.apply(r.v3 or {}, index)
            except V3Error as e:
                return e   # deterministic; delivered to the waiter as-is
            except Exception:
                # Environmental failure (disk I/O, sqlite corruption): the
                # apply did NOT record its consistent index and nothing
                # committed (atomic hold), so crashing this member and
                # re-applying on restart is the consistent outcome — the
                # reference panics on backend errors for the same reason.
                # Deterministic data errors can't land here: validate_op
                # turns them into V3Errors on every member identically.
                log.exception("fatal: v3 apply failed at index %d; "
                              "member refuses service until restart", index)
                self._fatal = True
                raise
        st = self.store
        exp = r.expiration
        if r.method == METHOD_POST:
            return st.create(r.path, is_dir=r.dir, value=r.val, unique=True,
                             expire_time=exp)
        if r.method == METHOD_PUT:
            if r.refresh:
                # TTL-only move: value kept, watchers not notified
                # (reference apply_v2.go Put refresh path).
                return st.update(r.path, None, exp, refresh=True)
            if r.prev_exist is not None:
                if r.prev_exist:
                    if r.prev_index or r.prev_value:
                        return st.compare_and_swap(r.path, r.prev_value,
                                                   r.prev_index, r.val, exp)
                    return st.update(r.path, r.val, exp)
                return st.create(r.path, is_dir=r.dir, value=r.val,
                                 expire_time=exp)
            if r.prev_index or r.prev_value:
                return st.compare_and_swap(r.path, r.prev_value,
                                           r.prev_index, r.val, exp)
            # Publish path: keep the cluster view in sync (reference
            # storeMemberAttributeRegexp special case).
            if (r.path.startswith(cl.STORE_CLUSTER_PREFIX) and
                    r.path.endswith(_MEMBER_ATTR_SUFFIX)):
                mid = int(r.path.rsplit("/", 2)[1], 16)
                d = json.loads(r.val)
                self.cluster.update_member_attributes(
                    mid, d.get("name", ""), d.get("clientURLs", ()))
                if mid == self.id:
                    self._published = True
                return st.set(r.path, is_dir=r.dir, value=r.val,
                              expire_time=exp)
            if not r.dir and self.wait.is_registered(r.id):
                # Unconditional file PUT with a live waiter: hand back
                # raw descriptors and let the serving thread materialize
                # the Event (do()), keeping the run-loop thread's apply
                # slice minimal. Falls through for stores without the
                # native lazy path.
                lazy = getattr(st, "set_applied_lazy", None)
                if lazy is not None:
                    return lazy(r.path, r.val, exp)
            return st.set(r.path, is_dir=r.dir, value=r.val, expire_time=exp)
        if r.method == METHOD_DELETE:
            if r.prev_index or r.prev_value:
                return st.compare_and_delete(r.path, r.prev_value,
                                             r.prev_index)
            return st.delete(r.path, is_dir=r.dir, recursive=r.recursive)
        if r.method == METHOD_QGET:
            return st.get(r.path, r.recursive, r.sorted)
        if r.method == METHOD_SYNC:
            st.delete_expired_keys(r.time)
            return None
        raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                               cause=f"bad method {r.method}")

    def _apply_conf_change(self, e: Entry) -> None:
        cc = raftpb.decode_conf_change(e.data)
        cs = self.node.apply_conf_change(cc)
        if cc.type == ConfChangeType.ADD_NODE:
            if cc.context:
                d = json.loads(cc.context.decode())
                m = Member(id=int(d["id"], 16) if isinstance(d["id"], str)
                           else d["id"],
                           name=d.get("name", ""),
                           peer_urls=tuple(d.get("peerURLs", ())),
                           client_urls=tuple(d.get("clientURLs", ())))
            else:
                m = Member(id=cc.node_id)
            self.cluster.add_member(m)
            if m.id != self.id:
                self.transport.add_peer(m.id, m.peer_urls)
        elif cc.type == ConfChangeType.REMOVE_NODE:
            self.cluster.remove_member(cc.node_id)
            if cc.node_id == self.id:
                self._removed_self = True
            else:
                self.transport.remove_peer(cc.node_id)
        elif cc.type == ConfChangeType.UPDATE_NODE:
            if cc.context:
                d = json.loads(cc.context.decode())
                self.cluster.update_member_raft_attributes(
                    cc.node_id, tuple(d.get("peerURLs", ())))
                if cc.node_id != self.id:
                    self.transport.update_peer(cc.node_id,
                                               d.get("peerURLs", ()))
        self.wait.trigger(cc.id, self.cluster.members())

    def _maybe_snapshot(self) -> None:
        """Snapshot + compact once enough entries applied (reference
        server.go:476-480,876-916)."""
        if self._applied - self._snapi <= self.cfg.snap_count:
            return
        # The snapshot advances the WAL-replay floor past every applied
        # entry, so the v3 backend's pending batch (data + consistent
        # index) must be durable FIRST — otherwise a crash inside the
        # batch interval loses v3 ops in (consistentIndex, snapshot] with
        # no replay to recover them.
        data = _encode_snap_data(self.store.save(),
                                 self.v3.snapshot_state())
        cs = ConfState(nodes=tuple(self.node.raft.nodes()))
        snap = self.raft_storage.create_snapshot(self._applied, cs, data)
        self.storage.save_snap(snap)
        self._snapi = self._applied
        compacti = self._snapi - self.cfg.catch_up_entries
        if compacti > self.raft_storage.first_index():
            try:
                self.raft_storage.compact(compacti)
            except CompactedError:
                pass
        purge_files(self.cfg.waldir, ".wal", MAX_WAL_FILES)
        purge_files(self.cfg.snapdir, ".snap", MAX_SNAP_FILES)
