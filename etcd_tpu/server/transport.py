"""Peer-transport seam (reference rafthttp.Transporter / rafthttp.Raft
interface pair, rafthttp/transport.go:29-70).

The server speaks to peers only through `Transporter.send`; inbound messages
arrive via `RaftHandler.process`. This module ships the in-memory
implementation used by tests and single-host multi-member deployments —
non-blocking sends with drop-on-full + unreachable reporting, plus the
pause/drop/isolate fault knobs of the reference test doubles
(rafthttp/transport.go:235-249 Pausable, raft_test.go network fixture). The
HTTP implementation lives in etcd_tpu/transport/.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from etcd_tpu.raftpb import Message, MessageType


class Transporter:
    """What the server core needs from any peer transport."""

    def send(self, msgs: Iterable[Message]) -> None:
        raise NotImplementedError

    def add_peer(self, mid: int, urls: Iterable[str]) -> None:
        pass

    def remove_peer(self, mid: int) -> None:
        pass

    def update_peer(self, mid: int, urls: Iterable[str]) -> None:
        pass

    def member_version(self, mid: int, peer_urls: Iterable[str]
                       ) -> Optional[str]:
        """The member's server version for cluster-version negotiation
        (reference getVersions cluster_util.go:118-137), or None when
        unreachable/unsupported."""
        return None

    def stop(self) -> None:
        pass


class InMemoryNetwork:
    """A hub connecting InMemoryTransports by member id, with fault
    injection: drop rates per edge, isolation, pausing."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._inboxes: Dict[int, "queue.Queue[Message]"] = {}
        self._dropped: Set[Tuple[int, int]] = set()   # (frm, to) edges cut
        self._isolated: Set[int] = set()
        self.delivered = 0
        self.dropped_count = 0

    def register(self, mid: int, inbox: "queue.Queue[Message]") -> None:
        with self._lock:
            self._inboxes[mid] = inbox

    def unregister(self, mid: int) -> None:
        with self._lock:
            self._inboxes.pop(mid, None)

    # -- fault knobs (reference rafttest/network.go, raft_test.go:1760-1837) --

    def cut(self, a: int, b: int) -> None:
        with self._lock:
            self._dropped.add((a, b))
            self._dropped.add((b, a))

    def heal(self, a: int = None, b: int = None) -> None:
        with self._lock:
            if a is None:
                self._dropped.clear()
                self._isolated.clear()
            else:
                self._dropped.discard((a, b))
                self._dropped.discard((b, a))

    def isolate(self, mid: int) -> None:
        with self._lock:
            self._isolated.add(mid)

    def unisolate(self, mid: int) -> None:
        with self._lock:
            self._isolated.discard(mid)

    def deliver(self, m: Message) -> bool:
        with self._lock:
            if (m.frm, m.to) in self._dropped:
                self.dropped_count += 1
                return False
            if m.frm in self._isolated or m.to in self._isolated:
                self.dropped_count += 1
                return False
            inbox = self._inboxes.get(m.to)
        if inbox is None:
            return False
        try:
            inbox.put_nowait(m)
        except queue.Full:
            self.dropped_count += 1
            return False
        self.delivered += 1
        return True


class InMemoryTransport(Transporter):
    """Per-member transport over an InMemoryNetwork. Mirrors rafthttp's
    liveness contract: sends never block; a failed send to a known peer
    reports unreachability back into the consensus core (reference
    rafthttp/peer.go:156-165)."""

    def __init__(self, net: InMemoryNetwork, mid: int,
                 report_unreachable: Optional[Callable[[int], None]] = None,
                 report_snapshot: Optional[Callable[[int, bool], None]] = None
                 ) -> None:
        self.net = net
        self.id = mid
        self._peers: Set[int] = set()
        self._paused = False
        self.report_unreachable = report_unreachable
        self.report_snapshot = report_snapshot

    def send(self, msgs: Iterable[Message]) -> None:
        for m in msgs:
            if m.to == 0 or self._paused:
                continue
            ok = self.net.deliver(m)
            is_snap = m.type == MessageType.SNAP
            if not ok:
                if self.report_unreachable is not None:
                    self.report_unreachable(m.to)
                if is_snap and self.report_snapshot is not None:
                    self.report_snapshot(m.to, False)
            elif is_snap and self.report_snapshot is not None:
                self.report_snapshot(m.to, True)

    def member_version(self, mid: int, peer_urls: Iterable[str]
                       ) -> Optional[str]:
        # All members of an in-memory cluster are this process: same code,
        # same version — reachable iff registered.
        if mid in self.net._inboxes:
            from etcd_tpu import version as ver
            return ver.VERSION
        return None

    # Pausable (reference transport.go:235-249).
    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def stop(self) -> None:
        self.net.unregister(self.id)
