"""Cluster membership registry, stored INSIDE the replicated v2 store.

Behavioral equivalent of reference etcdserver/cluster.go:208-288,
member.go:38-55: members live under /0/members/<idhex> (raftAttributes =
consensus-relevant peer URLs; attributes = name + client URLs, published
later via consensus), removed ids leave tombstones so stale peers are
rejected forever. Because membership lives in the store, snapshots carry it
automatically and recovery rebuilds it for free.
"""
from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from etcd_tpu import errors
from etcd_tpu.store import Store

STORE_CLUSTER_PREFIX = "/0"          # reference server.go:60
STORE_KEYS_PREFIX = "/1"
_MEMBERS = STORE_CLUSTER_PREFIX + "/members"
_REMOVED = STORE_CLUSTER_PREFIX + "/removed_members"
CLUSTER_VERSION_KEY = STORE_CLUSTER_PREFIX + "/version"


def compute_member_id(peer_urls: Sequence[str], cluster_token: str = "") -> int:
    """Deterministic member id from sorted peer URLs + bootstrap token
    (reference member.go NewMember sha1 scheme)."""
    b = ",".join(sorted(peer_urls)) + "|" + cluster_token
    return int.from_bytes(hashlib.sha1(b.encode()).digest()[:8], "big")


def compute_cluster_id(member_ids: Sequence[int]) -> int:
    """Cluster id = hash of the sorted founding member ids (reference
    cluster.go:208-217 genID)."""
    b = b"".join(i.to_bytes(8, "big") for i in sorted(member_ids))
    return int.from_bytes(hashlib.sha1(b).digest()[:8], "big")


@dataclass(frozen=True)
class Member:
    id: int
    name: str = ""
    peer_urls: Tuple[str, ...] = ()     # raftAttributes (consensus-critical)
    client_urls: Tuple[str, ...] = ()   # attributes (published post-boot)

    @staticmethod
    def new(name: str, peer_urls: Sequence[str],
            client_urls: Sequence[str] = (), cluster_token: str = "") -> "Member":
        return Member(id=compute_member_id(peer_urls, cluster_token),
                      name=name, peer_urls=tuple(peer_urls),
                      client_urls=tuple(client_urls))

    def raft_attributes_json(self) -> str:
        return json.dumps({"peerURLs": list(self.peer_urls)}, sort_keys=True)

    def attributes_json(self) -> str:
        return json.dumps({"name": self.name,
                           "clientURLs": list(self.client_urls)},
                          sort_keys=True)

    def to_dict(self) -> dict:
        return {
            "id": f"{self.id:x}",
            "name": self.name,
            "peerURLs": list(self.peer_urls),
            "clientURLs": list(self.client_urls),
        }


def member_store_key(mid: int) -> str:
    return f"{_MEMBERS}/{mid:x}"


class Cluster:
    """The live membership view. All mutations happen from the apply loop
    (single writer); reads come from anywhere."""

    def __init__(self, store: Store, token: str = "etcd-cluster") -> None:
        self._lock = threading.Lock()
        self.store = store
        self.token = token
        self.cluster_id = 0
        self._members: Dict[int, Member] = {}
        self._removed: Set[int] = set()

    # -- bootstrap -----------------------------------------------------------

    @staticmethod
    def from_initial(store: Store, initial: Dict[str, Sequence[str]],
                     token: str = "etcd-cluster") -> "Cluster":
        """Build the founding membership from an initial-cluster map
        {name: [peer_urls]} (reference NewClusterFromString)."""
        c = Cluster(store, token)
        ids = []
        for name, urls in sorted(initial.items()):
            m = Member.new(name, urls, cluster_token=token)
            c._members[m.id] = m
            ids.append(m.id)
        c.cluster_id = compute_cluster_id(ids)
        return c

    def recover(self) -> None:
        """Rebuild the in-memory view from the store after snapshot recovery
        (reference cluster.go membersFromStore)."""
        with self._lock:
            self._members = {}
            self._removed = set()
            try:
                e = self.store.get(_MEMBERS, recursive=True)
            except errors.EtcdError:
                return
            for n in e.node.nodes or []:
                mid = int(n.key.rsplit("/", 1)[1], 16)
                m = Member(id=mid)
                for leaf in n.nodes or []:
                    d = json.loads(leaf.value or "{}")
                    if leaf.key.endswith("/raftAttributes"):
                        m = replace(m, peer_urls=tuple(d.get("peerURLs", ())))
                    elif leaf.key.endswith("/attributes"):
                        m = replace(m, name=d.get("name", ""),
                                    client_urls=tuple(d.get("clientURLs", ())))
                self._members[mid] = m
            try:
                e = self.store.get(_REMOVED)
                for n in e.node.nodes or []:
                    self._removed.add(int(n.key.rsplit("/", 1)[1], 16))
            except errors.EtcdError:
                pass

    # -- reads ---------------------------------------------------------------

    def members(self) -> List[Member]:
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.id)

    def member(self, mid: int) -> Optional[Member]:
        with self._lock:
            return self._members.get(mid)

    def member_by_name(self, name: str) -> Optional[Member]:
        with self._lock:
            for m in self._members.values():
                if m.name == name:
                    return m
            return None

    def member_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._members)

    def is_id_removed(self, mid: int) -> bool:
        with self._lock:
            return mid in self._removed

    def client_urls(self) -> List[str]:
        with self._lock:
            out: List[str] = []
            for m in self._members.values():
                out.extend(m.client_urls)
            return sorted(out)

    def peer_urls(self) -> List[str]:
        with self._lock:
            out: List[str] = []
            for m in self._members.values():
                out.extend(m.peer_urls)
            return sorted(out)

    # -- validation (pre-propose) -------------------------------------------

    def version(self) -> Optional[str]:
        """The decided cluster version, stored replicated at /0/version
        (reference cluster.go Version / monitorVersions)."""
        try:
            e = self.store.get(CLUSTER_VERSION_KEY)
        except errors.EtcdError:
            return None
        return e.node.value if e.node else None

    def validate_conf_change(self, cc_type: str, mid: int,
                             peer_urls: Sequence[str] = ()) -> None:
        """Reject impossible membership changes before proposing (reference
        cluster.go:229-288 ValidateConfigurationChange)."""
        with self._lock:
            if mid in self._removed:
                raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                       cause=f"member {mid:x} was removed")
            if cc_type == "add":
                if mid in self._members:
                    raise errors.EtcdError(errors.ECODE_NODE_EXIST,
                                           cause=f"member {mid:x} exists")
                self._check_url_clash(peer_urls, exclude=None)
            elif cc_type == "remove":
                if mid not in self._members:
                    raise errors.EtcdError(errors.ECODE_KEY_NOT_FOUND,
                                           cause=f"member {mid:x} not found")
            elif cc_type == "update":
                if mid not in self._members:
                    raise errors.EtcdError(errors.ECODE_KEY_NOT_FOUND,
                                           cause=f"member {mid:x} not found")
                self._check_url_clash(peer_urls, exclude=mid)
            else:
                raise ValueError(f"bad conf change type {cc_type}")

    def _check_url_clash(self, urls: Sequence[str],
                         exclude: Optional[int]) -> None:
        taken = set()
        for m in self._members.values():
            if m.id == exclude:
                continue
            taken.update(m.peer_urls)
        for u in urls:
            if u in taken:
                raise errors.EtcdError(errors.ECODE_NODE_EXIST,
                                       cause=f"peer URL {u} already used")

    # -- apply-side mutations (single writer: the apply loop) ---------------

    def add_member(self, m: Member) -> None:
        """Apply an AddNode: record raftAttributes in the store (reference
        cluster.go AddMember)."""
        with self._lock:
            try:
                self.store.create(member_store_key(m.id) + "/raftAttributes",
                                  value=m.raft_attributes_json())
            except errors.EtcdError as e:
                if e.code != errors.ECODE_NODE_EXIST:  # replay after recovery
                    raise
            if m.name or m.client_urls:
                try:
                    self.store.create(member_store_key(m.id) + "/attributes",
                                      value=m.attributes_json())
                except errors.EtcdError as e:
                    if e.code != errors.ECODE_NODE_EXIST:
                        raise
            self._members[m.id] = m

    def remove_member(self, mid: int) -> None:
        """Apply a RemoveNode: delete from the store, add tombstone
        (reference cluster.go RemoveMember)."""
        with self._lock:
            try:
                self.store.delete(member_store_key(mid), recursive=True)
            except errors.EtcdError:
                pass
            try:
                self.store.create(f"{_REMOVED}/{mid:x}", value="removed")
            except errors.EtcdError:
                pass
            self._members.pop(mid, None)
            self._removed.add(mid)

    def update_member_attributes(self, mid: int, name: str,
                                 client_urls: Sequence[str]) -> None:
        """Apply a published attributes update (reference
        server.go:820 applyRequest PUT on attributes key)."""
        with self._lock:
            m = self._members.get(mid)
            if m is None:
                return
            self._members[mid] = replace(m, name=name,
                                         client_urls=tuple(client_urls))

    def update_member_raft_attributes(self, mid: int,
                                      peer_urls: Sequence[str]) -> None:
        with self._lock:
            m = self._members.get(mid)
            if m is None:
                return
            nm = replace(m, peer_urls=tuple(peer_urls))
            try:
                self.store.set(member_store_key(mid) + "/raftAttributes",
                               value=nm.raft_attributes_json())
            except errors.EtcdError:
                pass
            self._members[mid] = nm


# -- remote bootstrap helpers (reference etcdserver/cluster_util.go) ----------

def get_cluster_from_remote_peers(peer_urls: Sequence[str],
                                  timeout: float = 2.0, tls_context=None
                                  ) -> Tuple[int, List[Member]]:
    """GET /members from each peer URL until one answers; returns
    (cluster_id, members) — the joiner's view of the existing cluster
    (reference GetClusterFromRemotePeers cluster_util.go:54-98).
    tls_context secures https:// peers (joining a mutual-TLS cluster
    requires the same peer cert the raft transport presents)."""
    from etcd_tpu.utils.tlsutil import open_conn

    for base in peer_urls:
        try:
            conn = open_conn(base, timeout, tls_context)
            try:
                conn.request("GET", "/members")
                resp = conn.getresponse()
                if resp.status != 200:
                    continue
                cid_hex = resp.getheader("X-Etcd-Cluster-ID") or "0"
                data = json.loads(resp.read().decode())
            finally:
                conn.close()
        except (OSError, ValueError):
            continue
        members = [Member(id=int(m["id"], 16), name=m.get("name", ""),
                          peer_urls=tuple(m.get("peerURLs", ())),
                          client_urls=tuple(m.get("clientURLs", ())))
                   for m in data.get("members", [])]
        if members:
            return int(cid_hex, 16), members
    raise RuntimeError(
        f"cannot fetch cluster info from peer urls {list(peer_urls)}")


def validate_cluster_and_assign_ids(local: "Cluster",
                                    existing: List[Member]) -> None:
    """Match the locally-configured membership (-initial-cluster) against
    the running cluster's member list by sorted peer URLs, and take over the
    existing IDs (reference ValidateClusterAndAssignIDs
    cluster_util.go:103-140). Raises on any mismatch."""
    ems = sorted(existing, key=lambda m: sorted(m.peer_urls))
    lms = sorted(local.members(), key=lambda m: sorted(m.peer_urls))
    if len(ems) != len(lms):
        raise ValueError(
            f"member count is unequal: local {len(lms)} vs existing "
            f"{len(ems)}")
    for em, lm in zip(ems, lms):
        if sorted(em.peer_urls) != sorted(lm.peer_urls):
            raise ValueError(
                f"unmatched member while checking PeerURLs: local "
                f"{sorted(lm.peer_urls)} vs existing {sorted(em.peer_urls)}")
    with local._lock:
        local._members = {em.id: replace(lm, id=em.id)
                          for em, lm in zip(ems, lms)}
