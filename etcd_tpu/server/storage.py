"""WAL + Snapshotter composite (reference etcdserver/storage.go:34-132).

Save = WAL append+fsync of {HardState, Entries}. SaveSnap = snapshot file +
WAL snapshot marker + release of obsolete WAL locks, in that order. read_wal
replays with ONE auto-repair attempt on a torn tail (reference
storage.go:75-107).
"""
from __future__ import annotations

import os
from typing import List, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import Entry, HardState, Snapshot
from etcd_tpu.snap import Snapshotter
from etcd_tpu.wal import WAL, UnexpectedEOF, WalSnapshot
from etcd_tpu.wal import wal as wal_mod


class ServerStorage:
    def __init__(self, w: WAL, ss: Snapshotter) -> None:
        self.wal = w
        self.snapshotter = ss

    def save(self, st: HardState, ents: List[Entry]) -> None:
        self.wal.save(st, ents)

    def save_snap(self, snap: Snapshot) -> None:
        """Durable snapshot: WAL marker first (so replay knows the horizon),
        then the snapshot file, then unlock superseded segments (reference
        storage.go:55-73)."""
        ws = WalSnapshot(index=snap.metadata.index, term=snap.metadata.term)
        self.wal.save_snapshot(ws)
        self.snapshotter.save_snap(snap)
        self.wal.release_lock_to(snap.metadata.index)

    def close(self) -> None:
        self.wal.close()


def read_wal(waldir: str, snap: WalSnapshot,
             segment_size: int = wal_mod.SEGMENT_SIZE_BYTES
             ) -> Tuple[WAL, bytes, HardState, List[Entry]]:
    """Open + replay the WAL from `snap`, auto-repairing a torn tail once
    (reference storage.go:75-107 readWAL)."""
    repaired = False
    while True:
        w = WAL.open(waldir, snap, segment_size=segment_size)
        try:
            metadata, st, ents = w.read_all()
            return w, metadata, st, ents
        except UnexpectedEOF:
            w.close()
            if repaired or not wal_mod.repair(waldir):
                raise
            repaired = True
