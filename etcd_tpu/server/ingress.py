"""Coalescing ingress tier: manufacture batch depth from shallow clients.

Every headline engine number is measured from deep per-tenant queues,
but a million-user deployment is the opposite shape: tens of thousands
of SHALLOW clients, each issuing depth-1 writes, TTL refreshes and
watches. "Scaling Replicated State Machines with Compartmentalization"
(PAPERS.md) names the fix — a stateless proxy/batcher role in front of
the ordering core — and ROADMAP item 2 scopes it for this engine. This
module is that role:

  * An EVENT-DRIVEN front (one epoll loop, not thread-per-connection)
    holds tens of thousands of client sockets at a few fds' and one
    thread's cost — the whole point; a threaded front would burn the
    same GIL the direct path does and manufacture nothing.

  * A per-tenant COALESCING LANE buffers writes inside an adaptive
    window and ships each flush upstream over a PERSISTENT BINARY
    CHANNEL (server/batchframe.py: one 101-upgraded socket per lane,
    length-prefixed frames, the slot payload packed by ONE
    walcodec.pack_multi call) feeding MultiEngine.submit_many -> the
    existing P_MULTI multi-request log-entry packing, so WAL format and
    replay are untouched. The channel PIPELINES: up to
    IngressConfig.flush_window flushes ride the wire at once, demuxed
    by flush id — the engine's staging queue never drains to zero
    between flushes, which is what lets the tier track the engine's
    deep-queue capacity instead of its round-trip latency. The window
    never sleeps: it closes on request count (flush_max_requests), on
    bytes (flush_max_bytes), or the moment a pipeline slot frees while
    the buffer is non-empty (the "drain" reason) — group commit's
    natural-batching policy at the tier above the engine. Upstreams
    that refuse the handshake (a router that only rewrites
    /tenants/{t}/batch) fall back per lane to the round-10 JSON POST
    path; channel re-establishment is paced by capped exponential
    backoff.

  * The PER-REQUEST HOT LOOP is native when built (ingresscore.c): one
    GIL-releasing C pass scans a connection's read buffer into request
    tuples, and each flush's fan-back materializes all N client
    responses in one formatter call — the pure-Python reference path
    remains the automatic fallback (etcd_ingress_native_enabled says
    which is serving).

  * Acks/errors DEMULTIPLEX back to each waiting client only after the
    upstream ack: the ingress holds no durable state and never
    acknowledges ahead of the engine's fsync-gated ack, so SIGKILLing
    an ingress process can lose in-flight (unacked) writes but never an
    acked one (tests/test_ingress.py proves it across a real SIGKILL).

  * A WATCH FAN-OUT HUB multiplexes N downstream watchers of the same
    (tenant, key, recursive) onto ONE upstream watch stream, with a
    small replay ring so late long-polls with a waitIndex inside the
    ring are served without another upstream round trip. A waitIndex
    OLDER than the ring's coverage forwards upstream verbatim on a
    dedicated proxy — history replays (or 401s EventIndexCleared)
    exactly as on the direct path, never silently skipped.

  * Quorum GETs forward to the PR 9 read plane upstream; with
    read_lease_ms > 0 the ingress downgrades them to plain local GETs
    while a lease holds — any upstream quorum-confirmed ack (every
    batch ack is one: a committed write proves the leader's quorum)
    within the window renews it. Same clock-bound contract as
    EngineConfig.read_lease_ms; off by default.

Run one per core (scripts/ingress_serve.py) in front of an engine or a
pool_serve.py router — the router rewrites /tenants/{t}/batch through
the same tenant mapping as every other per-tenant path, so ingress and
process sharding compose unchanged.
"""
from __future__ import annotations

import http.client
import json
import logging
import os
import posixpath
import selectors
import socket
import threading
import time
import urllib.parse
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from etcd_tpu import native
from etcd_tpu.server import batchframe, obs

log = logging.getLogger("etcd_tpu.ingress")

_MAX_HEADER = 64 * 1024
_MAX_BODY = 4 * 1024 * 1024
_MAX_WBUF = 8 * 1024 * 1024   # slow-client cap: close past this backlog
_RING_CAP = 256          # hub replay ring (events per upstream stream)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

@dataclass
class IngressConfig:
    upstream: str                      # "http://host:port" (engine or router)
    host: str = "127.0.0.1"
    port: int = 0
    flush_max_requests: int = 1024     # window closes on count...
    flush_max_bytes: int = 1 << 20     # ...or on encoded bytes...
    max_inflight: int = 1              # ...or when an inflight slot frees.
    # max_inflight=1 keeps per-client FIFO strict even for pipelined
    # writes (batches commit in flush order); depth-1 clients are
    # order-safe at any setting because they never overlap their own
    # writes. (JSON-path slot count; the binary channel's depth is
    # flush_window.)
    flush_window: int = 4              # pipelined flushes per lane on the
    #                                    binary channel; per-client FIFO
    #                                    holds at any depth because the
    #                                    busy gate allows one outstanding
    #                                    request per connection, and
    #                                    frames submit to engine staging
    #                                    in channel order.
    upstream_mode: str = "auto"        # "auto" | "frame" | "json"
    use_native: bool = True            # ingresscore.c hot loop when built
    read_lease_ms: int = 0
    request_timeout: float = 30.0


def _upstream_addr(url: str) -> Tuple[str, int]:
    u = urllib.parse.urlsplit(url if "//" in url else "//" + url)
    return u.hostname or "127.0.0.1", int(u.port or 2379)


# ---------------------------------------------------------------------------
# HTTP plumbing (loop side)
# ---------------------------------------------------------------------------

class _Conn:
    """One downstream client connection's loop-side state."""

    __slots__ = ("sock", "rbuf", "wbuf", "closing", "streaming",
                 "want_write", "open", "busy", "subs", "fwd",
                 "pending", "perr")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.closing = False       # close after wbuf drains
        self.streaming = False     # chunked watch stream in progress
        self.want_write = False
        self.open = True
        self.busy = False          # a response is owed; pause parsing
        self.subs: list = []       # hub subscriptions (for close cleanup)
        self.fwd: list = []        # upstream conns of dedicated watch
        #                            proxies; severed on close to unblock
        #                            their reader threads
        self.pending: deque = deque()  # scanned-but-undispatched requests
        self.perr = 0              # scanner error latched behind pending


def _response(status: int, body: bytes,
              ctype: str = "application/json",
              extra: Optional[Dict[str, str]] = None,
              close: bool = False) -> bytes:
    reason = {200: "OK", 201: "Created", 400: "Bad Request",
              404: "Not Found", 405: "Method Not Allowed",
              408: "Request Timeout", 500: "Internal Server Error",
              503: "Service Unavailable"}.get(status, "OK")
    h = [f"HTTP/1.1 {status} {reason}",
         f"Content-Type: {ctype}",
         f"Content-Length: {len(body)}"]
    for k, v in (extra or {}).items():
        h.append(f"{k}: {v}")
    if close:
        h.append("Connection: close")
    return ("\r\n".join(h) + "\r\n\r\n").encode() + body


def _json_response(status: int, obj,
                   extra: Optional[Dict[str, str]] = None) -> bytes:
    return _response(status, json.dumps(obj).encode() + b"\n",
                     extra=extra)


def _chunk(data: bytes) -> bytes:
    return f"{len(data):x}\r\n".encode() + data + b"\r\n"


def _err_body(cause: str) -> bytes:
    """Client-facing body of a whole-flush upstream failure."""
    return json.dumps({"errorCode": 300, "message": "Raft Internal Error",
                       "cause": cause}).encode() + b"\n"


# ---------------------------------------------------------------------------
# the coalescing lane (one per tenant)
# ---------------------------------------------------------------------------

class _PendingWrite:
    __slots__ = ("conn", "item", "size", "t0")

    def __init__(self, conn: _Conn, item: dict, size: int) -> None:
        self.conn = conn
        self.item = item
        self.size = size
        self.t0 = time.perf_counter()


class _Channel:
    """One lane's persistent binary upstream channel (batchframe).

    Flushes PIPELINE: send_flush registers the batch under a fresh flush
    id and writes one request frame without waiting; the reader thread
    demultiplexes response frames back to their batches in any order.
    A send/read failure SEVERS the channel: every registered (in-flight)
    flush fans back a 503 and nothing is ever re-sent — a flush the
    upstream may have read MAY have committed, and re-sending it would
    double-apply POSTs and break CAS chains. The clients that never got
    an ack own the retry, exactly as with a direct engine."""

    __slots__ = ("lane", "sock", "rfile", "lock", "inflight", "next_id",
                 "alive", "born", "reader")

    def __init__(self, lane: "_Lane", sock: socket.socket, rfile) -> None:
        self.lane = lane
        self.sock = sock
        self.rfile = rfile
        self.lock = threading.Lock()
        self.inflight: Dict[int, List[_PendingWrite]] = {}
        self.next_id = 1
        self.alive = True
        self.born = time.monotonic()
        self.reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"ingress-chan{lane.tenant}")
        self.reader.start()

    def window_used(self) -> int:
        with self.lock:
            return len(self.inflight)

    def send_flush(self, batch: List[_PendingWrite], auth_json: bytes,
                   payload: bytes) -> bool:
        """Register + send one flush. False = channel already dead and
        the CALLER still owns the batch. True = the channel owns it: the
        reader acks it or sever() 503s it."""
        err: Optional[Exception] = None
        with self.lock:
            if not self.alive:
                return False
            fid = self.next_id
            self.next_id += 1
            self.inflight[fid] = batch
            try:
                # Send under the lock: concurrent flushers' frame bytes
                # must never interleave on the wire.
                self.sock.sendall(batchframe.pack_request_frame(
                    fid, auth_json, payload))
            except OSError as e:
                err = e
        if err is not None:
            self.sever(err)
        else:
            obs.ingress_upstream_frames.labels("sent").inc()
        return True

    def _read_loop(self) -> None:
        lane = self.lane
        try:
            while True:
                frame = batchframe.read_response_frame(self.rfile)
                if frame is None:
                    raise OSError("upstream closed batchframe channel")
                fid, slots, error = frame
                obs.ingress_upstream_frames.labels("recv").inc()
                with self.lock:
                    batch = self.inflight.pop(fid, None)
                if batch is None:
                    continue       # already failed over in sever()
                if slots is None:
                    status, body = error
                    lane.fan_error(batch, status, bytes(body))
                elif len(slots) != len(batch):
                    lane.fan_error(batch, 503, _err_body(
                        "upstream batchframe slot count mismatch"))
                else:
                    lane.fan_acks(batch, slots)
                lane.window_notify()
        except Exception as e:  # noqa: BLE001 — sever fans back per client
            self.sever(e)
        finally:
            # Only this (the reader) thread closes the fds: other
            # threads sever via shutdown so a blocked read unblocks with
            # EOF instead of racing a close-and-reuse under it.
            try:
                self.rfile.close()
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass

    def sever(self, err: Exception) -> None:
        """Mark the channel dead and 503 EXACTLY the in-flight flushes
        (never a retry). Idempotent; callable from any thread."""
        with self.lock:
            was_alive, self.alive = self.alive, False
            pending, self.inflight = self.inflight, {}
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        if pending:
            obs.ingress_upstream_severed.inc(len(pending))
            body = _err_body(f"ingress upstream channel severed: {err}")
            for batch in pending.values():
                self.lane.fan_error(batch, 503, body)
        if was_alive:
            self.lane.channel_down(self)


class _Lane:
    """Per-tenant coalescing window + its flusher thread(s).

    The flusher never sleeps on a timer: it waits on the condition until
    the buffer is non-empty AND a pipeline slot is free (flush_window on
    the binary channel, max_inflight on the JSON fallback), takes up to
    the caps, and ships the batch. On the channel the ship is
    FIRE-AND-FORGET — the flusher loops straight back to building the
    next window while up to flush_window flushes ride the wire, so
    upstream round-trip latency stops being the lane's clock; acks
    demultiplex on the channel's reader thread. On the JSON path the
    POST is synchronous and upstream latency IS the adaptive window,
    exactly the round-10 behavior."""

    def __init__(self, ing: "Ingress", tenant: int) -> None:
        self.ing = ing
        self.tenant = tenant
        self.buf: deque = deque()
        self.bytes = 0
        self.cv = threading.Condition()
        self.inflight = 0
        self.stopped = False
        self.lease_until = 0.0       # monotonic; quorum-read lease
        cfg = ing.cfg
        self.mode = cfg.upstream_mode     # "auto" | "frame" | "json";
        #                                   auto flips to json per lane
        #                                   when the upstream 4xxes the
        #                                   batchframe handshake
        self.chan: Optional[_Channel] = None
        self._connect_lock = threading.Lock()
        self._backoff = 0.0          # capped exponential reconnect pace
        self._next_connect = 0.0     # monotonic gate for the next dial
        self._had_channel = False
        self.threads = [
            threading.Thread(target=self._flusher, daemon=True,
                             name=f"ingress-lane{tenant}-{i}")
            for i in range(max(1, cfg.max_inflight))]
        for t in self.threads:
            t.start()

    def enqueue(self, pw: _PendingWrite) -> None:
        with self.cv:
            self.buf.append(pw)
            self.bytes += pw.size
            self.cv.notify()

    def stop(self) -> None:
        with self.cv:
            self.stopped = True
            self.cv.notify_all()
            chan = self.chan
        if chan is not None:
            chan.sever(RuntimeError("ingress stopping"))

    def window_notify(self) -> None:
        """A pipeline slot freed (channel reader finished a flush)."""
        with self.cv:
            self.cv.notify_all()

    def channel_down(self, chan: "_Channel") -> None:
        """The channel severed: pace the re-dial. A channel that lived a
        while earns a fresh (minimal) backoff; a flapping one doubles it
        up to the cap."""
        with self.cv:
            if self.chan is chan:
                self.chan = None
            now = time.monotonic()
            if now - chan.born > 2.0:
                self._backoff = 0.0
            self._backoff = min(2.0, self._backoff * 2 or 0.05)
            self._next_connect = now + self._backoff
            self.cv.notify_all()

    def _take(self) -> Tuple[List[_PendingWrite], str]:
        """Called under cv with a non-empty buffer and a free slot."""
        cfg = self.ing.cfg
        if len(self.buf) >= cfg.flush_max_requests:
            reason = "count"
        elif self.bytes >= cfg.flush_max_bytes:
            reason = "bytes"
        else:
            reason = "drain"
        batch, nbytes = [], 0
        while (self.buf and len(batch) < cfg.flush_max_requests
               and nbytes < cfg.flush_max_bytes):
            pw = self.buf.popleft()
            batch.append(pw)
            nbytes += pw.size
        self.bytes -= nbytes
        return batch, reason

    def _ready(self) -> bool:
        """cv predicate: non-empty buffer AND a free upstream slot.
        On the channel a slot is a flush_window pipeline slot (hard cap:
        a tripped threshold waits for a slot rather than overrunning the
        window); on the JSON path thresholds may overrun max_inflight
        exactly as in round 10."""
        if not self.buf:
            return False
        cfg = self.ing.cfg
        if self.mode != "json":
            chan = self.chan
            if chan is None or not chan.alive:
                return True      # dial (or backoff-503) proceeds
            return chan.window_used() < cfg.flush_window
        if self.inflight < cfg.max_inflight:
            return True
        return (len(self.buf) >= cfg.flush_max_requests
                or self.bytes >= cfg.flush_max_bytes)

    def _flusher(self) -> None:
        upstream: Optional[http.client.HTTPConnection] = None
        host, port = _upstream_addr(self.ing.cfg.upstream)
        while True:
            with self.cv:
                while not self.stopped and not self._ready():
                    self.cv.wait(0.5)
                if self.stopped:
                    return
                batch, reason = self._take()
                self.inflight += 1
            obs.ingress_inflight.inc()
            obs.ingress_flush_reason.labels(reason).inc()
            obs.ingress_batch.observe(len(batch))
            # Exactly ONE fan_acks/fan_error happens per batch (that is
            # where ingress_inflight decrements): immediately below on
            # the failure paths, on the channel's reader thread for a
            # pipelined flush, inline for a JSON POST.
            try:
                if self.mode != "json":
                    chan = self._ensure_channel(host, port)
                    if self.mode == "json":
                        # auto-fallback flipped during this dial
                        upstream = self._flush_json(upstream, host, port,
                                                    batch)
                    elif chan is None:
                        self.fan_error(batch, 503, _err_body(
                            "ingress upstream channel unavailable: "
                            "reconnect backoff"))
                    elif not chan.send_flush(
                            batch, *self._encode_frame(batch)):
                        self.fan_error(batch, 503, _err_body(
                            "ingress upstream channel severed"))
                else:
                    upstream = self._flush_json(upstream, host, port,
                                                batch)
            finally:
                with self.cv:
                    self.inflight -= 1
                    self.cv.notify_all()

    def _ensure_channel(self, host: str,
                        port: int) -> Optional[_Channel]:
        """Return the live channel, (re)dialing under capped exponential
        backoff; None while backing off or unreachable. In auto mode a
        non-101 handshake (an upstream that routes /batch but not
        /batchframe) flips this lane to the JSON path permanently."""
        with self._connect_lock:
            chan = self.chan
            if chan is not None and chan.alive:
                return chan
            now = time.monotonic()
            if now < self._next_connect:
                return None
            if self._had_channel or self._backoff:
                obs.ingress_upstream_reconnects.inc()
            sock = rfile = None
            try:
                sock = socket.create_connection(
                    (host, port), timeout=self.ing.cfg.request_timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(batchframe.handshake_request(
                    self.tenant, f"{host}:{port}"))
                rfile = sock.makefile("rb")
                status = batchframe.read_handshake_status(rfile)
            except OSError as e:
                for f in (rfile, sock):
                    try:
                        if f is not None:
                            f.close()
                    except OSError:
                        pass
                self._backoff = min(2.0, self._backoff * 2 or 0.05)
                self._next_connect = now + self._backoff
                log.warning("lane %d: batchframe dial failed (%s); "
                            "next try in %.2fs", self.tenant, e,
                            self._backoff)
                return None
            if status != 101:
                for f in (rfile, sock):
                    try:
                        f.close()
                    except OSError:
                        pass
                if self.mode == "auto":
                    self.mode = "json"
                    obs.ingress_upstream_fallbacks.inc()
                    log.info("lane %d: upstream has no batchframe "
                             "endpoint (handshake status %d); using the "
                             "JSON batch path", self.tenant, status)
                    return None
                self._backoff = min(2.0, self._backoff * 2 or 0.05)
                self._next_connect = now + self._backoff
                return None
            sock.settimeout(None)    # the reader blocks on acks forever
            self._had_channel = True
            self.chan = _Channel(self, sock, rfile)
            return self.chan

    def _encode_frame(self, batch: List[_PendingWrite]
                      ) -> Tuple[bytes, bytes]:
        """(auth_json, payload) of one request frame. Items ride as the
        same JSON dicts the /batch route takes (TTLs must resolve
        against the ENGINE clock; rids are assigned engine-side); the
        whole flush packs in ONE pack_multi call."""
        auth_json = b""
        if any("auth" in pw.item for pw in batch):
            auth_json = json.dumps(
                [pw.item.get("auth") for pw in batch]).encode()
        payload = native.pack_multi(
            [(0, b"\x00" + json.dumps(pw.item).encode())
             for pw in batch], batchframe.P_MULTI)
        return auth_json, payload

    def fan_acks(self, batch: List[_PendingWrite],
                 slots: List[Tuple[int, bytes]]) -> None:
        """Upstream acked (durable: results release after the engine
        round's fsync) — only NOW may any client see its ack. One
        formatter call materializes the whole flush's responses."""
        lease_s = self.ing.cfg.read_lease_ms / 1000.0
        if lease_s > 0:
            self.lease_until = time.monotonic() + lease_s
        now = time.perf_counter()
        outs = self.ing.fmt_responses(
            [(status, bytes(body)) for status, body in slots])
        sends = []
        for pw, (status, _body), out in zip(batch, slots, outs):
            obs.ingress_ack_ms.observe((now - pw.t0) * 1000.0)
            if status >= 400:
                obs.ingress_errors.inc()
            else:
                obs.ingress_acked.inc()
            sends.append((pw.conn, out))
        self.ing.post_send_many(sends)
        obs.ingress_inflight.dec()

    def fan_error(self, batch: List[_PendingWrite], status: int,
                  body: bytes) -> None:
        """Whole-flush failure: one formatted response, every rider."""
        out = self.ing.fmt_responses([(status, body)])[0]
        obs.ingress_errors.inc(len(batch))
        self.ing.post_send_many([(pw.conn, out) for pw in batch])
        obs.ingress_inflight.dec()

    def _flush_json(self, upstream, host, port,
                    batch: List[_PendingWrite]):
        """Round-10 fallback: one window -> ONE JSON POST
        /tenants/{t}/batch -> per-client fan-back. Returns the (possibly
        re-opened) upstream connection. Never raises and never retries:
        a batch that died after the upstream read its request MAY have
        committed, and re-sending it would double-apply POSTs and break
        CAS chains. The client that never got an ack owns the retry,
        exactly as with a direct engine."""
        if upstream is None and time.monotonic() < self._next_connect:
            self.fan_error(batch, 503, _err_body(
                "ingress upstream unavailable: reconnect backoff"))
            return None
        body = json.dumps(
            {"reqs": [pw.item for pw in batch]}).encode()
        path = f"/tenants/{self.tenant}/batch"
        try:
            if upstream is None:
                if self._backoff:
                    obs.ingress_upstream_reconnects.inc()
                upstream = http.client.HTTPConnection(
                    host, port, timeout=self.ing.cfg.request_timeout)
            upstream.request("POST", path, body=body,
                             headers={"Content-Type": "application/json"})
            resp = upstream.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise OSError(f"upstream batch status {resp.status}")
            results = json.loads(data)["results"]
            if len(results) != len(batch):
                raise OSError("upstream batch result count mismatch")
        except Exception as e:  # noqa: BLE001 — fans back per client
            try:
                if upstream is not None:
                    upstream.close()
            except OSError:
                pass
            self._backoff = min(2.0, self._backoff * 2 or 0.05)
            self._next_connect = time.monotonic() + self._backoff
            self.fan_error(batch, 503, _err_body(
                f"ingress upstream flush failed: {e}"))
            return None
        self._backoff = 0.0
        slots = []
        for res in results:
            if "error" in res:
                slots.append((res.get("status", 500),
                              json.dumps(res["error"]).encode() + b"\n"))
            else:
                slots.append((res.get("status", 200),
                              json.dumps(res["event"]).encode() + b"\n"))
        self.fan_acks(batch, slots)
        return upstream


# ---------------------------------------------------------------------------
# watch fan-out hub
# ---------------------------------------------------------------------------

class _HubSub:
    __slots__ = ("conn", "stream", "since")

    def __init__(self, conn: _Conn, stream: bool, since: int) -> None:
        self.conn = conn
        self.stream = stream
        self.since = since


class _HubStream:
    """One upstream watch stream fanned out to N downstream watchers."""

    def __init__(self, hub: "_Hub", key: tuple) -> None:
        self.hub = hub
        self.key = key                     # (tenant, path, recursive)
        self.subs: List[_HubSub] = []
        self.ring: deque = deque(maxlen=_RING_CAP)   # (index, bytes)
        self.stopped = False
        self.sock: Optional[socket.socket] = None
        self.thread = threading.Thread(
            target=self._reader, daemon=True,
            name=f"ingress-hub-{key[0]}{key[1]}")

    def _reader(self) -> None:
        ing = self.hub.ing
        host, port = _upstream_addr(ing.cfg.upstream)
        t, path, rec = self.key
        q = f"wait=true&stream=true&recursive={'true' if rec else 'false'}"
        conn = http.client.HTTPConnection(host, port, timeout=None)
        try:
            conn.request(
                "GET", f"/tenants/{t}/v2/keys{path}?{q}")
            self.sock = conn.sock
            resp = conn.getresponse()
            if resp.status != 200:
                raise OSError(f"upstream watch status {resp.status}")
            while not self.stopped:
                line = resp.readline()
                if not line:
                    raise OSError("upstream watch stream closed")
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                self._deliver(ev, line + b"\n")
        except Exception as e:  # noqa: BLE001 — fail every sub, not the tier
            if not self.stopped:
                log.warning("hub stream %s died: %s", self.key, e)
            self.hub.drop_stream(self, e)
        finally:
            # Only this thread may close the connection: other threads
            # sever it via sock.shutdown (see _close_stream).
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    def _deliver(self, ev: dict, raw: bytes) -> None:
        idx = int(ev.get("node", {}).get("modifiedIndex", 0) or 0)
        ing = self.hub.ing
        with self.hub.lock:
            self.ring.append((idx, raw))
            subs, self.subs = self.subs, []
            keep = []
            delivered = 0
            for s in subs:
                if not s.conn.open:
                    continue
                if s.since and idx and idx < s.since:
                    keep.append(s)
                    continue
                delivered += 1
                if s.stream:
                    ing.post_send(s.conn, _chunk(raw))
                    keep.append(s)
                else:
                    ing.post_send(s.conn, _response(
                        200, raw, extra={"X-Etcd-Index": str(idx)}))
                    try:
                        s.conn.subs.remove((self, s))
                    except ValueError:
                        pass
            self.subs = keep + self.subs
            if not self.subs and not self.stopped:
                # Last long-poll served: drop the upstream stream too,
                # or every once-watched key leaks a connection forever.
                self.hub._close_stream(self)
            if delivered:
                obs.ingress_hub_deliveries.inc(delivered)
                obs.ingress_hub_watchers.set(self.hub.watcher_count())


class _Hub:
    def __init__(self, ing: "Ingress") -> None:
        self.ing = ing
        self.lock = threading.Lock()
        self.streams: Dict[tuple, _HubStream] = {}

    def watcher_count(self) -> int:
        return sum(len(st.subs) for st in self.streams.values())

    def subscribe(self, conn: _Conn, tenant: int, path: str,
                  recursive: bool, stream: bool, since: int) -> bool:
        """Attach a downstream watcher; serve from the replay ring when
        its waitIndex is already covered (no upstream round trip).

        Returns False when `since` predates the ring's coverage: the
        ring only holds events seen since this hub stream opened, so
        serving an older waitIndex from it would silently skip history
        that direct etcd replays (or 401s EventIndexCleared on). The
        caller must forward such watches upstream verbatim instead."""
        key = (tenant, path, recursive)
        with self.lock:
            st = self.streams.get(key)
            if since and not (st is not None and st.ring
                              and st.ring[0][0]
                              and st.ring[0][0] <= since):
                return False
            if st is None:
                st = self.streams[key] = _HubStream(self, key)
                st.thread.start()
                obs.ingress_hub_streams.set(len(self.streams))
            if stream:
                # Headers first, BEFORE the sub registers — a live
                # delivery racing in from the reader thread must never
                # beat the status line onto the wire.
                self.ing.post_send(conn, (
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n"))
            if since:
                ready = [(i, raw) for i, raw in st.ring if i >= since]
                if ready:
                    if not stream:
                        i, raw = ready[0]
                        self.ing.post_send(conn, _response(
                            200, raw, extra={"X-Etcd-Index": str(i)}))
                        if not st.subs:
                            self._close_stream(st)
                        return
                    for _i, raw in ready:
                        self.ing.post_send(conn, _chunk(raw))
                    since = 0    # caught up; go live below
            sub = _HubSub(conn, stream, since)
            st.subs.append(sub)
            conn.subs.append((st, sub))
            obs.ingress_hub_watchers.set(self.watcher_count())
            return True

    def unsubscribe_conn(self, conn: _Conn) -> None:
        with self.lock:
            for st, sub in conn.subs:
                try:
                    st.subs.remove(sub)
                except ValueError:
                    pass
                if not st.subs:
                    self._close_stream(st)
            conn.subs.clear()
            obs.ingress_hub_watchers.set(self.watcher_count())

    def _close_stream(self, st: _HubStream) -> None:
        st.stopped = True
        self.streams.pop(st.key, None)
        obs.ingress_hub_streams.set(len(self.streams))
        try:
            if st.sock is not None:
                # shutdown, not close: close() leaves a reader already
                # blocked in recv blocked forever (and frees the fd for
                # reuse under it); shutdown unblocks it with EOF and the
                # reader thread closes its own connection on exit.
                st.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def drop_stream(self, st: _HubStream, err: Exception) -> None:
        """Upstream stream died: fail every subscriber loudly (a silent
        hub would turn a dead upstream into watchers that never fire)."""
        with self.lock:
            if self.streams.get(st.key) is st:
                self.streams.pop(st.key, None)
                obs.ingress_hub_streams.set(len(self.streams))
            subs, st.subs = st.subs, []
            for s in subs:
                if not s.conn.open:
                    continue
                if s.stream:
                    self.ing.post_send(s.conn, b"0\r\n\r\n",
                                       close_after=True)
                else:
                    self.ing.post_send(s.conn, _json_response(
                        503, {"errorCode": 300,
                              "message": "Raft Internal Error",
                              "cause": f"ingress upstream watch died: "
                                       f"{err}"}))
                try:
                    s.conn.subs.remove((st, s))
                except ValueError:
                    pass
            obs.ingress_hub_watchers.set(self.watcher_count())

    def stop(self) -> None:
        with self.lock:
            for st in list(self.streams.values()):
                self._close_stream(st)


# ---------------------------------------------------------------------------
# the ingress server
# ---------------------------------------------------------------------------

class Ingress:
    """The event-driven front + lanes + hub + upstream GET forwarders."""

    def __init__(self, cfg: IngressConfig) -> None:
        self.cfg = cfg
        self.use_native = cfg.use_native and native.HAVE_NATIVE_INGRESS
        self._scan = (native.scan_requests if self.use_native
                      else native._py_scan_requests)
        self._fmt = (native.format_responses if self.use_native
                     else native._py_format_responses)
        obs.ingress_native_enabled.set(1.0 if self.use_native else 0.0)
        self.lanes: Dict[int, _Lane] = {}
        self._lanes_lock = threading.Lock()
        self.hub = _Hub(self)
        self.sel = selectors.DefaultSelector()
        self._posted: deque = deque()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._stop = threading.Event()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((cfg.host, cfg.port))
        self._lsock.listen(4096)
        self._lsock.setblocking(False)
        self.port = self._lsock.getsockname()[1]
        self._thread: Optional[threading.Thread] = None
        # Small pool for upstream GET forwarding (reads must not block
        # the loop; they are not coalescable and just proxy through).
        self._fetchq: deque = deque()
        self._fetch_cv = threading.Condition()
        self._fetchers = [
            threading.Thread(target=self._fetcher, daemon=True,
                             name=f"ingress-fetch{i}") for i in range(4)]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self.sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        for t in self._fetchers:
            t.start()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ingress-loop")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self.hub.stop()
        with self._lanes_lock:
            for lane in self.lanes.values():
                lane.stop()
        with self._fetch_cv:
            self._fetch_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)

    @property
    def url(self) -> str:
        return f"http://{self.cfg.host}:{self.port}"

    # -- cross-thread completion hand-off -----------------------------------

    def post_send(self, conn: _Conn, data: bytes,
                  close_after: bool = False) -> None:
        """Queue bytes for a client from ANY thread; the loop owns every
        socket write (no per-connection locks, no interleaved sends)."""
        self._posted.append((conn, data, close_after))
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def post_send_many(self, sends: List[Tuple[_Conn, bytes]]) -> None:
        """post_send for a whole flush's fan-back: one wake byte, not N."""
        self._posted.extend((conn, data, False) for conn, data in sends)
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def fmt_responses(self, slots: List[Tuple[int, bytes]]) -> List[bytes]:
        """Materialize final HTTP responses for (status, body) slots —
        one ingresscore call per flush when the extension is built."""
        if self.use_native:
            obs.ingress_native_formatted.inc(len(slots))
        return self._fmt(slots)

    # -- the loop ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            for key, mask in self.sel.select(timeout=0.5):
                tag = key.data
                # One connection's failure (malformed input, handler
                # bug) must never escape and freeze the loop — it owns
                # every other connection on this ingress.
                try:
                    if tag == "accept":
                        self._accept()
                    elif tag == "wake":
                        try:
                            self._wake_r.recv(65536)
                        except OSError:
                            pass
                    else:
                        conn: _Conn = tag
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if conn.open and (mask & selectors.EVENT_WRITE):
                            self._flush_wbuf(conn)
                except Exception:  # noqa: BLE001 — close one conn, not all
                    log.exception("ingress loop: connection handler failed")
                    if isinstance(tag, _Conn):
                        self._close(tag)
            self._drain_posted()
        # teardown
        for key in list(self.sel.get_map().values()):
            if isinstance(key.data, _Conn):
                self._close(key.data)
        try:
            self.sel.unregister(self._lsock)
            self.sel.unregister(self._wake_r)
        except (KeyError, ValueError):
            pass
        self._lsock.close()
        self._wake_r.close()
        self._wake_w.close()
        self.sel.close()

    def _drain_posted(self) -> None:
        while self._posted:
            conn, data, close_after = self._posted.popleft()
            if not conn.open:
                continue
            try:
                conn.busy = False
                conn.wbuf += data
                if close_after:
                    conn.closing = True
                    conn.streaming = False   # the stream just ended
                self._flush_wbuf(conn)
                # A pipelined request may already be buffered.
                if conn.open and not conn.busy and not conn.streaming:
                    self._parse(conn)
            except Exception:  # noqa: BLE001 — close one conn, not all
                log.exception("ingress loop: posted-send handling failed")
                self._close(conn)

    def _accept(self) -> None:
        for _ in range(256):
            try:
                s, _addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            s.setblocking(False)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(s)
            self.sel.register(s, selectors.EVENT_READ, conn)

    def _close(self, conn: _Conn) -> None:
        if not conn.open:
            return
        conn.open = False
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.subs:
            self.hub.unsubscribe_conn(conn)
        for up in list(conn.fwd):
            # Sever any dedicated watch proxy's upstream socket so its
            # blocked readline unblocks and the thread exits. shutdown,
            # NOT close: close() neither unblocks a reader already in
            # recv nor is HTTPConnection.close() safe here — it grabs
            # the response buffer's lock the blocked reader holds, which
            # would deadlock this (the loop) thread. The proxy thread
            # closes its own connection on the way out.
            try:
                if up.sock is not None:
                    up.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        conn.fwd.clear()

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        conn.rbuf += data
        if not conn.busy and not conn.streaming:
            self._parse(conn)

    def _flush_wbuf(self, conn: _Conn) -> None:
        try:
            while conn.wbuf:
                n = conn.sock.send(conn.wbuf)
                if n <= 0:
                    break
                del conn.wbuf[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(conn)
            return
        if len(conn.wbuf) > _MAX_WBUF:
            # Backpressure: a stalled reader (slow watcher on a busy
            # key) must not grow ingress memory without bound — drop it.
            obs.ingress_slow_clients.inc()
            self._close(conn)
            return
        events = selectors.EVENT_READ
        if conn.wbuf:
            events |= selectors.EVENT_WRITE
        elif conn.closing and not conn.streaming:
            # A streaming watcher that asked Connection: close still
            # holds the stream open until it ends (0-chunk or hangup).
            self._close(conn)
            return
        try:
            self.sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError):
            pass

    # -- HTTP parse + dispatch ----------------------------------------------

    def _parse(self, conn: _Conn) -> None:
        """Drain complete pipelined requests off the read buffer — ONE
        scanner pass (ingresscore.c when built) emits every complete
        request at once; dispatch then pops them as the busy gate
        allows (≤1 outstanding request per connection)."""
        while conn.open and not conn.busy and not conn.streaming:
            if not conn.pending:
                if conn.perr:
                    self._scan_error(conn)
                    return
                if not conn.rbuf:
                    return
                reqs, consumed, err = self._scan(conn.rbuf)
                if consumed:
                    del conn.rbuf[:consumed]
                if reqs and self.use_native:
                    obs.ingress_native_scanned.inc(len(reqs))
                conn.pending.extend(reqs)
                conn.perr = err
                if not conn.pending:
                    if err:
                        self._scan_error(conn)
                    return
            (method, target, ctype, auth, close,
             body) = conn.pending.popleft()
            if close:
                conn.closing = True
            headers: Dict[str, str] = {}
            if ctype is not None:
                headers["content-type"] = ctype
            if auth is not None:
                headers["authorization"] = auth
            conn.busy = True
            try:
                self._dispatch(conn, method, target, headers, body)
            except Exception as e:  # noqa: BLE001 — client-controlled input
                # must never escape to the loop: 400 this connection only.
                log.warning("ingress dispatch failed for %s %s: %s",
                            method, target, e)
                if conn.open:
                    conn.busy = False
                    self._bad_request(conn, f"bad request: {e}")
                return

    def _scan_error(self, conn: _Conn) -> None:
        """A scanner error surfaced behind the already-emitted requests:
        act on it only once those have dispatched (here)."""
        err, conn.perr = conn.perr, 0
        if err == native.ING_EBADLINE:
            self._close(conn)
            return
        self._bad_request(conn, {
            native.ING_EBADLEN: "malformed Content-Length",
            native.ING_EBODY: "body too large",
            native.ING_EHEADERS: "headers too large",
        }.get(err, "bad request"))

    def _bad_request(self, conn: _Conn, msg: str) -> None:
        """400 + close THIS connection; the loop keeps serving the rest."""
        conn.rbuf.clear()       # never re-parse the poisoned bytes
        conn.pending.clear()
        conn.perr = 0
        conn.wbuf += _json_response(400, {"message": msg})
        conn.closing = True
        self._flush_wbuf(conn)

    def _reply(self, conn: _Conn, data: bytes) -> None:
        """Loop-thread synchronous reply to the CURRENT request."""
        conn.busy = False
        conn.wbuf += data
        self._flush_wbuf(conn)

    def _dispatch(self, conn: _Conn, method: str, target: str,
                  headers: Dict[str, str], body: bytes) -> None:
        path, _, query = target.partition("?")
        params = urllib.parse.parse_qs(query, keep_blank_values=True)
        if body and headers.get("content-type", "").startswith(
                "application/x-www-form-urlencoded"):
            for k, v in urllib.parse.parse_qs(
                    body.decode("latin-1"),
                    keep_blank_values=True).items():
                params[k] = v

        def p(name: str, default: str = "") -> str:
            v = params.get(name)
            return v[0] if v else default

        if path == "/health":
            self._reply(conn, _json_response(200, {"health": "true"}))
            return
        if path == "/metrics":
            self._reply(conn, self._metrics_response())
            return
        parts = path.split("/", 3)
        if len(parts) >= 3 and parts[1] == "tenants" and parts[2]:
            try:
                tenant = int(parts[2])
            except ValueError:
                self._reply(conn, _json_response(
                    404, {"message": f"no such tenant {parts[2]!r}"}))
                return
            rest = "/" + (parts[3] if len(parts) > 3 else "")
            if rest.startswith("/v2/keys"):
                key = rest[len("/v2/keys"):] or "/"
                key = posixpath.normpath("/" + key.lstrip("/"))
                if method in ("PUT", "POST", "DELETE"):
                    self._handle_write(conn, tenant, method, key, p,
                                       headers)
                    return
                if method == "GET":
                    if p("wait") == "true":
                        try:
                            since = int(p("waitIndex") or 0)
                        except ValueError:
                            self._reply(conn, _json_response(400, {
                                "errorCode": 203,
                                "message": "The given index in POST "
                                           "form is not a number"}))
                            return
                        recursive = p("recursive") == "true"
                        stream = p("stream") == "true"
                        if self.hub.subscribe(conn, tenant, key,
                                              recursive, stream, since):
                            if stream:
                                conn.streaming = True
                            return
                        # waitIndex predates the hub ring's coverage:
                        # forward upstream verbatim so history replay /
                        # 401 EventIndexCleared keep direct semantics.
                        if stream:
                            conn.streaming = True
                        self._forward_watch(conn, tenant, key, recursive,
                                            stream, since)
                        return
                    self._forward(conn, tenant, method, target,
                                  headers=headers)
                    return
        # Everything else (status, stats, engine surfaces) proxies
        # through unchanged — the ingress is transparent for them.
        self._forward(conn, None, method, target, body=body,
                      headers=headers)

    def _handle_write(self, conn: _Conn, tenant: int, method: str,
                      key: str, p, headers: Dict[str, str]) -> None:
        item = {"method": method, "path": key}
        if p("value"):
            item["value"] = p("value")
        if p("recursive") == "true":
            item["recursive"] = True
        auth = headers.get("authorization")
        if auth:
            # Batches share ONE upstream connection for many clients:
            # each slot carries its own client's credentials so the
            # engine's per-tenant security evaluates the real identity,
            # not the ingress's anonymous upstream socket.
            item["auth"] = auth
        if p("ttl"):
            try:
                item["ttl"] = int(p("ttl"))
            except ValueError:
                self._reply(conn, _json_response(400, {
                    "errorCode": 202,
                    "message": "The given TTL in POST form is not a "
                               "number"}))
                return
        if p("dir") == "true":
            item["dir"] = True
        if p("refresh") == "true":
            item["refresh"] = True
        if p("prevValue"):
            item["prevValue"] = p("prevValue")
        if p("prevIndex"):
            try:
                item["prevIndex"] = int(p("prevIndex"))
            except ValueError:
                self._reply(conn, _json_response(400, {
                    "errorCode": 203,
                    "message": "The given index in POST form is not a "
                               "number"}))
                return
        if p("prevExist"):
            item["prevExist"] = p("prevExist") == "true"
        size = sum(len(k) + len(str(v)) + 8 for k, v in item.items())
        self.lane(tenant).enqueue(_PendingWrite(conn, item, size))

    def lane(self, tenant: int) -> _Lane:
        lane = self.lanes.get(tenant)
        if lane is None:
            with self._lanes_lock:
                lane = self.lanes.get(tenant)
                if lane is None:
                    lane = self.lanes[tenant] = _Lane(self, tenant)
        return lane

    def _metrics_response(self) -> bytes:
        from etcd_tpu.utils.metrics import REGISTRY, fd_usage
        used, limit = fd_usage()
        extra = (
            "# HELP process_open_fds Number of open file descriptors.\n"
            "# TYPE process_open_fds gauge\n"
            f"process_open_fds {float(used)}\n"
            "# HELP process_max_fds Maximum number of open file "
            "descriptors.\n"
            "# TYPE process_max_fds gauge\n"
            f"process_max_fds {float(limit)}\n")
        return _response(200, (REGISTRY.expose() + extra).encode(),
                         ctype="text/plain; version=0.0.4")

    # -- upstream GET / passthrough forwarding --------------------------------

    def _forward(self, conn: _Conn, tenant: Optional[int], method: str,
                 target: str, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None) -> None:
        """Proxy a non-coalescable request upstream on a fetcher thread,
        carrying the client's Authorization/Content-Type (identity must
        survive the proxy hop or per-user ACLs break). Quorum GETs may
        be downgraded to local GETs under the lane's read lease (renewed
        by every upstream batch ack — a committed write proves the
        leader held quorum at ack time)."""
        if (tenant is not None and "quorum=true" in target
                and self.cfg.read_lease_ms > 0):
            lane = self.lane(tenant)
            if time.monotonic() < lane.lease_until:
                target = target.replace("quorum=true", "quorum=false")
                obs.ingress_lease_reads.inc()
        fwd_headers = {}
        for k in ("authorization", "content-type"):
            v = (headers or {}).get(k)
            if v:
                fwd_headers[k.title()] = v
        with self._fetch_cv:
            self._fetchq.append((conn, tenant, method, target, body,
                                 fwd_headers))
            self._fetch_cv.notify()

    def _forward_watch(self, conn: _Conn, tenant: int, path: str,
                       recursive: bool, stream: bool, since: int) -> None:
        """A watch whose waitIndex the hub ring cannot cover gets its own
        upstream connection on a dedicated thread (NOT the fetcher pool:
        an unfired watch blocks until its event, and a handful of these
        would starve every plain GET). Upstream then replays from event
        history, answers 401 EventIndexCleared, or blocks — exactly the
        direct-path semantics the ring cannot reproduce."""
        q = (f"wait=true&waitIndex={since}"
             f"&recursive={'true' if recursive else 'false'}")
        if stream:
            q += "&stream=true"
        target = f"/tenants/{tenant}/v2/keys{path}?{q}"
        threading.Thread(target=self._watch_proxy,
                         args=(conn, target, stream), daemon=True,
                         name="ingress-watch-fwd").start()

    def _watch_proxy(self, conn: _Conn, target: str, stream: bool) -> None:
        host, port = _upstream_addr(self.cfg.upstream)
        up = http.client.HTTPConnection(host, port, timeout=None)
        conn.fwd.append(up)      # _close severs this to unblock us
        sent_headers = False
        try:
            up.request("GET", target)
            resp = up.getresponse()
            if not stream or resp.status != 200:
                data = resp.read()
                hdrs = {k: v for k, v in resp.getheaders()
                        if k.lower().startswith("x-etcd")
                        or k.lower().startswith("x-raft")}
                ctype = resp.getheader("Content-Type", "application/json")
                self.post_send(conn, _response(resp.status, data,
                                               ctype=ctype, extra=hdrs),
                               close_after=stream)
                return
            self.post_send(conn, (
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"))
            sent_headers = True
            while conn.open:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if line:
                    self.post_send(conn, _chunk(line + b"\n"))
            if conn.open:
                self.post_send(conn, b"0\r\n\r\n", close_after=True)
        except Exception as e:  # noqa: BLE001 — fail this conn only
            if conn.open and sent_headers:
                self.post_send(conn, b"0\r\n\r\n", close_after=True)
            elif conn.open:
                self.post_send(conn, _json_response(503, {
                    "errorCode": 300, "message": "Raft Internal Error",
                    "cause": f"ingress upstream watch failed: {e}"}))
        finally:
            try:
                up.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            try:
                conn.fwd.remove(up)
            except ValueError:
                pass

    def _fetcher(self) -> None:
        upstream: Optional[http.client.HTTPConnection] = None
        host, port = _upstream_addr(self.cfg.upstream)
        while True:
            with self._fetch_cv:
                while not self._fetchq and not self._stop.is_set():
                    self._fetch_cv.wait(0.5)
                if self._stop.is_set():
                    return
                conn, tenant, method, target, body, fwd_headers = \
                    self._fetchq.popleft()
            if not conn.open:
                continue
            try:
                if upstream is None:
                    upstream = http.client.HTTPConnection(
                        host, port, timeout=self.cfg.request_timeout)
                upstream.request(method, target, body=body or None,
                                 headers=fwd_headers)
                resp = upstream.getresponse()
                data = resp.read()
                hdrs = {k: v for k, v in resp.getheaders()
                        if k.lower().startswith("x-etcd")
                        or k.lower().startswith("x-raft")}
                ctype = resp.getheader("Content-Type",
                                       "application/json")
                if (tenant is not None and resp.status == 200
                        and "quorum=true" in target
                        and self.cfg.read_lease_ms > 0):
                    # A served quorum read is itself a leadership proof.
                    self.lane(tenant).lease_until = (
                        time.monotonic()
                        + self.cfg.read_lease_ms / 1000.0)
                self.post_send(conn, _response(resp.status, data,
                                               ctype=ctype, extra=hdrs))
            except Exception as e:  # noqa: BLE001 — per-request fan-back
                try:
                    if upstream is not None:
                        upstream.close()
                except OSError:
                    pass
                upstream = None
                self.post_send(conn, _json_response(503, {
                    "errorCode": 300, "message": "Raft Internal Error",
                    "cause": f"ingress upstream fetch failed: {e}"}))


# ---------------------------------------------------------------------------
# CLI: one ingress process (scripts/ingress_serve.py runs N of these)
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        description="coalescing ingress tier (one process)")
    ap.add_argument("--upstream", required=True,
                    help="engine front or pool router base URL")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--flush-max-requests", type=int, default=1024)
    ap.add_argument("--flush-max-bytes", type=int, default=1 << 20)
    ap.add_argument("--max-inflight", type=int, default=1)
    ap.add_argument("--flush-window", type=int, default=4,
                    help="pipelined flushes per lane on the binary "
                         "upstream channel")
    ap.add_argument("--upstream-mode", default="auto",
                    choices=("auto", "frame", "json"),
                    help="binary batchframe channel, JSON POSTs, or "
                         "auto-detect per lane")
    ap.add_argument("--no-native", action="store_true",
                    help="force the pure-Python request scan / response "
                         "format hot loop")
    ap.add_argument("--read-lease-ms", type=int, default=0)
    args = ap.parse_args(argv)
    ing = Ingress(IngressConfig(
        upstream=args.upstream, host=args.host, port=args.port,
        flush_max_requests=args.flush_max_requests,
        flush_max_bytes=args.flush_max_bytes,
        max_inflight=args.max_inflight,
        flush_window=args.flush_window,
        upstream_mode=args.upstream_mode,
        use_native=(not args.no_native
                    and os.environ.get("ETCD_INGRESS_NO_NATIVE") != "1"),
        read_lease_ms=args.read_lease_ms))
    ing.start()
    print(json.dumps({"port": ing.port, "pid": os.getpid(),
                      "upstream": args.upstream}), flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    ing.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
