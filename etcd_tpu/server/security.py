"""v2 auth ("security"): users, roles, and prefix ACLs stored through the
server's own consensus path.

Behavioral equivalent of reference etcdserver/security/security.go +
security_requests.go: state lives in the replicated v2 store under
StorePermsPrefix "/2" (`/2/users/<name>`, `/2/roles/<name>`, `/2/enabled`)
and every mutation is an ordinary consensus write through a `doer`
(security.go:66-68), so auth state is consistent cluster-wide. Root role is
virtual and almighty (security.go:29-37); the guest role governs
unauthenticated access and is auto-created permissive on enable
(security.go:39-46, 368-375); ACLs are glob-free prefix patterns where a
trailing '*' matches any suffix (simpleMatch/prefixMatch
security.go:546-557).

Passwords: the reference uses bcrypt (security.go:170-175). bcrypt isn't in
this environment, so hashes use PBKDF2-HMAC-SHA256 (stdlib) in a tagged
"pbkdf2$<iters>$<salt>$<hex>" format — same role in the design: slow, salted,
one-way.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from etcd_tpu import errors
from etcd_tpu.server.request import Request

log = logging.getLogger("security")

STORE_PERMS_PREFIX = "/2"       # reference security.go:21
ROOT_ROLE = "root"
GUEST_ROLE = "guest"

# pbkdf2 is the bcrypt stand-in (no bcrypt in the image); the iteration
# count is tagged into each stored hash so existing hashes keep verifying
# when the default changes. 600k matches current OWASP guidance for
# pbkdf2-sha256; tests override via ETCD_PBKDF2_ITERS to stay fast.
_PBKDF2_ITERS = int(os.environ.get("ETCD_PBKDF2_ITERS", "600000"))


def hash_password(password: str, iters: Optional[int] = None) -> str:
    if iters is None:
        iters = _PBKDF2_ITERS
    salt = os.urandom(16).hex()
    h = hashlib.pbkdf2_hmac("sha256", password.encode(), salt.encode(),
                            iters).hex()
    return f"pbkdf2${iters}${salt}${h}"


# Verification cache: basic-auth re-verifies on EVERY request (the
# reference runs bcrypt per request too, security.go usersEqual), and at
# 600k iterations an uncached check is hundreds of ms of CPU per request
# on a small host. The cache key is itself a SMALL pbkdf2 of
# (stored-hash, password) — ~1k iterations, ~1 ms — NOT a bare sha256:
# a process-memory disclosure of the key must not hand an attacker a
# GPU-speed fingerprint of an in-use password (bare sha256 would undo
# the 600k-iteration hardening by ~10^6x for recently-auth'd accounts).
# Bounded; cleared wholesale when full.
_VERIFY_CACHE: dict = {}
_VERIFY_CACHE_MAX = 1024
_CACHE_KEY_ITERS = 1000


def check_password(stored: str, password: str) -> bool:
    try:
        tag, iters, salt, want = stored.split("$")
        if tag != "pbkdf2":
            return False
        ck = hashlib.pbkdf2_hmac("sha256", password.encode(),
                                 f"cache\x00{stored}".encode(),
                                 _CACHE_KEY_ITERS)
        hit = _VERIFY_CACHE.get(ck)
        if hit is not None:
            return hit
        got = hashlib.pbkdf2_hmac("sha256", password.encode(), salt.encode(),
                                  int(iters)).hex()
        ok = hmac.compare_digest(got, want)
        if len(_VERIFY_CACHE) >= _VERIFY_CACHE_MAX:
            _VERIFY_CACHE.clear()
        _VERIFY_CACHE[ck] = ok
        return ok
    except (ValueError, AttributeError):
        return False


class SecurityError(Exception):
    """reference security.Error — surfaced as HTTP 400/401 by the API."""


def simple_match(pattern: str, key: str) -> bool:
    if pattern.endswith("*"):
        return key.startswith(pattern[:-1])
    return key == pattern


def prefix_match(pattern: str, key: str) -> bool:
    if not pattern.endswith("*"):
        return False
    return key.startswith(pattern[:-1])


@dataclass
class RWPermission:
    read: List[str] = field(default_factory=list)
    write: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: dict) -> "RWPermission":
        return RWPermission(list(d.get("read") or []),
                            list(d.get("write") or []))

    def to_dict(self) -> dict:
        return {"read": sorted(self.read), "write": sorted(self.write)}

    def grant(self, n: "RWPermission") -> "RWPermission":
        read, write = set(self.read), set(self.write)
        for r in n.read:
            if r in read:
                raise SecurityError(
                    f"security-merging: Granting duplicate read permission "
                    f"{r}")
            read.add(r)
        for w in n.write:
            if w in write:
                raise SecurityError(
                    f"security-merging: Granting duplicate write permission "
                    f"{w}")
            write.add(w)
        return RWPermission(sorted(read), sorted(write))

    def revoke(self, n: "RWPermission") -> "RWPermission":
        read, write = set(self.read), set(self.write)
        for r in n.read:
            if r not in read:
                log.info("revoking ungranted read permission %s", r)
                continue
            read.remove(r)
        for w in n.write:
            if w not in write:
                log.info("revoking ungranted write permission %s", w)
                continue
            write.remove(w)
        return RWPermission(sorted(read), sorted(write))

    def has_access(self, key: str, write: bool) -> bool:
        pats = self.write if write else self.read
        return any(simple_match(p, key) for p in pats)

    def has_recursive_access(self, key: str, write: bool) -> bool:
        pats = self.write if write else self.read
        return any(prefix_match(p, key) for p in pats)


@dataclass
class Role:
    role: str
    kv: RWPermission = field(default_factory=RWPermission)

    @staticmethod
    def from_dict(d: dict) -> "Role":
        perms = d.get("permissions") or {}
        return Role(d.get("role", ""),
                    RWPermission.from_dict(perms.get("kv") or {}))

    def to_dict(self) -> dict:
        return {"role": self.role, "permissions": {"kv": self.kv.to_dict()}}

    def merge(self, grant: Optional[dict], revoke: Optional[dict]) -> "Role":
        out = Role(self.role, RWPermission(list(self.kv.read),
                                           list(self.kv.write)))
        if grant is not None:
            out.kv = out.kv.grant(
                RWPermission.from_dict((grant.get("kv") or {})))
        if revoke is not None:
            out.kv = out.kv.revoke(
                RWPermission.from_dict((revoke.get("kv") or {})))
        return out

    def has_key_access(self, key: str, write: bool) -> bool:
        if self.role == ROOT_ROLE:
            return True
        return self.kv.has_access(key, write)

    def has_recursive_access(self, key: str, write: bool) -> bool:
        if self.role == ROOT_ROLE:
            return True
        return self.kv.has_recursive_access(key, write)


ROOT_ROLE_OBJ = Role(ROOT_ROLE, RWPermission(["*"], ["*"]))
GUEST_ROLE_OBJ = Role(GUEST_ROLE, RWPermission(["*"], ["*"]))


@dataclass
class User:
    user: str
    password: str = ""          # stored hashed
    roles: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: dict) -> "User":
        return User(d.get("user", ""), d.get("password", ""),
                    sorted(d.get("roles") or []))

    def to_dict(self, with_password: bool = True) -> dict:
        d = {"user": self.user, "roles": sorted(self.roles)}
        if with_password:
            d["password"] = self.password
        return d

    def merge(self, password: str, grant: List[str],
              revoke: List[str]) -> "User":
        """reference User.Merge security.go:405-430."""
        out = User(self.user, self.password, [])
        if password:
            out.password = hash_password(password)
        roles = set(self.roles)
        for g in grant or []:
            if g in roles:
                log.info("granting duplicate role %s for user %s", g,
                         self.user)
                continue
            roles.add(g)
        for r in revoke or []:
            if r not in roles:
                log.info("revoking ungranted role %s for user %s", r,
                         self.user)
                continue
            roles.remove(r)
        out.roles = sorted(roles)
        return out

    def check_password(self, password: str) -> bool:
        return check_password(self.password, password)


class SecurityStore:
    """Users/roles/enabled flag via the server's consensus path (the `doer`
    seam, reference security.go:66-68, 98-103)."""

    def __init__(self, server) -> None:
        self.server = server
        self._ensured = False

    # -- raw resource plumbing (security_requests.go) -----------------------

    def _do(self, method: str, path: str, val: str = "",
            prev_exist: Optional[bool] = None, dir: bool = False):
        return self.server.do(Request(
            method=method, path=STORE_PERMS_PREFIX + path, val=val, dir=dir,
            prev_exist=prev_exist))

    def _get(self, path: str):
        # Local (non-quorum) read, like the reference's requestResource
        # plain GETs (security_requests.go:86-97): auth state is served from
        # the local replica, so the gate costs no consensus round-trip and
        # keeps working during leader loss.
        return self.server.do(Request(method="GET",
                                      path=STORE_PERMS_PREFIX + path))

    def ensure_dirs(self) -> None:
        """Create /2, /2/users/, /2/roles/, /2/enabled=false once
        (reference ensureSecurityDirectories security_requests.go:28-73)."""
        if self._ensured:
            return
        for res in ("", "/users", "/roles"):
            try:
                self._do("PUT", res or "/", dir=True, prev_exist=False)
            except errors.EtcdError as e:
                if e.code != errors.ECODE_NODE_EXIST:
                    raise
        try:
            self._do("PUT", "/enabled", val="false", prev_exist=False)
        except errors.EtcdError as e:
            if e.code != errors.ECODE_NODE_EXIST:
                raise
        self._ensured = True

    # -- users --------------------------------------------------------------

    def all_users(self) -> List[str]:
        try:
            ev = self._get("/users")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                return []
            raise
        return sorted(n.key.rsplit("/", 1)[-1]
                      for n in (ev.node.nodes or []))

    def get_user(self, name: str) -> User:
        try:
            ev = self._get(f"/users/{name}")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                raise SecurityError(f"User {name} does not exist.")
            raise
        u = User.from_dict(json.loads(ev.node.value))
        if u.user == "root" and ROOT_ROLE not in u.roles:
            # root always carries the root role (security.go:155-157)
            u.roles = sorted(u.roles + [ROOT_ROLE])
        return u

    def create_user(self, name: str, password: str,
                    roles: Optional[List[str]] = None) -> User:
        if not password:
            raise SecurityError(
                f"Cannot create user {name} with an empty password")
        self.ensure_dirs()
        u = User(name, hash_password(password), sorted(roles or []))
        try:
            self._do("PUT", f"/users/{name}",
                     val=json.dumps(u.to_dict()), prev_exist=False)
        except errors.EtcdError as e:
            if e.code == errors.ECODE_NODE_EXIST:
                raise SecurityError(f"User {name} already exists.")
            raise
        log.info("security: created user %s", name)
        return u

    def update_user(self, name: str, password: str = "",
                    grant: Optional[List[str]] = None,
                    revoke: Optional[List[str]] = None) -> User:
        old = self.get_user(name)  # raises if missing
        new = old.merge(password, grant or [], revoke or [])
        if new.to_dict() == old.to_dict():
            if grant or revoke:
                raise SecurityError(
                    "User not updated. Grant/Revoke lists didn't match any "
                    "current roles.")
            raise SecurityError(
                "User not updated. Use Grant/Revoke/Password to update the "
                "user.")
        self._do("PUT", f"/users/{name}", val=json.dumps(new.to_dict()),
                 prev_exist=True)
        log.info("security: updated user %s", name)
        return new

    def create_or_update_user(self, name: str, password: str = "",
                              roles: Optional[List[str]] = None,
                              grant=None, revoke=None) -> Tuple[User, bool]:
        """reference CreateOrUpdateUser security.go:161-169: a fresh user
        takes the literal roles list; an existing one only moves via
        grant/revoke (Roles is nil'd on the update path)."""
        try:
            self.get_user(name)
        except SecurityError:
            return self.create_user(name, password, roles), True
        return self.update_user(name, password, grant, revoke), False

    def delete_user(self, name: str) -> None:
        if self.enabled() and name == "root":
            raise SecurityError(
                "Cannot delete root user while security is enabled.")
        try:
            self._do("DELETE", f"/users/{name}")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                raise SecurityError(f"User {name} doesn't exist.")
            raise
        log.info("security: deleted user %s", name)

    # -- roles --------------------------------------------------------------

    def all_roles(self) -> List[str]:
        names = [GUEST_ROLE, ROOT_ROLE]
        try:
            ev = self._get("/roles")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                return sorted(names)
            raise
        names.extend(n.key.rsplit("/", 1)[-1] for n in (ev.node.nodes or []))
        return sorted(set(names))

    def get_role(self, name: str) -> Role:
        if name == ROOT_ROLE:
            return ROOT_ROLE_OBJ
        try:
            ev = self._get(f"/roles/{name}")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                raise SecurityError(f"Role {name} does not exist.")
            raise
        return Role.from_dict(json.loads(ev.node.value))

    def create_role(self, role: Role) -> None:
        if role.role == ROOT_ROLE:
            raise SecurityError(
                f"Cannot modify role {role.role}: is root role.")
        self.ensure_dirs()
        try:
            self._do("PUT", f"/roles/{role.role}",
                     val=json.dumps(role.to_dict()), prev_exist=False)
        except errors.EtcdError as e:
            if e.code == errors.ECODE_NODE_EXIST:
                raise SecurityError(f"Role {role.role} already exists.")
            raise
        log.info("security: created new role %s", role.role)

    def update_role(self, name: str, grant: Optional[dict],
                    revoke: Optional[dict]) -> Role:
        if name == ROOT_ROLE:
            raise SecurityError(f"Cannot modify role {name}: is root role.")
        old = self.get_role(name)
        new = old.merge(grant, revoke)
        if new.to_dict() == old.to_dict():
            if grant or revoke:
                raise SecurityError(
                    "Role not updated. Grant/Revoke lists didn't match any "
                    "current permissions.")
            raise SecurityError(
                "Role not updated. Use Grant/Revoke to update the role.")
        self._do("PUT", f"/roles/{name}", val=json.dumps(new.to_dict()),
                 prev_exist=True)
        log.info("security: updated role %s", name)
        return new

    def create_or_update_role(self, name: str, permissions: Optional[dict],
                              grant: Optional[dict],
                              revoke: Optional[dict]) -> Tuple[Role, bool]:
        try:
            self.get_role(name)
        except SecurityError:
            r = Role.from_dict({"role": name,
                                "permissions": permissions or {}})
            self.create_role(r)
            return r, True
        return self.update_role(name, grant, revoke), False

    def delete_role(self, name: str) -> None:
        if name == ROOT_ROLE:
            raise SecurityError(
                f"Cannot modify role {name}: is superuser role.")
        try:
            self._do("DELETE", f"/roles/{name}")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                raise SecurityError(f"Role {name} doesn't exist.")
            raise
        log.info("security: deleted role %s", name)

    # -- enable/disable ------------------------------------------------------

    def enabled(self) -> bool:
        try:
            ev = self._get("/enabled")
        except errors.EtcdError as e:
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                return False  # never configured
            raise  # anything else must DENY upstream, not fail open
        return ev.node.value == "true"

    def enable(self) -> None:
        """reference EnableSecurity security.go:358-381: needs a root user;
        auto-creates a permissive guest role if absent."""
        if self.enabled():
            raise SecurityError("already enabled")
        self.ensure_dirs()
        try:
            self.get_user("root")
        except SecurityError:
            raise SecurityError("No root user available, please create one")
        try:
            self.get_role(GUEST_ROLE)
        except SecurityError:
            log.info("security: no guest role access found, creating default")
            self.create_role(GUEST_ROLE_OBJ)
        self._do("PUT", "/enabled", val="true", prev_exist=True)
        log.info("security: enabled security")

    def disable(self) -> None:
        if not self.enabled():
            raise SecurityError("already disabled")
        self._do("PUT", "/enabled", val="false", prev_exist=True)
        log.info("security: disabled security")
