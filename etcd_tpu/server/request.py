"""The replicated command (reference etcdserverpb.Request).

Every client mutation becomes one of these, is serialized into a raft entry,
and is applied deterministically on every member (reference
etcdserver/server.go:766-820 applyRequest). Encoding is canonical JSON
(sorted keys, no whitespace) — deterministic and debuggable; the consensus
hot path never touches these bytes (they ride the host log store).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

METHOD_GET = "GET"
METHOD_PUT = "PUT"
METHOD_POST = "POST"
METHOD_DELETE = "DELETE"
METHOD_QGET = "QGET"
METHOD_SYNC = "SYNC"
METHOD_V3 = "V3"        # v3 op (the `v3` field) through the same log


@dataclass(frozen=True)
class Request:
    id: int = 0
    method: str = METHOD_GET
    path: str = ""
    val: str = ""
    dir: bool = False
    prev_value: str = ""
    prev_index: int = 0
    prev_exist: Optional[bool] = None   # tri-state (reference *bool)
    expiration: Optional[float] = None  # absolute unix seconds; None = keep forever
    wait: bool = False
    since: int = 0
    recursive: bool = False
    sorted: bool = False
    quorum: bool = False
    stream: bool = False
    time: float = 0.0                   # SYNC: the leader's cutoff timestamp
    refresh: bool = False               # TTL refresh without value change
    v3: Optional[dict] = None           # METHOD_V3 payload (server/v3.py)

    def encode(self) -> bytes:
        # self.__dict__ instead of dataclasses.asdict: asdict deep-copies
        # recursively (19 internal calls per request) and was the single
        # hottest host function in the serving profile; the fields here are
        # all scalars except `v3` (a dict the apply path treats as opaque
        # JSON), so a shallow copy is equivalent.
        d = {k: v for k, v in self.__dict__.items()
             if v not in (None, "", 0, 0.0, False)}
        d["id"] = self.id
        d["method"] = self.method
        if self.prev_exist is not None:
            d["prev_exist"] = self.prev_exist
        return json.dumps(d, sort_keys=True, separators=(",", ":")).encode()

    @staticmethod
    def decode(data: bytes) -> "Request":
        d = json.loads(data.decode())
        return Request(**d)
