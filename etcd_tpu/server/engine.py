"""MultiEngine: the batched MultiNode host engine — G Raft groups served
from ONE TPU kernel, the north star's serving path.

This is the integrated run loop the reference implements per-process in
raft.MultiNode (raft/multinode.go:166-322) + raftNode (etcdserver/raft.go:
112-172), re-expressed for the batched kernel (etcd_tpu/ops/kernel.py):

  one engine round =
    batch proposals -> ASYNC kernel.step dispatch (ONE XLA program for all
    G x P) -> flush the PREVIOUS round while the device computes: hand the
    round record to the WAL-writer compartment (walwriter.WALWriter, which
    group-commits queued rounds with ONE fsync on its own thread[s]), then
    hand committed entries to the applier pool — workers apply to the
    per-group stores and trigger client waiters only after the writer's
    durability watermark passes the round's ticket (acks strictly follow
    their round's fsync — the doc.go:31-39 ordering contract, enforced by
    GATING rather than inline ordering; the pipeline overlap is the
    batched form of the reference's apply/persist pipeline,
    etcdserver/raft.go:112-172) -> read back state deltas -> consume
    need_host flags (snapshot-install lagging followers via host-side
    state surgery). On the single-host crash model, letting round k+1's
    device step start before round k's fsync completes is safe: a crash
    truncates the WAL at a round boundary no client ever observed (applies
    may run ahead of durability, but acks never do, and in-memory store
    state dies with the process), and device state never survives a crash
    anyway.

Entry payloads never touch the device: the kernel commits (index, term)
metadata; payloads live in the host log store keyed (group, index, term) —
the Raft log-matching invariant makes that key unique, so leader turnover
overwrites at an index can never alias a committed payload. Leader no-op
entries are simply absent from the payload store and skip application.

Crash model: ALL P peer slots of a group live in this process, so a crash
is a whole-cluster crash — restart reconstructs every slot from the newest
checkpoint + WAL replay at the last durable round boundary. Nothing after
that boundary was ever acked to a client (applies happen after the WAL
fsync), so the restart is externally indistinguishable from a crash of a
real P-member cluster at that instant. In the multi-host deployment (peers
axis sharded over the mesh, parallel/mesh.py) each host persists only its
own slots; this engine is the single-host/multi-tenant serving path.

Membership changes are committed entries (reference multinode.go:181-218
CreateGroup-/RemoveGroup-at-commit semantics): applying one flips a bit in
the device peer_mask and resets the affected progress column; a joining
empty slot is then caught up by the leader (direct appends while within the
ring window, host snapshot-install beyond it).
"""
from __future__ import annotations

import json
import logging
import queue
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from etcd_tpu import errors
from etcd_tpu.server import obs as obs_mod
from etcd_tpu.server.enginewal import (CONF_ADD, CONF_REMOVE, EngineWAL,
                                       RoundRecord, b64_np, np_b64)
from etcd_tpu.server.walwriter import WALWriter
from etcd_tpu.utils import metrics
from etcd_tpu.server.request import (METHOD_DELETE, METHOD_GET, METHOD_POST,
                                     METHOD_PUT, METHOD_QGET, METHOD_SYNC,
                                     Request)
from etcd_tpu.store import new_store
from etcd_tpu.store.event import LazyWriteEvent
from etcd_tpu.utils import idutil
from etcd_tpu.utils.wait import Wait

log = logging.getLogger("etcd_tpu.engine")

# Payload tags (first byte of every entry payload).
P_REQ = 0x00    # etcd v2 Request (JSON)
P_CONF = 0x01   # membership change (JSON {"id", "op", "slot"})
P_MULTI = 0x02  # batched Requests: u32 count, then (u32 len, Request JSON)*

_LEADER = 2  # ops.state.LEADER (kept in sync; imported lazily with jax)


try:
    from etcd_tpu.native.walcodec import pack_multi as _c_pack_multi
except ImportError:          # pure-Python fallback (un-built tree)
    _c_pack_multi = None


def _pack_entry(items: List[tuple]) -> bytes:
    """One log entry's payload from its coalesced (rid, tagged-payload,
    ...) items: singletons keep their original tagged bytes (P_REQ/P_CONF,
    replay-compatible with pre-batching WALs); multi-request entries pack
    as P_MULTI + u32 count + (u32 len + Request JSON)*. The C packer
    (walcodec.pack_multi, byte-identical — tests/test_native.py) carries
    the deep-queue stage phase; the Python body is the un-built-tree
    fallback and the reference implementation."""
    if len(items) == 1:
        return items[0][1]
    if _c_pack_multi is not None:
        return _c_pack_multi(items, P_MULTI)
    out = [bytes([P_MULTI]), struct.pack("<I", len(items))]
    for it in items:
        blob = it[1][1:]            # strip the P_REQ tag
        out.append(struct.pack("<I", len(blob)))
        out.append(blob)
    return b"".join(out)


def _unpack_multi(payload: bytes) -> List[bytes]:
    (n,) = struct.unpack_from("<I", payload, 1)
    off = 5
    blobs = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", payload, off)
        off += 4
        blobs.append(payload[off:off + ln])
        off += ln
    return blobs


class EngineViolation(RuntimeError):
    """A consensus safety violation detected by the kernel (NH_VIOLATION:
    an append conflicted with a committed entry — the condition the
    reference panics on in log.maybeAppend). The engine dumps the affected
    groups' state and refuses to continue; state after this point cannot
    be trusted."""


@dataclass
class EngineConfig:
    groups: int
    peers: int
    data_dir: str
    window: int = 32
    max_ents: int = 8
    election_tick: int = 10
    heartbeat_tick: int = 3
    fsync: bool = True
    checkpoint_rounds: int = 2048     # rounds between full checkpoints
    request_timeout: float = 5.0
    # How often the host scans tenant stores for DUE TTL expirations and
    # stages a replicated SYNC into those groups (reference SyncTicker,
    # etcdserver/server.go:667-681; expiry must ride the log so replay
    # after restart deletes identically). 0 disables.
    sync_interval: float = 0.5
    # Max client requests coalesced into ONE log entry (group commit). The
    # device commits (index, term) metadata only, so entry payloads are
    # free to carry many requests — this is what lets a hot tenant drain
    # max_ents*batch_max writes per round while the on-device ring stays
    # statically shaped (the Zipf-skew answer; the reference's analogue is
    # batching many Ready entries into one WAL fsync, wal.go:459-487).
    # The REAL cap is bytes (batch_bytes, mirroring the reference's 1MB
    # maxSizePerMsg, etcdserver/raft.go:48): a hot tenant's admission
    # scales with its queue depth up to ~max_ents MB/round instead of
    # pinning at a fixed request count.
    batch_max: int = 4096
    batch_bytes: int = 1 << 20
    round_interval: float = 0.0       # seconds between rounds (0 = flat out)
    ticks_per_round: int = 1          # logical clock rate
    stagger: bool = True              # deterministic fast first election
    initial_peers: Optional[int] = None  # active slots at fresh boot (<= peers)
    # Tenants (groups) provisioned at fresh boot. None = all `groups` (the
    # pre-lifecycle behavior); smaller values leave the rest of the pool
    # inactive (peer_mask all-false: no elections, no ticks) for runtime
    # create_tenant()/remove_tenant() — the engine's CreateGroup/
    # RemoveGroup (reference raft/multinode.go:181-218), without
    # recompilation: the kernel shape is the POOL, liveness is the mask.
    initial_tenants: Optional[int] = None
    # Optional jax.sharding.Mesh with ("groups", "peers") axes
    # (parallel/mesh.py): the kernel state shards over it and the per-round
    # message routing becomes an all_to_all over the "peers" mesh axis —
    # the multi-chip serving path. None = single-device arrays.
    mesh: Any = None
    # Store applies + client acks run on a dedicated applier thread,
    # decoupling the round cadence (device step + WAL fsync + diff) from
    # the O(committed requests) Python apply work — the engine's version
    # of the reference's separate apply goroutine (etcdserver/raft.go:
    # 112-172 hands committed entries to the server loop and only waits
    # at the NEXT Ready). False = apply inline each round (deterministic
    # single-thread mode).
    pipeline_applies: bool = True
    # Backpressure: how many rounds of committed-but-unapplied work may
    # queue at the applier before the round loop blocks. Bounds ack
    # latency at ~(this+1) x apply-time-per-round under saturation.
    # With applier_shards > 1 this bounds the DEEPEST shard's backlog,
    # not the sum — one hot shard cannot borrow the others' budget.
    apply_queue_rounds: int = 2
    # Compartmentalized applier pool (PAPERS.md "Scaling Replicated
    # State Machines with Compartmentalization"): partition each round's
    # committed-entry view by tenant range into this many shards, each
    # applied+acked by its own worker thread. storecore.c releases the
    # GIL around batched mutations and every shard owns a disjoint set
    # of tenant stores, so K workers make real parallel progress on a
    # multi-core box while per-group apply order stays FIFO (a group
    # lives in exactly one shard). 1 = today's single-applier behavior.
    applier_shards: int = 1
    # WAL-writer compartment (walwriter.WALWriter): the round loop hands
    # each non-empty RoundRecord to a dedicated writer stage and steps
    # the device ahead; the writer group-commits queued rounds (ONE
    # fsync covers every round queued when it starts) and publishes a
    # durability watermark that applier workers gate acks on — fsync
    # leaves the round loop's critical path without weakening the
    # ack-after-fsync contract. False = the pre-compartment behavior:
    # append+fsync inline in the round loop before applies (rounds that
    # carry conf flips do this regardless — device surgery must follow
    # a durable record).
    pipeline_wal: bool = True
    # Per-tenant-range WAL segment streams (aligned with applier_shards
    # ranges): each RoundRecord splits into per-range sub-records
    # appended to its range's own stream by its own writer thread, so S
    # fsyncs proceed in parallel on a multi-core box. Replay reassembles
    # the streams at the consistent round boundary (min over stream
    # tails) and truncates whole records beyond it. 1 = one stream, in
    # the pre-compartment root-dir layout (byte-compatible). The value
    # is pinned in geometry.json; an existing dir may go 1 -> S once
    # (the root stream freezes as legacy history) but never change
    # between sharded values.
    wal_shards: int = 1
    # Backpressure: rounds that may queue at a writer shard before
    # submit() blocks. Deeper = bigger group commits under load; ack
    # latency stays bounded at ~(this x append + 1 fsync).
    wal_queue_rounds: int = 64
    # Message hops chained inside ONE kernel invocation (both the
    # single-device and the mesh path). 3 = propose -> replicate ->
    # commit completes within the round it was staged, cutting ack
    # latency from ~4 round-trips to ~1.5 (kernel.step_routed_auto).
    hops: int = 3
    # Compact readback (kernel.step_routed_compact): the round's state
    # diff is computed ON DEVICE and the host reads back a (G, P) uint8
    # flag map plus values for only the rows that changed, instead of
    # the full O(G*P*W) state every round (32 MB of ring alone at
    # G=100k — the term that dominates ack latency when the device is
    # behind a network tunnel). Rounds that change more rows than
    # compact_cap — or that raise need_host — fall back to the full
    # readback, so saturated throughput is untouched. None = auto
    # (enabled when mesh is None); the mesh path keeps full readback
    # (its readback is sharded-resident and the flag map would need its
    # own out_sharding).
    compact_readback: Optional[bool] = None
    # Max changed+staged rows served by the gather path before a round
    # falls back to full readback. 0 = auto: max(2048, G*P//8).
    compact_cap: int = 0
    # Liveness watchdog cadence (rounds): every N rounds verify the
    # DEVICE peer_mask still equals the host h_mask and repair it from
    # the host copy if not. Membership only ever flows host -> device
    # (_apply_conf / _restore surgery), so any divergence is device
    # buffer corruption — observed on the CPU backend under the donated
    # multi-hop step, where the mask buffer occasionally comes back
    # holding the step's is-leader intermediate. A corrupt mask is a
    # PERMANENT wedge (it silences every cross-slot send and suppresses
    # campaigns, and feeds the next round's donated step), so the check
    # is on by default; it costs one (G, P) bool readback per N rounds.
    # The root cause is gated at source — cpu engines run an UNDONATED
    # step (kernel.py "CPU donation hazard") — so on cpu this is pure
    # defense-in-depth (repairs only fire with ETCD_TPU_DONATE=on);
    # donating backends keep the safety net. 0 disables.
    mask_check_rounds: int = 64
    # Leader-lease read fast path (OFF by default). After a ReadIndex
    # round confirms a group's leader, quorum reads arriving within the
    # next read_lease_ms milliseconds skip the confirmation round and
    # park directly at the current commit mirror. This trades the strict
    # message-proven ReadIndex guarantee for the classic clock-bound
    # lease assumption (bounded drift: a deposed leader's host notices
    # within the lease window); 0 keeps every quorum read on the full
    # confirmation path.
    read_lease_ms: int = 0


class _AckCounter:
    """Mutable ack tally. _apply_committed increments whichever tally it
    is handed — a shard worker's own, or the engine's synchronous-path
    one — so the counters need no locking (one writer each) and
    MultiEngine.acked_requests sums them."""

    __slots__ = ("acked",)

    def __init__(self) -> None:
        self.acked = 0


class _AckBatch:
    """Deferred waiter wakeups: an applier worker collects its pass's
    (rid, result) triggers and ack tally here instead of firing them
    inline, then releases everything after wait_durable(ticket) — the
    apply work may run AHEAD of the WAL pipeline (stores are in-memory
    and die with the process anyway), but no client observes a result
    before its round's record is fsynced (doc.go:31-39). Synchronous
    paths pass no sink and keep the inline trigger."""

    __slots__ = ("items", "acked")

    def __init__(self) -> None:
        self.items: List[Tuple[int, Any]] = []
        self.acked = 0


class _ApplierShard:
    """One compartment of the applier pool: a worker thread owning the
    contiguous tenant range [g_lo, g_hi), with its own commit-view
    queue, its own backpressure/condition variable, and its own ack
    tally. Shards share no mutable state except disjoint slices of
    engine.applied and disjoint tenant stores, so K workers drive K
    GIL-releasing storecore batch applies in true parallel."""

    __slots__ = ("idx", "g_lo", "g_hi", "cv", "q", "stop", "exc",
                 "thread", "acct")

    def __init__(self, idx: int, g_lo: int, g_hi: int) -> None:
        self.idx = idx
        self.g_lo = g_lo
        self.g_hi = g_hi
        self.cv = threading.Condition()
        self.q: deque = deque()
        self.stop = False
        self.exc: Optional[Exception] = None
        self.thread: Optional[threading.Thread] = None
        self.acct = _AckCounter()


class MultiEngine:
    """G consensus groups stepped by the batched kernel, served as G
    independent etcd v2 keyspaces ("tenants")."""

    def __init__(self, cfg: EngineConfig) -> None:
        # jax imports deferred so constructing configs stays cheap.
        import jax
        import jax.numpy as jnp
        from etcd_tpu.ops import kernel
        from etcd_tpu.ops.state import (KernelConfig, LEADER, init_state)

        assert LEADER == _LEADER
        self._jax, self._jnp, self._kernel = jax, jnp, kernel
        self.cfg = cfg
        self.kcfg = KernelConfig(
            groups=cfg.groups, peers=cfg.peers, window=cfg.window,
            max_ents=cfg.max_ents, election_tick=cfg.election_tick,
            heartbeat_tick=cfg.heartbeat_tick)
        G, P, W = cfg.groups, cfg.peers, cfg.window

        # Mesh placement: pinned out_shardings keep the state AND the routed
        # inbox on their canonical shardings round over round (one compile;
        # the outbox->inbox peer-axis swap lowers to an all_to_all over the
        # "peers" mesh axis — the ICI transport of SURVEY §2.4).
        self._st_sh = self._mb_sh = None
        if cfg.mesh is not None:
            import functools
            from etcd_tpu.parallel.mesh import (mailbox_sharding,
                                                state_sharding)
            self._st_sh = state_sharding(cfg.mesh)
            self._mb_sh = mailbox_sharding(cfg.mesh)
            # Measured on the 8-device CPU mesh at G=4096 (r4): the auto
            # (quiescent-fast-path) kernel runs the sharded round 2x
            # faster than the always-full kernel (62 vs 127 ms), and
            # hops=3 beats three 1-hop rounds (145 vs 187 ms) while
            # cutting propose->commit to one round — the earlier
            # "lax.cond constrains sharded layouts" concern did not
            # survive measurement, so the mesh path now runs the same
            # auto+hops program as the single-device engine (drop mask
            # riding into the kernel, cut per hop).
            _mesh_step = jax.jit(
                functools.partial(kernel.step_routed_auto.__wrapped__,
                                  self.kcfg, hops=cfg.hops),
                donate_argnums=kernel.donate_safe((0, 1)),
                out_shardings=(self._st_sh, self._mb_sh))
            self._step_fn = (
                lambda st, inbox, pc, ps, t: _mesh_step(
                    st, inbox, pc, ps, t, self.drop_mask))
        else:
            # step_routed_auto: quiescent rounds (the serving steady
            # state) take the one-pass fast path; election/term-change
            # rounds take the full sequential path — selected on device,
            # bit-identical trajectories (tests/test_quiet_path.py).
            # cfg.hops chains propose->replicate->commit inside the one
            # program (see kernel.step_routed_auto); the drop mask rides
            # into the kernel so fault injection cuts EVERY hop.
            # step_variant: undonated twin on the cpu backend — XLA:CPU
            # has a donated-buffer race (see kernel.py "CPU donation
            # hazard"); donation stays on TPU.
            _auto = kernel.step_variant("step_routed_auto")
            self._step_fn = (
                lambda st, inbox, pc, ps, t: _auto(
                    self.kcfg, st, inbox, pc, ps, t, self.drop_mask,
                    self.cfg.hops))
        self._compact = (cfg.compact_readback if cfg.compact_readback
                         is not None else cfg.mesh is None)
        if cfg.mesh is not None:
            self._compact = False    # see EngineConfig.compact_readback
        self._compact_cap = cfg.compact_cap or max(2048, G * P // 8)
        # Set whenever device state was mutated WITHOUT updating the
        # h_* mirrors (the snapshot-install surgery leaves mirrors stale
        # on purpose so the NEXT round's full diff journals the install,
        # _service_need_host). A compact diff is device-vs-device and
        # would never see the surgery — the next round must take the
        # full-readback path to re-sync mirrors and journal it.
        self._force_full = False
        # Count of peer_mask watchdog repairs (EngineConfig.
        # mask_check_rounds); >0 means the device mask diverged from the
        # host's and was restored.
        self.mask_repairs = 0
        _compact_step = kernel.step_variant("step_routed_compact")
        self._step_fn_c = (
            lambda st, inbox, pc, ps, t: _compact_step(
                self.kcfg, st, inbox, pc, ps, t, self.drop_mask,
                self.cfg.hops))
        # The ReadIndex step (the zero-append read plane): the same
        # routed round plus a forced leader heartbeat and a per-group
        # read-quorum tally — one extra (G,) confirmed flag and one (G,)
        # captured commit index come back with the state. The mesh path
        # pins both to a groups-sharded layout next to the state/mailbox
        # shardings; the non-mesh path rides step_variant (CPU donation
        # hazard twin, same as the other kernels).
        if cfg.mesh is not None:
            import functools
            from jax.sharding import NamedSharding, PartitionSpec
            _g_sh = NamedSharding(cfg.mesh, PartitionSpec("groups"))
            _mesh_read = jax.jit(
                functools.partial(kernel.step_routed_read_auto.__wrapped__,
                                  self.kcfg, hops=cfg.hops),
                donate_argnums=kernel.donate_safe((0, 1)),
                out_shardings=(self._st_sh, self._mb_sh, _g_sh, _g_sh))
            self._step_fn_r = (
                lambda st, inbox, pc, ps, t: _mesh_read(
                    st, inbox, pc, ps, t, self.drop_mask))
        else:
            _read_step = kernel.step_variant("step_routed_read_auto")
            self._step_fn_r = (
                lambda st, inbox, pc, ps, t: _read_step(
                    self.kcfg, st, inbox, pc, ps, t, self.drop_mask,
                    self.cfg.hops))

        # Geometry guard BEFORE anything touches the data dir: a mismatch
        # must refuse the dir before the WAL opens/creates any file in it.
        self._check_geometry()
        self.wait = Wait()
        self.reqid = idutil.Generator(1)
        self._pending: List[deque] = [deque() for _ in range(G)]
        self._dirty: set = set()            # groups with queued proposals
        self._confs_outstanding = 0         # enqueued, not-yet-applied
        # Per group: the entries staged this round, each a list of
        # (request id, tagged payload) items coalesced into one log entry.
        # g -> (leader_slot, [entry batches]) staged this round
        self._staged: Dict[int, Tuple[int, list]] = {}
        # The read plane's two parking lots (both under self._lock):
        # _reads holds quorum reads waiting for a ReadIndex confirmation
        # (rid, Request); _ripe holds confirmed reads waiting for the
        # apply cursor to reach their read index (rid, Request, index).
        # The waiting counters let run_round skip the plane when idle,
        # and the dirty sets bound per-round scans to active groups.
        self._reads: List[deque] = [deque() for _ in range(G)]
        self._read_dirty: set = set()
        self._ripe: List[deque] = [deque() for _ in range(G)]
        self._ripe_dirty: set = set()
        self._reads_waiting = 0
        self._ripe_waiting = 0
        # Leader-lease fast path state (cfg.read_lease_ms): per-group
        # monotonic-clock deadline and the term the lease was granted
        # under — a lease dies with its term.
        self._lease_until = np.zeros(G, np.float64)
        self._lease_term = np.zeros(G, np.int64)
        self._stores: Dict[int, Any] = {}
        self._lock = threading.Lock()       # guards _pending/_dirty enqueue
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.round_no = 0
        self.round_ms_ewma = 0.0   # smoothed wall time per round
        # Cumulative per-phase wall time (seconds) of the round loop —
        # the profile VERDICT r3 asked for (device/readback/fsync/apply/
        # ack shares). Reset with reset_phase_profile(). The writer
        # compartment's threads record "wal_fsync"/"wal_fsync[k]" here
        # (one writer thread per key); the round loop records only the
        # cheap "wal_submit" hand-off.
        self.phase_s: Dict[str, float] = {}
        # Observability plane (obs.py): per-compartment Prometheus
        # series with children pre-bound to this engine's shard
        # geometry, the round flight recorder, and the sampled proposal
        # tracer. Constructed before the WAL writer and applier pool so
        # both compartments can record into it. ETCD_TPU_OBS=off keeps
        # it inert (the overhead A/B's baseline side).
        self.obs = obs_mod.EngineObs(
            wal_shards=max(1, min(cfg.wal_shards, G)),
            applier_shards=max(1, min(cfg.applier_shards, G)))
        # Requests admitted into this round's entries / sampled rids
        # admitted this round (round-thread-private, reset per round).
        self._last_admitted = 0
        self._trace_rids: List[int] = []
        # The WAL compartment: submit() hands records to the writer
        # stage; acks gate on its durability watermark (wait_durable).
        # Constructed after phase_s — the writer threads profile into it.
        self.wal = WALWriter(cfg.data_dir, groups=G,
                             shards=cfg.wal_shards, fsync=cfg.fsync,
                             queue_rounds=cfg.wal_queue_rounds,
                             phase_s=self.phase_s, obs=self.obs)
        # Last few durable round records, kept for the violation dump.
        self._recent_recs: deque = deque(maxlen=8)
        self.failed: Optional[Exception] = None
        # Applier pool (cfg.pipeline_applies): committed spans are handed
        # off as immutable views and applied+acked concurrently with the
        # next rounds' device steps and WAL fsyncs (both of which release
        # the GIL, so the appliers make real progress under them). With
        # applier_shards=K the tenant pool is partitioned into K
        # contiguous ranges — shard k owns [k*ceil(G/K), ...), the same
        # convention scripts/pool_serve.py uses — each applied by its own
        # worker. Empty tail shards (K not dividing G) get no thread.
        K = max(1, min(cfg.applier_shards, G))
        per = -(-G // K)
        self._appliers = [
            _ApplierShard(k, min(k * per, G), min((k + 1) * per, G))
            for k in range(K)]
        self._appliers = [sh for sh in self._appliers if sh.g_lo < sh.g_hi]
        # Acks from synchronous applies (conf rounds, pipeline off,
        # restore); shard workers tally into their own counters.
        self._acks = _AckCounter()
        self._last_sync_scan = 0.0
        # g -> redeadline for the one in-flight SYNC allowed per tenant.
        self._sync_pending: Dict[int, float] = {}
        # Tenant-lifecycle admin ops: (op dict, done Event, result dict),
        # processed at a round boundary by the engine loop; acks fire only
        # after the record carrying the flips is fsynced.
        self._admin_q: deque = deque()
        self._admin_flips: List[Tuple[int, int, int]] = []
        self._admin_acks: List[threading.Event] = []
        # Per-slot lifecycle generation: bumped on every create/remove so
        # frontends can invalidate per-tenant caches (an HTTP layer that
        # cached handlers for generation k must not serve a recycled slot's
        # generation k+1 keyspace through them).
        self.tenant_gen = np.zeros(G, np.int64)

        # Host mirrors of the last read-back device state.
        self.h_term = np.zeros((G, P), np.int32)
        self.h_vote = np.zeros((G, P), np.int32)
        self.h_commit = np.zeros((G, P), np.int32)
        self.h_state = np.zeros((G, P), np.int32)
        self.h_last = np.zeros((G, P), np.int32)
        self.h_ring = np.zeros((G, P, W), np.int32)
        self.h_mask = np.zeros((G, P), bool)
        self.applied = np.zeros(G, np.int64)
        self.payloads: Dict[Tuple[int, int, int], bytes] = {}
        # Live-path sidecar of self.payloads: the already-decoded Requests
        # of an admitted entry, so the apply loop skips re-parsing JSON it
        # produced moments ago (restart replay decodes from bytes). Popped
        # at apply; GC'd with the payload store.
        self.payload_reqs: Dict[Tuple[int, int, int], list] = {}

        ckpt_round, ckpt = self.wal.load_checkpoint()
        # Full consumption also positions the writer (next segment seq) and
        # seeds the rolling CRC for appends.
        recs = list(self.wal.replay(after_round=ckpt_round))
        if ckpt is not None or recs:
            self._restore(ckpt_round, ckpt, recs)
        else:
            self.st = init_state(self.kcfg, n_peers=self._boot_peers(),
                                 stagger=cfg.stagger)
            self.h_mask = np.asarray(self.st.peer_mask).copy()
        if self._st_sh is not None:
            from etcd_tpu.parallel.mesh import shard_state
            self.st = shard_state(self.st, cfg.mesh)
        inbox0 = jnp.zeros((G, P, P, self.kcfg.fields), jnp.int32)
        self.inbox = (jax.device_put(inbox0, self._mb_sh)
                      if self._mb_sh is not None else inbox0)
        self._zero = jnp.zeros(G, jnp.int32)
        # Chaos hook: (G, P_to, P_from, 1)-broadcastable 0/1 mask applied to
        # the routed inbox (tests inject drops/partitions here).
        self.drop_mask = None

    def _boot_peers(self):
        """Per-group active-slot counts at fresh boot: the first
        initial_tenants groups get initial_peers (or all P) slots, the
        rest of the pool stays unprovisioned (all-false mask rows)."""
        n = self.cfg.initial_peers or self.cfg.peers
        if self.cfg.initial_tenants is None:
            return n
        arr = np.zeros(self.cfg.groups, np.int32)
        arr[:min(self.cfg.initial_tenants, self.cfg.groups)] = n
        return arr

    def _check_geometry(self) -> None:
        """Persist (groups, peers, window) beside the WAL and refuse a
        restart with different values — the checkpoint/WAL arrays are
        shaped by them, and restoring a (G,P)-shaped checkpoint into a
        different-shaped state would crash at best and silently corrupt
        consensus state at worst. (max_ents shapes only the mailbox, not
        persisted state, so it may change.)"""
        import os
        from etcd_tpu.utils.fileutil import touch_dir_all
        touch_dir_all(self.cfg.data_dir)
        self._grew_from: Optional[int] = None
        path = os.path.join(self.cfg.data_dir, "geometry.json")
        S = max(1, min(self.cfg.wal_shards, self.cfg.groups))
        want = {"groups": self.cfg.groups, "peers": self.cfg.peers,
                "window": self.cfg.window, "wal_shards": S}

        def write(d):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(d, f)
            os.replace(tmp, path)

        if os.path.exists(path):
            with open(path) as f:
                have = json.load(f)
            # WAL shard layout is pinned separately from the array
            # shapes: an unsharded dir (including pre-wal_shards dirs,
            # where the key is absent) may upgrade 1 -> S once — the
            # root stream freezes as legacy history and new records go
            # to the shard streams. Any OTHER change is refused: a
            # shrunk/re-grown stream set would leave frozen streams
            # whose stale tails drag the min-over-streams replay
            # boundary below live records forever.
            have_ws = have.pop("wal_shards", 1)
            core = {k: want[k] for k in ("groups", "peers", "window")}
            if have_ws != S and have_ws != 1:
                raise ValueError(
                    f"engine data dir {self.cfg.data_dir} was written "
                    f"with wal_shards={have_ws}, refusing to open with "
                    f"wal_shards={S} — the segment-stream layout may "
                    "only go 1 -> S once; move the data dir aside or "
                    "match the flag")
            if have != core:
                # The pool may GROW (tenant lifecycle: restart with more
                # groups; restore pads the arrays, WAL group ids stay
                # valid). Peer/window shapes and shrinking still refuse.
                if (have["peers"] == core["peers"]
                        and have["window"] == core["window"]
                        and core["groups"] > have["groups"]):
                    # Remember the old pool size: groups beyond it were
                    # never provisioned, whatever the boot defaults say.
                    self._grew_from = have["groups"]
                    write(want)
                    return
                raise ValueError(
                    f"engine data dir {self.cfg.data_dir} was initialized "
                    f"with geometry {have}, refusing to open with {core} — "
                    "move the data dir aside or match the flags (only the "
                    "group pool may grow)")
            if have_ws != S:
                write(want)
        else:
            write(want)

    def _dev(self, name: str, arr) -> Any:
        """Host array -> device, on the field's canonical sharding when a
        mesh is configured (host-surgery writebacks must not knock fields
        off their sharding, or the pinned-sharding step would silently
        reshard every round)."""
        x = self._jnp.asarray(arr)
        if self._st_sh is not None:
            x = self._jax.device_put(x, getattr(self._st_sh, name))
        return x

    # ------------------------------------------------------------------
    # restore
    # ------------------------------------------------------------------

    def _restore(self, ckpt_round: int, ckpt: Optional[dict],
                 recs: List[RoundRecord]) -> None:
        """Rebuild host mirrors + device state from checkpoint + WAL replay.
        Every slot restarts as a follower with its replayed log, term, vote
        and commit (reference RestartNode semantics, raft/node.go:186-192)."""
        from etcd_tpu.ops.state import init_state
        jnp = self._jnp
        G, P, W = self.cfg.groups, self.cfg.peers, self.cfg.window

        base = init_state(self.kcfg, n_peers=self._boot_peers(),
                          stagger=self.cfg.stagger)
        self.h_mask = np.asarray(base.peer_mask).copy()
        if self._grew_from is not None:
            # Pool slots added by a post-boot growth were never
            # provisioned — the checkpoint pad and the WAL both know
            # nothing of them.
            self.h_mask[self._grew_from:] = False
        def pool_pad(a):
            """Pad checkpoint arrays along the group axis when the pool
            grew since the checkpoint (new slots: zeroed, unprovisioned)."""
            if a.shape[0] < G:
                pad = np.zeros((G - a.shape[0],) + a.shape[1:], a.dtype)
                return np.concatenate([a, pad], axis=0)
            return a

        if ckpt is not None:
            self.h_term = pool_pad(b64_np(ckpt["term"]).astype(np.int32))
            self.h_vote = pool_pad(b64_np(ckpt["vote"]).astype(np.int32))
            self.h_commit = pool_pad(b64_np(ckpt["commit"])
                                     .astype(np.int32))
            self.h_last = pool_pad(b64_np(ckpt["last"]).astype(np.int32))
            self.h_ring = pool_pad(b64_np(ckpt["ring"]).astype(np.int32))
            self.h_mask = pool_pad(b64_np(ckpt["mask"]).astype(bool))
            self.applied = pool_pad(b64_np(ckpt["applied"])
                                    .astype(np.int64))
            for g_s, blob in ckpt["stores"].items():
                st = new_store(namespaces=("/0", "/1"))
                st.recovery(blob.encode())
                self._stores[int(g_s)] = st
            for g, i, t, b64p in ckpt["payloads"]:
                import base64 as _b64
                self.payloads[(g, i, t)] = _b64.b64decode(b64p)

        # Per-slot log terms reconstructed from history: the final ring only
        # covers the last W entries, but the restart apply span can reach
        # further back (committed-but-unapplied suffix). Seed from the
        # checkpoint's ring, then track BOTH ring deltas (term rewrites —
        # conflicts always change the term) and last_index advances (a
        # same-term append leaves its ring slot's VALUE unchanged when it
        # aliases an equal-term entry, so it is only visible as growth).
        slot_log: Dict[Tuple[int, int], Dict[int, int]] = {}

        def _log_set(g, p, i, t):
            slot_log.setdefault((int(g), int(p)), {})[int(i)] = int(t)

        if ckpt is not None:
            for g in range(G):
                for p in range(P):
                    lastv = int(self.h_last[g, p])
                    for w in range(W):
                        i = lastv - ((lastv - w) % W)
                        if i >= 1:
                            _log_set(g, p, i, self.h_ring[g, p, w])

        last_round = ckpt_round
        for rec in recs:
            last_round = max(last_round, rec.round_no)
            gi = rec.hs_g.astype(np.int64)
            pi = rec.hs_p.astype(np.int64)
            self.h_term[gi, pi] = rec.hs_term
            self.h_vote[gi, pi] = rec.hs_vote
            self.h_commit[gi, pi] = rec.hs_commit
            # Ring deltas first: the round's appends need the post-round
            # ring to resolve their terms.
            gi = rec.ring_g.astype(np.int64)
            pi = rec.ring_p.astype(np.int64)
            self.h_ring[gi, pi, rec.ring_i.astype(np.int64) % W] = rec.ring_t
            for g, p, i, t in zip(rec.ring_g, rec.ring_p, rec.ring_i,
                                  rec.ring_t):
                _log_set(g, p, i, t)
            for g, p, new in zip(rec.last_g.astype(np.int64),
                                 rec.last_p.astype(np.int64),
                                 rec.last_v.astype(np.int64)):
                prev = int(self.h_last[g, p])
                self.h_last[g, p] = new
                for i in range(max(prev + 1, int(new) - W + 1), int(new) + 1):
                    _log_set(g, p, i, self.h_ring[g, p, i % W])
            for g, i, t, payload in rec.entries:
                self.payloads[(g, i, t)] = payload
            for g, slot, op in rec.confs:
                self.h_mask[g, slot] = (op == CONF_ADD)
                if op == CONF_ADD:
                    # Live _apply_conf zeroes a joining slot's state (it may
                    # have a stale former life); replay must match, or the
                    # restarted slot would claim a log it no longer has.
                    self.h_term[g, slot] = 0
                    self.h_vote[g, slot] = 0
                    self.h_commit[g, slot] = 0
                    self.h_last[g, slot] = 0
                    self.h_ring[g, slot] = 0
                    slot_log.pop((int(g), int(slot)), None)
                elif not self.h_mask[g].any():
                    # This REMOVE flip deprovisioned the tenant: replay the
                    # host-side reset AT THIS POINT in the flip sequence —
                    # a remove+re-create batched into the same record must
                    # reset between the two, or the re-created tenant's
                    # fresh indices land below the stale apply cursor and
                    # acked writes vanish while old data resurfaces.
                    g = int(g)
                    self.applied[g] = 0
                    self._stores.pop(g, None)
                    for k in [k for k in self.payloads if k[0] == g]:
                        del self.payloads[k]
        self.round_no = last_round + 1

        # Device state: followers everywhere, logs/HS restored.
        self.st = base._replace(
            term=jnp.asarray(self.h_term),
            vote=jnp.asarray(self.h_vote),
            commit=jnp.asarray(self.h_commit),
            last_index=jnp.asarray(self.h_last),
            log_term=jnp.asarray(self.h_ring),
            peer_mask=jnp.asarray(self.h_mask),
        )
        self.h_state = np.zeros((G, P), np.int32)  # all followers
        # Committed terms across ALL slots: where committed, every slot's
        # log agrees at an index (log matching), so any slot with
        # commit >= i supplies THE term. Zero terms are placeholder slots
        # (e.g. zeroed by a snapshot install) and are skipped.
        hist: Dict[Tuple[int, int], int] = {}
        for (g, p), entries in slot_log.items():
            c = int(self.h_commit[g, p])
            lastv = int(self.h_last[g, p])
            for i, t in entries.items():
                if t > 0 and i <= c and i <= lastv:
                    hist.setdefault((g, i), t)
        # Re-apply the committed-but-unapplied suffix; hist supplies entry
        # terms older than the live ring window.
        self._apply_committed(trigger=False, hist=hist)
        self._gc_payloads()
        # Admitted-but-uncommitted conf entries survive restart in the
        # payload store; the committed-conf scan must stay armed for them
        # (its short-circuit would otherwise skip binding the mask flip
        # into the committing round's durable record).
        self._confs_outstanding = sum(
            1 for (g, i, t), p in self.payloads.items()
            if p and p[0] == P_CONF and i > self.applied[g])

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._install_flight_signal()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="multi-engine")
        self._thread.start()

    def dump_flight(self, reason: str = "manual") -> Optional[str]:
        """Write the flight-recorder ring as Chrome trace-event JSON
        under <data_dir>/diagnostics; returns the path (None on
        failure). Also reachable via SIGUSR2 and GET /debug/flight."""
        return self.obs.flight.dump(self.cfg.data_dir, reason)

    def _install_flight_signal(self) -> None:
        """SIGUSR2 -> flight dump. Best-effort: only the main thread
        may install handlers (tests start engines from worker threads),
        and with several engines in one process the last one started
        owns the signal — the /debug/flight endpoint and fail-stop
        auto-dump cover the rest."""
        import signal as _signal
        if not hasattr(_signal, "SIGUSR2"):
            return
        try:
            _signal.signal(_signal.SIGUSR2,
                           lambda _s, _f: self.dump_flight("sigusr2"))
        except ValueError:
            pass

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # A wedged device round still owns the WAL and the applier
                # queue; draining or closing under it would race.
                log.error("engine thread did not stop in 10s; leaving "
                          "final round unflushed")
                return
        if self.failed is None:
            try:
                self._drain_applies()
            except Exception as e:  # noqa: BLE001 — applier's deferred error
                self.failed = e
        for sh in self._appliers:
            with sh.cv:
                sh.stop = True
                sh.cv.notify_all()
        for sh in self._appliers:
            if sh.thread is not None:
                sh.thread.join(timeout=10)
        # Parked quorum reads can never ripen once the round loop is
        # down; fail them now instead of letting clients ride out the
        # request timeout.
        self._fail_parked_reads("engine stopped")
        self.wal.close()

    # ------------------------------------------------------------------
    # applier pool (cfg.pipeline_applies, cfg.applier_shards)
    # ------------------------------------------------------------------

    @property
    def acked_requests(self) -> int:
        """Client REQUESTS acked in LIVE rounds (not entries: a batched
        entry carries many; restart replay does not count). The
        serving-throughput counter — meters measure deltas. Summed across
        the synchronous path and every applier shard's own tally."""
        return self._acks.acked + sum(sh.acct.acked
                                      for sh in self._appliers)

    def _commit_view(self) -> tuple:
        """Immutable snapshot of what the applier needs from this round's
        mirrors: per-group commit (masked max over live slots), the slot
        holding it, the ring/last arrays it resolves terms from, and the
        WAL durability ticket ack release gates on (wait_durable). The
        mirror arrays are replaced (never mutated) each round, so handing
        references across threads is safe. The trailing round number is
        for the flight recorder's applied/acked marks."""
        c = np.where(self.h_mask, self.h_commit, 0)
        return (c.max(axis=1), c.argmax(axis=1), self.h_ring, self.h_last,
                self.wal.ticket, self.round_no)

    def _ensure_appliers(self) -> None:
        for sh in self._appliers:
            t = sh.thread
            if t is None or not t.is_alive():
                if sh.exc is not None:
                    # The worker HALTed mid-span; respawning would
                    # re-apply (and re-ack) the queued view from the
                    # top. Stay down — the seam re-raises.
                    continue
                sh.stop = False
                sh.thread = threading.Thread(
                    target=self._applier_loop, args=(sh,), daemon=True,
                    name=f"engine-applier-{sh.idx}")
                sh.thread.start()

    def _applier_loop(self, sh: _ApplierShard) -> None:
        # Phase key: "apply" for the single-shard pool (keeps profiles
        # comparable with pre-pool captures), "apply[k]" per worker
        # otherwise — each key has exactly one writer thread.
        pkey = "apply" if len(self._appliers) == 1 else f"apply[{sh.idx}]"
        o = self.obs if self.obs.enabled else None
        tr = self.obs.tracer
        while True:
            with sh.cv:
                while not sh.q and not sh.stop:
                    sh.cv.wait(0.2)
                if not sh.q:
                    return           # stop requested and queue drained
                view = sh.q[0]       # stays queued while in progress
            t0 = time.perf_counter()
            try:
                # Applies run ahead of the WAL pipeline; the acks they
                # produce are collected and released only once the
                # view's durability ticket clears the writer's
                # watermark (ack-after-fsync, gated not ordered).
                batch = _AckBatch()
                self._apply_committed(trigger=True, view=view,
                                      g_lo=sh.g_lo, g_hi=sh.g_hi,
                                      acct=sh.acct, sink=batch)
                if o:
                    o.flight.mark(view[5], obs_mod.APPLIED)
                if batch.acked or batch.items:
                    t_gate = time.perf_counter()
                    self.wal.wait_durable(view[4])
                    if o:
                        o.h_ack_wait.observe(time.perf_counter()
                                             - t_gate)
                    if tr.every:
                        for rid, _res in batch.items:
                            tr.mark(rid, "durable", ticket=view[4])
                    for rid, res in batch.items:
                        self.wait.trigger(rid, res)
                        if tr.every:
                            tr.mark(rid, "acked")
                    sh.acct.acked += batch.acked
                    if o:
                        o.c_acked.inc(batch.acked)
                        o.h_appl_batch[sh.idx].observe(batch.acked)
                        o.flight.mark(view[5], obs_mod.ACKED)
            except Exception as e:  # noqa: BLE001 — re-raised at the seam
                log.exception("engine applier shard %d failed", sh.idx)
                self.obs.flight.dump(self.cfg.data_dir,
                                     f"applier-shard-{sh.idx}")
                with sh.cv:
                    sh.exc = e
                    sh.cv.notify_all()
                # HALT — consuming further views after a mid-span failure
                # would re-apply and re-ack around the hole. The engine
                # fail-stops at the next enqueue/drain, which re-raises.
                return
            self.phase_s[pkey] = self.phase_s.get(pkey, 0.0) + \
                (time.perf_counter() - t0)
            with sh.cv:
                sh.q.popleft()
                sh.cv.notify_all()

    def _enqueue_apply(self, view: tuple) -> None:
        """Hand one round's committed work to every applier shard,
        blocking while the DEEPEST shard's backlog is at the cap (bounds
        ack latency under saturation; a sum-bound would let one hot
        shard spend the other shards' latency budget)."""
        self._ensure_appliers()
        o = self.obs if self.obs.enabled else None
        for sh in self._appliers:
            with sh.cv:
                while (len(sh.q) >= self.cfg.apply_queue_rounds
                       and sh.exc is None):
                    sh.cv.wait(0.5)
                sh.q.append(view)
                if o:
                    o.g_appl_queue[sh.idx].set(len(sh.q))
                sh.cv.notify_all()
        self._raise_apply_exc()

    def _drain_applies(self) -> None:
        """Block until every queued apply on every shard finished; then
        surface any applier error. All synchronous seams (conf changes,
        checkpoints, admin surgery, stop) come through here before
        touching state the appliers also own (stores, applied, payload
        GC)."""
        for sh in self._appliers:
            if sh.thread is not None:
                with sh.cv:
                    while (sh.q and sh.exc is None
                           and sh.thread.is_alive()):
                        sh.cv.notify_all()
                        sh.cv.wait(0.5)
        self._raise_apply_exc()
        for sh in self._appliers:
            if sh.q and (sh.thread is None or not sh.thread.is_alive()):
                raise RuntimeError(
                    f"applier shard {sh.idx} died with work queued")

    def _raise_apply_exc(self) -> None:
        # sh.exc stays set: a HALTed shard is terminally failed (its
        # worker never respawns — see _ensure_appliers), so EVERY later
        # seam re-raises rather than letting one caller absorb the
        # error and the next one sail past a dead compartment.
        for sh in self._appliers:
            if sh.exc is not None:
                raise sh.exc

    def store(self, g: int):
        s = self._stores.get(g)
        if s is None:
            # Lock: HTTP handler threads race the engine apply thread on
            # first touch of a tenant; an unsynchronized check-then-set
            # could discard a Store already holding applied writes.
            # Namespaces match the classic server's store (reference
            # store.New(StoreClusterPrefix, StoreKeysPrefix)) so an empty
            # tenant serves GET /v2/keys/ identically.
            with self._lock:
                s = self._stores.get(g)
                if s is None:
                    s = self._stores[g] = new_store(namespaces=("/0", "/1"))
        return s

    def leader_slot(self, g: int) -> int:
        """The group's current leader slot, or -1. Only ACTIVE slots count —
        a just-removed slot's device row freezes in whatever state it held
        (reference removed-member tombstones make its traffic inert the same
        way, server.go:387-391)."""
        row = np.where(self.h_mask[g], self.h_state[g], 0)
        idx = np.nonzero(row == _LEADER)[0]
        return int(idx[0]) if len(idx) else -1

    def wait_leaders(self, timeout: float = 30.0, groups=None) -> bool:
        """Block until every (requested) PROVISIONED group has a leader —
        unprovisioned pool slots have no peers and never elect."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            gs = (np.nonzero(self.h_mask.any(axis=1))[0]
                  if groups is None else groups)
            if all(self.leader_slot(int(g)) >= 0 for g in gs):
                return True
            time.sleep(0.005)
        return False

    def do(self, g: int, r: Request, timeout: Optional[float] = None) -> Any:
        """Serve one request against group g (the engine's Do,
        reference server.go:519-576). Reads are local; writes ride the
        kernel's consensus."""
        if r.method == METHOD_GET:
            if r.quorum:
                if not r.wait:
                    # The zero-append read plane: ReadIndex confirmation
                    # + local serve; no log entry, no WAL bytes, no
                    # fsync. (A quorum WATCH still rides the propose
                    # path below, unchanged.)
                    return self._quorum_read(g, r, timeout)
                r = Request(**{**r.__dict__, "method": METHOD_QGET})
            elif r.wait:
                return self.store(g).watch(r.path, r.recursive, r.stream,
                                           r.since)
            else:
                return self.store(g).get(r.path, r.recursive, r.sorted)
        if r.method not in (METHOD_PUT, METHOD_POST, METHOD_DELETE,
                            METHOD_QGET, METHOD_SYNC):
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"bad method {r.method}")
        if r.id == 0:
            r = Request(**{**r.__dict__, "id": self.reqid.next()})
        obs_on = self.obs.enabled
        tr = self.obs.tracer
        if tr.every:
            tr.mark(r.id, "submit", g=g)
        q = self.wait.register(r.id)
        payload = bytes([P_REQ]) + r.encode()
        with self._lock:
            # The decoded Request rides along so the live apply path never
            # re-parses JSON it already has (replay still decodes bytes).
            self._pending[g].append((r.id, payload, r))
            self._dirty.add(g)
        # Reference proposal metrics (etcdserver/metrics.go), previously
        # observed only by the legacy server.py path.
        if obs_on:
            metrics.propose_pending.inc()
        t0 = time.perf_counter()
        try:
            result = q.get(timeout=timeout or self.cfg.request_timeout)
        except queue.Empty:
            if obs_on:
                metrics.propose_failed.inc()
            self.wait.cancel(r.id)
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="request timed out",
                                   index=int(self.applied[g]))
        finally:
            if obs_on:
                metrics.propose_pending.dec()
        if obs_on:
            metrics.propose_durations.observe(
                (time.perf_counter() - t0) * 1000.0)
        if isinstance(result, errors.EtcdError):
            # Application-level error (e.g. a failed CAS) — served, not
            # a failed proposal; propose_failed counts only proposals
            # that never produced a result.
            raise result
        if type(result) is LazyWriteEvent:
            # The ack/waiter stage woke us with raw C descriptors; the
            # Event/NodeExtern churn happens HERE, on the serving thread,
            # off the (serialized) apply stage.
            return result.resolve()
        return result

    def do_many(self, g: int, reqs: List[Request],
                timeout: Optional[float] = None) -> List[Any]:
        """Serve a BATCH of write requests against group g from one
        caller (the ingress tier's coalesced submission surface): all of
        them are enqueued under ONE lock acquisition, so the next round's
        staging packs them into deep P_MULTI log entries — the exact
        multi-request packing `do()` traffic already coalesces into, which
        keeps the WAL format and replay path unchanged (an entry written
        through this path is indistinguishable from one that coalesced
        out of N concurrent `do()` calls).

        Returns one result per request, in request order. Application
        errors (failed CAS, auth, timeout) come back IN-SLOT as EtcdError
        instances instead of raising — the caller is a demultiplexer that
        must fan each slot's outcome back to a different waiting client,
        so one bad request must never poison its batch-mates. Results are
        only produced after the engine's ack path released the waiters,
        i.e. after this batch's round is fsync-durable — an ingress crash
        after `do_many` returns can never lose an acked write."""
        return self.collect_many(g, self.submit_many(g, reqs), timeout)

    def submit_many(self, g: int, reqs: List[Request]) -> List[tuple]:
        """The NON-BLOCKING half of do_many: validate, assign request
        ids, register wait queues and stage everything under one lock
        acquisition — then return immediately with the (rid, queue)
        tokens collect_many() blocks on. The batchframe channel
        (etcdhttp/tenants.py) submits frame N+1 through this before
        frame N's round has committed, which is what lets a pipelined
        ingress window keep the staging queue deep instead of draining
        it to zero between flushes. Submission order IS log-staging
        order per group, so frames submitted in channel-arrival order
        keep the lane's FIFO."""
        for r in reqs:
            if r.method not in (METHOD_PUT, METHOD_POST, METHOD_DELETE,
                                METHOD_QGET, METHOD_SYNC):
                raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                       cause=f"bad batch method {r.method}")
        obs_on = self.obs.enabled
        tr = self.obs.tracer
        items = []
        queues = []
        for r in reqs:
            if r.id == 0:
                r = Request(**{**r.__dict__, "id": self.reqid.next()})
            if tr.every:
                tr.mark(r.id, "submit", g=g)
            queues.append((r.id, self.wait.register(r.id)))
            items.append((r.id, bytes([P_REQ]) + r.encode(), r))
        with self._lock:
            self._pending[g].extend(items)
            if items:
                self._dirty.add(g)
        if obs_on:
            for _ in range(len(items)):
                metrics.propose_pending.inc()
        return queues

    def collect_many(self, g: int, queues: List[tuple],
                     timeout: Optional[float] = None) -> List[Any]:
        """The BLOCKING half of do_many: gather one result per submitted
        (rid, queue) token, in submission order, timing out slots that
        never produce one. Only returns results the ack path released —
        i.e. after their round's fsync."""
        obs_on = self.obs.enabled
        n = len(queues)
        t0 = time.perf_counter()
        deadline = t0 + (timeout or self.cfg.request_timeout)
        out = []
        try:
            for rid, q in queues:
                try:
                    result = q.get(
                        timeout=max(0.0, deadline - time.perf_counter()))
                except queue.Empty:
                    if obs_on:
                        metrics.propose_failed.inc()
                    self.wait.cancel(rid)
                    out.append(errors.EtcdError(
                        errors.ECODE_RAFT_INTERNAL,
                        cause="request timed out",
                        index=int(self.applied[g])))
                    continue
                if type(result) is LazyWriteEvent:
                    result = result.resolve()
                out.append(result)
        finally:
            if obs_on:
                for _ in range(n):
                    metrics.propose_pending.dec()
        if obs_on and n:
            # One batch = one client-visible submission window; the
            # per-request proposal latency is the window's mean.
            dt = (time.perf_counter() - t0) * 1000.0 / n
            for _ in range(n):
                metrics.propose_durations.observe(dt)
        return out

    # ------------------------------------------------------------------
    # the read plane (batched ReadIndex; zero-append quorum reads)
    # ------------------------------------------------------------------

    def _mirror_term(self, g: int) -> int:
        return int(np.where(self.h_mask[g], self.h_term[g], 0).max())

    def _mirror_commit(self, g: int) -> int:
        return int(np.where(self.h_mask[g], self.h_commit[g], 0).max())

    def _quorum_read(self, g: int, r: Request,
                     timeout: Optional[float] = None) -> Any:
        """Linearizable GET without a log entry (the reference's
        ReadIndex protocol, raft read_only.go, batched over all G
        groups): park the read, let the next round's ReadIndex step
        confirm the group's leader still holds a quorum and capture its
        commit index, then serve from the local store once the apply
        cursor reaches that index. Quorum reads leave the
        etcd_server_proposal_* families entirely (nothing is proposed)
        and meter the read_index_* families instead."""
        if r.id == 0:
            r = Request(**{**r.__dict__, "id": self.reqid.next()})
        obs_on = self.obs.enabled
        tr = self.obs.tracer
        if tr.every:
            tr.mark(r.id, "submit", g=g)
        q = self.wait.register(r.id)
        t0 = time.perf_counter()
        with self._lock:
            lease_ms = self.cfg.read_lease_ms
            if (lease_ms > 0
                    and time.monotonic() < float(self._lease_until[g])
                    and int(self._lease_term[g]) == self._mirror_term(g)):
                # Lease fast path: a confirmation round within the lease
                # window proved leadership, and the lease term still
                # matches — skip the confirmation and park directly at
                # the CURRENT commit mirror (>= every acked write's
                # index, so acked writes stay visible).
                self._ripe[g].append((r.id, r, self._mirror_commit(g)))
                self._ripe_dirty.add(g)
                self._ripe_waiting += 1
                if obs_on:
                    self.obs.c_reads_lease.inc()
            else:
                self._reads[g].append((r.id, r))
                self._read_dirty.add(g)
                self._reads_waiting += 1
            if obs_on:
                self.obs.g_read_parked.inc()
        try:
            result = q.get(timeout=timeout or self.cfg.request_timeout)
        except queue.Empty:
            if obs_on:
                self.obs.c_reads_failed.inc()
            self.wait.cancel(r.id)
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="quorum read timed out",
                                   index=int(self.applied[g]))
        finally:
            if obs_on:
                self.obs.g_read_parked.dec()
        if obs_on:
            self.obs.s_read_dur.observe(
                (time.perf_counter() - t0) * 1000.0)
        if isinstance(result, errors.EtcdError):
            raise result
        return result

    def _confirm_reads(self, read_take: Dict[int, int], conf: np.ndarray,
                       rc: np.ndarray) -> None:
        """Move snapshotted parked reads of confirmed groups to the ripe
        queue at this round's captured read index. Only the
        PRE-DISPATCH snapshot count moves — a read that parked after the
        step was dispatched could postdate a write acked at a commit
        index above the captured one, so it waits for its own round.
        Unconfirmed groups keep their reads parked: a deposed leader's
        reads either re-confirm under the next leader (at its >= read
        index — still linearizable) or time out; never served stale."""
        o = self.obs if self.obs.enabled else None
        n_conf = 0
        now = time.monotonic()
        lease_s = self.cfg.read_lease_ms / 1000.0
        with self._lock:
            for g, take in read_take.items():
                if not conf[g]:
                    continue
                n_conf += 1
                ri = int(rc[g])
                dq = self._reads[g]
                moved = min(take, len(dq))
                for _ in range(moved):
                    self._ripe[g].append(dq.popleft() + (ri,))
                if moved:
                    self._ripe_dirty.add(g)
                    self._ripe_waiting += moved
                    self._reads_waiting -= moved
                if not dq:
                    self._read_dirty.discard(g)
                if lease_s > 0:
                    # A confirmed quorum round proves leadership NOW;
                    # the clock bound extends it lease_ms forward.
                    self._lease_until[g] = now + lease_s
                    self._lease_term[g] = self._mirror_term(g)
        if o:
            o.h_read_confirms.observe(n_conf)

    def _serve_ripe_reads(self) -> None:
        """Serve every ripe read whose group's apply cursor has reached
        its read index. Queue surgery holds self._lock; the store gets
        (GIL-released in the C core) and waiter triggers run outside
        it. Per group the ripe queue is FIFO and read indexes are
        nondecreasing (commit is monotone within a term, and a new
        leader's own-term-committed index covers everything previously
        committed), so serving stops at the first not-yet-applied
        head."""
        served: List[Tuple[int, Request, int]] = []
        with self._lock:
            for g in list(self._ripe_dirty):
                dq = self._ripe[g]
                a = int(self.applied[g])
                while dq and dq[0][2] <= a:
                    rid, r, _ri = dq.popleft()
                    served.append((rid, r, g))
                if not dq:
                    self._ripe_dirty.discard(g)
            self._ripe_waiting -= len(served)
        if not served:
            return
        o = self.obs if self.obs.enabled else None
        tr = self.obs.tracer
        # Read coalescing: every read in this pass is at-or-past its
        # read index NOW, so one store get per distinct (group, path,
        # recursive, sorted) answers all of them — the get's instant
        # lies inside every coalesced read's [park, serve] window,
        # which is all linearizability requires. (The reference serves
        # a whole ReadIndex batch from one state the same way,
        # read_only.go advance; hot-key read storms collapse to one
        # tree walk per key per round.)
        memo: Dict[Tuple[int, str, bool, bool], Any] = {}
        for rid, r, g in served:
            k = (g, r.path, r.recursive, r.sorted)
            result = memo.get(k)
            if result is None:
                try:
                    result = self.store(g).get(r.path, r.recursive,
                                               r.sorted)
                except errors.EtcdError as err:
                    result = err
                memo[k] = result
            self.wait.trigger(rid, result)
            if tr.every:
                tr.mark(rid, "acked", g=g)
        if o:
            o.c_reads_served.inc(len(served))

    def _fail_parked_reads(self, why: str) -> None:
        """Fail every parked and ripe quorum read (engine shutdown) so
        serving threads don't ride out the full request timeout."""
        rids: List[int] = []
        with self._lock:
            for g in self._read_dirty:
                rids.extend(rid for rid, _r in self._reads[g])
                self._reads[g].clear()
            for g in self._ripe_dirty:
                rids.extend(rid for rid, _r, _i in self._ripe[g])
                self._ripe[g].clear()
            self._read_dirty.clear()
            self._ripe_dirty.clear()
            self._reads_waiting = 0
            self._ripe_waiting = 0
        for rid in rids:
            self.wait.trigger(rid, errors.EtcdError(
                errors.ECODE_RAFT_INTERNAL, cause=why))

    def conf_change(self, g: int, op: str, slot: int,
                    timeout: Optional[float] = None) -> List[int]:
        """Propose a membership change for group g through its own
        consensus; returns the new active slot list (reference
        configure() server.go:640-662 + multinode group management)."""
        if not 0 <= slot < self.cfg.peers:
            raise ValueError(f"slot {slot} out of range")
        if op == "add":
            if self.h_mask[g, slot]:
                raise errors.EtcdError(errors.ECODE_NODE_EXIST,
                                       cause=f"slot {slot} already active")
        elif op == "remove":
            if not self.h_mask[g, slot]:
                raise errors.EtcdError(errors.ECODE_KEY_NOT_FOUND,
                                       cause=f"slot {slot} not active")
        else:
            raise ValueError(op)
        rid = self.reqid.next()
        payload = bytes([P_CONF]) + json.dumps(
            {"id": rid, "op": op, "slot": slot}).encode()
        q = self.wait.register(rid)
        with self._lock:
            self._pending[g].append((rid, payload, None))
            self._dirty.add(g)
            self._confs_outstanding += 1
        try:
            result = q.get(timeout=timeout or self.cfg.request_timeout)
        except queue.Empty:
            self.wait.cancel(rid)
            raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                   cause="conf change timed out")
        if isinstance(result, errors.EtcdError):
            raise result
        return result

    # ------------------------------------------------------------------
    # tenant lifecycle (the engine's CreateGroup/RemoveGroup — reference
    # raft/multinode.go:181-218 — over a fixed pre-compiled pool)
    # ------------------------------------------------------------------

    def tenant_active(self, g: int) -> bool:
        """Provisioned = at least one active peer slot."""
        return bool(self.h_mask[g].any())

    def tenants(self) -> List[int]:
        return [int(g) for g in np.nonzero(self.h_mask.any(axis=1))[0]]

    def create_tenant(self, g: Optional[int] = None,
                      n_peers: Optional[int] = None,
                      timeout: Optional[float] = None) -> int:
        """Provision a tenant group at runtime (g=None allocates the
        lowest free pool slot). Returns the group id once the creation is
        DURABLE (its conf flips fsynced in a round record). No
        recompilation: the kernel shape is the pool; creation is a masked
        state reset + peer-mask flips, exactly the shape a committed
        membership change already takes in the WAL — so replay needs no
        new machinery."""
        n = n_peers or self.cfg.initial_peers or self.cfg.peers
        if not 1 <= n <= self.cfg.peers:
            raise ValueError(f"n_peers {n} out of range 1..{self.cfg.peers}")
        return self._admin({"op": "create", "g": g, "n": n}, timeout)

    def remove_tenant(self, g: int,
                      timeout: Optional[float] = None) -> int:
        """Deprovision a tenant: all peer slots go inactive, its store,
        payloads and pending proposals are dropped (pending waiters get an
        error), and the pool slot becomes reusable."""
        return self._admin({"op": "remove", "g": int(g)}, timeout)

    def _admin(self, op: dict, timeout: Optional[float]) -> int:
        done = threading.Event()
        out: dict = {}
        item = (op, done, out)
        with self._lock:
            self._admin_q.append(item)
        if not done.wait(timeout or self.cfg.request_timeout):
            # Withdraw the op if it never started — a timed-out create must
            # not silently provision later (a client retry would then
            # consume a second pool slot). If it already left the queue,
            # give the in-flight execution a short grace.
            with self._lock:
                try:
                    self._admin_q.remove(item)
                    withdrawn = True
                except ValueError:
                    withdrawn = False
            if withdrawn or not done.wait(2.0):
                raise errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                       cause="tenant admin op timed out")
        if "err" in out:
            raise out["err"]
        return out["g"]

    def _process_admin(self) -> None:
        """Apply queued tenant ops at a round boundary: device surgery via
        the shared per-slot conf machinery (CONF_ADD zeroes the slot on
        both live and replay paths — a freshly created tenant IS a set of
        added slots). The flips are persisted in their OWN record at this
        boundary, BEFORE the upcoming round's record: live surgery happens
        before the round runs, so replay must zero the slot before it sees
        that round's term/vote/commit deltas — appending the flips to the
        round's record would replay them AFTER its HS deltas and wipe the
        new group's first campaign (a restarted slot could then re-vote at
        a term it already voted in). Requester acks fire after the flips'
        fsync."""
        self._drain_applies()    # applies must not straddle the surgery
        with self._lock:
            ops = list(self._admin_q)
            self._admin_q.clear()
        for op, done, out in ops:
            try:
                if op["op"] == "create":
                    g = op["g"]
                    if g is None:
                        free = np.nonzero(~self.h_mask.any(axis=1))[0]
                        if not len(free):
                            raise errors.EtcdError(
                                errors.ECODE_RAFT_INTERNAL,
                                cause=f"tenant pool exhausted "
                                      f"({self.cfg.groups} groups)")
                        g = int(free[0])
                    g = int(g)
                    if not 0 <= g < self.cfg.groups:
                        raise errors.EtcdError(
                            errors.ECODE_KEY_NOT_FOUND,
                            cause=f"group {g} outside pool")
                    if self.h_mask[g].any():
                        raise errors.EtcdError(
                            errors.ECODE_NODE_EXIST,
                            cause=f"tenant {g} already provisioned")
                    self._tenant_reset(g)
                    for s in range(op["n"]):
                        self._apply_conf(g, "add", s, admin=True)
                        self._admin_flips.append((g, s, CONF_ADD))
                    # Fast first election (same trick as boot stagger).
                    el = np.asarray(self.st.elapsed).copy()
                    el[g, g % op["n"]] = 2 * self.cfg.election_tick
                    self.st = self.st._replace(
                        elapsed=self._dev("elapsed", el))
                    out["g"] = g
                else:
                    g = int(op["g"])
                    if not (0 <= g < self.cfg.groups
                            and self.h_mask[g].any()):
                        raise errors.EtcdError(
                            errors.ECODE_KEY_NOT_FOUND,
                            cause=f"no such tenant {g}")
                    for s in np.nonzero(self.h_mask[g])[0]:
                        self._apply_conf(g, "remove", int(s), admin=True)
                        self._admin_flips.append((g, int(s), CONF_REMOVE))
                    self._tenant_reset(g)
                    out["g"] = g
            except Exception as e:  # noqa: BLE001 — relayed to requester
                out["err"] = e
                done.set()
                continue
            self._admin_acks.append(done)
        if self._admin_flips:
            rec = RoundRecord(round_no=self.round_no)
            rec.confs.extend(self._admin_flips)
            self._admin_flips = []
            self.wal.append_sync(rec)     # fsync: the op is durable NOW
            self._recent_recs.append(rec)
        for done in self._admin_acks:
            done.set()
        self._admin_acks = []

    def _tenant_reset(self, g: int) -> None:
        """Drop all host-side state of a pool slot (store, payloads,
        apply cursor, queued proposals)."""
        self.tenant_gen[g] += 1
        st = self._stores.pop(g, None)
        if st is not None:
            st.watcher_hub.clear()   # wake/close blocked watchers
        self.applied[g] = 0
        self._sync_pending.pop(g, None)
        for k in [k for k in self.payloads if k[0] == g]:
            del self.payloads[k]
            self.payload_reqs.pop(k, None)
        with self._lock:
            dq = self._pending[g]
            while dq:
                rid = dq.popleft()[0]
                self.wait.trigger(rid, errors.EtcdError(
                    errors.ECODE_RAFT_INTERNAL, cause="tenant removed"))
            self._dirty.discard(g)

    def _stage_syncs(self, now: float) -> None:
        """Enqueue METHOD_SYNC for every tenant whose store holds an
        expiration <= now. At most one SYNC in flight per tenant (a
        leaderless group must not accumulate one queued SYNC per interval);
        the inflight marker self-heals by deadline in case the SYNC entry
        is orphaned by a leader change and never applies."""
        due = [g for g, s in list(self._stores.items())
               if (x := s.next_expiration()) is not None and x <= now
               and self._sync_pending.get(g, 0.0) <= now]
        if not due:
            return
        redeadline = now + max(2.0, 10 * self.cfg.sync_interval)
        with self._lock:
            for g in due:
                self._sync_pending[g] = redeadline
                r = Request(method=METHOD_SYNC, time=now,
                            id=self.reqid.next())
                self._pending[g].append((r.id, bytes([P_REQ]) + r.encode(),
                                         r))
                self._dirty.add(g)

    def status(self, g: int) -> dict:
        """Introspection snapshot for one group (/debug/vars analogue)."""
        lead = self.leader_slot(g)
        return {
            "group": g,
            "lead": lead,
            "term": int(self.h_term[g].max()),
            "commit": int(self.h_commit[g].max()),
            "applied": int(self.applied[g]),
            "active_slots": [int(s) for s in np.nonzero(self.h_mask[g])[0]],
        }

    def profile(self, rounds: int = 20, out_dir: Optional[str] = None) -> str:
        """Capture an XLA/device profile of `rounds` engine rounds (the
        per-batch-step profiler hook SURVEY §5 calls for). Writes a
        TensorBoard-loadable trace under <data_dir>/profiles and returns
        the path. Drive rounds manually if the engine thread isn't
        running."""
        import os
        out = out_dir or os.path.join(self.cfg.data_dir, "profiles")
        os.makedirs(out, exist_ok=True)
        running = self._thread is not None and self._thread.is_alive()
        with self._jax.profiler.trace(out):
            if running:
                target = self.round_no + rounds
                while (self.round_no < target
                       and not self._stop_ev.is_set()):
                    time.sleep(0.001)
            else:
                for _ in range(rounds):
                    self.run_round()
        return out

    # ------------------------------------------------------------------
    # the round
    # ------------------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop_ev.is_set():
                self.run_round()
                if self.cfg.round_interval:
                    time.sleep(self.cfg.round_interval)
        except Exception as e:  # noqa: BLE001 — record, then re-raise
            self.failed = e
            self._stop_ev.set()
            raise

    def run_round(self) -> None:
        """One engine round. Callable directly (tests drive the engine
        synchronously); the background thread just loops it."""
        t_round = time.perf_counter()
        jnp, kernel = self._jnp, self._kernel
        G, P, W, E = (self.cfg.groups, self.cfg.peers, self.cfg.window,
                      self.cfg.max_ents)
        o = self.obs if self.obs.enabled else None
        r_no = self.round_no
        self._last_admitted = 0
        self._trace_rids.clear()
        if o:
            o.flight.mark(r_no, obs_mod.SUBMITTED, t_round)

        # -- -1. tenant lifecycle admin ops (rare; round-boundary surgery)
        if self._admin_q:
            self._process_admin()

        # -- 0. TTL expiry: stage a replicated SYNC into tenants holding a
        # DUE expiration (leader-clock cutoff; deletion applies — and
        # replays — deterministically from the log).
        if self.cfg.sync_interval:
            now = time.time()
            if now - self._last_sync_scan >= self.cfg.sync_interval:
                self._last_sync_scan = now
                self._stage_syncs(now)

        # -- 1. stage proposals at known leaders --------------------------
        prop_count = np.zeros(G, np.int32)
        prop_slot = np.zeros(G, np.int32)
        self._staged.clear()
        with self._lock:
            if self._dirty:
                # One vectorized pass instead of a per-group leader_slot
                # call (16k np calls/round at bench scale); .tolist() once
                # beats 16k numpy scalar __getitem__s in the loop below.
                lead_rows = (np.where(self.h_mask, self.h_state, 0)
                             == _LEADER)
                has_lead = lead_rows.any(axis=1).tolist()
                lead_slots = lead_rows.argmax(axis=1).tolist()
            B = self.cfg.batch_max
            for g in list(self._dirty):
                dq = self._pending[g]
                if not dq:
                    self._dirty.discard(g)
                    continue
                if not has_lead[g]:
                    continue
                s = lead_slots[g]
                # Pack queued requests into at most E log entries of up to
                # B requests each (group commit): conf changes stay
                # singleton entries (their committed-boundary scan keys on
                # the payload tag), plain requests coalesce.
                ents: List[List[Tuple[int, bytes]]] = []
                while dq and len(ents) < E:
                    if dq[0][1] and dq[0][1][0] == P_CONF:
                        ents.append([dq.popleft()])
                        continue
                    cur: List[Tuple[int, bytes]] = []
                    nbytes = 0
                    while (dq and len(cur) < B
                           and nbytes < self.cfg.batch_bytes and dq[0][1]
                           and dq[0][1][0] == P_REQ):
                        nbytes += len(dq[0][1])
                        cur.append(dq.popleft())
                    if not cur:
                        # Head is neither P_CONF nor P_REQ (empty or junk
                        # tag): consume it or the group jams on count=0
                        # entries forever; fail its waiter immediately
                        # rather than letting the client ride out the
                        # full request timeout.
                        rid, junk = dq.popleft()[:2]
                        log.error("engine: dropping untagged proposal "
                                  "g=%d rid=%d len=%d", g, rid, len(junk))
                        self.wait.trigger(rid, errors.EtcdError(
                            errors.ECODE_RAFT_INTERNAL,
                            cause="untagged proposal dropped"))
                        continue
                    ents.append(cur)
                if not dq:
                    self._dirty.discard(g)
                self._staged[g] = (s, ents)
        # One pass builds the staged index arrays; they feed the two
        # scatter writes here AND the admission gather after the step
        # (_staged is round-thread-private and not mutated in between).
        # Batching replaces ~2*G numpy scalar stores at ~0.2 µs each.
        staged_gs = staged_ss = None
        if self._staged:
            gs_l, ss_l, cnt_l = [], [], []
            for g, (s, ents) in self._staged.items():
                gs_l.append(g)
                ss_l.append(s)
                cnt_l.append(len(ents))
            staged_gs = np.asarray(gs_l, np.int64)
            staged_ss = np.asarray(ss_l, np.int64)
            prop_count[staged_gs] = cnt_l
            prop_slot[staged_gs] = ss_l

        # -- 1b. read plane: snapshot how many parked quorum reads each
        # group carries BEFORE the step is dispatched. A read parking
        # after this point must not adopt this round's confirmation —
        # an applier running under the device step could ack a write
        # whose commit index exceeds the index this round captures, and
        # serving such a late read at the captured index would miss that
        # acked write. The snapshot pins exactly which reads this
        # round's confirmation covers (see tests/test_read_plane.py).
        read_take: Optional[Dict[int, int]] = None
        if self._reads_waiting:
            with self._lock:
                if self._reads_waiting:
                    read_take = {g: len(self._reads[g])
                                 for g in self._read_dirty
                                 if self._reads[g]}

        ph = self.phase_s
        t_ph = time.perf_counter()
        ph["stage"] = ph.get("stage", 0.0) + (t_ph - t_round)
        if o:
            o.h_phase["stage"].observe(t_ph - t_round)

        # -- 2. the kernel round (fused step + routing: one ASYNC
        # dispatch; jax queues it and returns immediately) ----------------
        tick = (self.round_no % self.cfg.ticks_per_round) == 0
        flags_d = anh_d = None
        conf_d = rc_d = None
        if read_take:
            # A ReadIndex round is a full round (proposals, ticks and
            # the forced leader heartbeat all ride the same program) but
            # skips the compact path: the read step returns no flag map,
            # and the confirmation wants the full mirror refresh anyway.
            st, inbox, conf_d, rc_d = self._step_fn_r(
                self.st, self.inbox,
                jnp.asarray(prop_count), jnp.asarray(prop_slot),
                jnp.asarray(bool(tick)))
        elif self._compact:
            st, inbox, flags_d, anh_d = self._step_fn_c(
                self.st, self.inbox,
                jnp.asarray(prop_count), jnp.asarray(prop_slot),
                jnp.asarray(bool(tick)))
        else:
            st, inbox = self._step_fn(
                self.st, self.inbox,
                jnp.asarray(prop_count), jnp.asarray(prop_slot),
                jnp.asarray(bool(tick)))
        self.st = st
        self.inbox = inbox
        t_now = time.perf_counter()
        d_dispatch = t_now - t_ph
        ph["dispatch"] = ph.get("dispatch", 0.0) + d_dispatch
        t_ph = t_now

        # -- 3. read back round k (blocks until the device finishes; the
        # GIL is released while waiting, so the applier thread makes
        # progress on earlier rounds' committed work here). Compact mode
        # reads the on-device diff flags first and fetches values for
        # only the changed rows; need_host rounds and rounds changing
        # more rows than the cap take the full readback below. ----------
        rec = None
        need_host = None
        d_readback = d_record = 0.0
        t_stepped = t_ph
        if flags_d is not None:
            # Check the 1-byte attestation BEFORE pulling the flag map:
            # need-host/post-surgery rounds take the full readback anyway
            # and must not pay a discarded (G, P) transfer first.
            if not bool(anh_d) and not self._force_full:
                flags_np = np.asarray(flags_d)
                t_now = time.perf_counter()
                d_readback = t_now - t_ph
                ph["readback"] = ph.get("readback", 0.0) + d_readback
                t_ph = t_stepped = t_now
                rec = self._compact_record_admit(flags_np, staged_gs,
                                                 staged_ss)
                if rec is not None:
                    t_now = time.perf_counter()
                    d_record = t_now - t_ph
                    ph["record"] = ph.get("record", 0.0) + d_record
                    t_ph = t_now
        if rec is None:
            (term, vote, commit, state, last, ring, need_host) = (
                np.array(a) for a in
                self._jax.device_get(
                    (st.term, st.vote, st.commit, st.state,
                     st.last_index, st.log_term, st.need_host)))
            t_now = time.perf_counter()
            d_readback = t_now - t_ph
            ph["readback"] = ph.get("readback", 0.0) + d_readback
            t_ph = t_stepped = t_now

            # Violation check FIRST — before this round's WAL append,
            # applies, or acks: a flagged round's commits come from state
            # the kernel just classified as untrustworthy, and must never
            # reach clients.
            if need_host.any():
                from etcd_tpu.ops.state import NH_VIOLATION
                viol = (need_host & NH_VIOLATION) != 0
                if viol.any():
                    self._fail_violation(viol)

            # -- 5. durable round record ----------------------------------
            rec = RoundRecord(round_no=self.round_no)
            chg = (term != self.h_term) | (vote != self.h_vote) | \
                  (commit != self.h_commit)
            gi, pi = np.nonzero(chg)
            rec.hs_g, rec.hs_p = gi.astype(np.uint32), pi.astype(np.uint16)
            rec.hs_term = term[gi, pi].astype(np.uint32)
            rec.hs_vote = vote[gi, pi].astype(np.uint16)
            rec.hs_commit = commit[gi, pi].astype(np.uint32)

            last_chg = last != self.h_last
            gi, pi = np.nonzero(last_chg)
            rec.last_g = gi.astype(np.uint32)
            rec.last_p = pi.astype(np.uint16)
            rec.last_v = last[gi, pi].astype(np.uint32)

            # Ring diff in two stages: a vectorized per-row any-reduction
            # finds the rows whose ring changed (SIMD compare — NOT the
            # 3-axis np.nonzero over (G, P, W) that dominated host cost
            # at 100k groups), then the slot-level diff runs only on
            # those rows. The full compare is required for correctness:
            # an equal-length conflict overwrite can change ring terms in
            # a round where that row's term/vote/commit/last are ALL
            # unchanged (the follower adopted the new leader's term in an
            # earlier round), so a HardState-based row filter would
            # silently drop the overwrite from the WAL and crash replay
            # would resurrect superseded entries.
            act_g, act_p = np.nonzero(np.any(ring != self.h_ring, axis=2))
            if len(act_g):
                sub = ring[act_g, act_p] != self.h_ring[act_g, act_p]
                ai, wi = np.nonzero(sub)
                gi, pi = act_g[ai], act_p[ai]
                lastv = last[gi, pi]
                # ring slot w holds absolute index
                # i = last - ((last - w) mod W)
                absi = lastv - ((lastv - wi) % W)
                keep = absi >= 1
                rec.ring_g = gi[keep].astype(np.uint32)
                rec.ring_p = pi[keep].astype(np.uint16)
                rec.ring_i = absi[keep].astype(np.uint32)
                rec.ring_t = ring[gi[keep], pi[keep],
                                  wi[keep]].astype(np.uint32)

            # Index assignment for admitted proposals: a pre-existing
            # leader admits in order at prev_last+1.. (its last_index can
            # move this round ONLY by admission: it was already leader,
            # so no no-op, and leaders ignore MsgApp).
            if self._staged:
                # Batch-gather the admission scalars: one fancy-indexed
                # pull per array instead of 6 numpy scalar reads per
                # staged group, reusing the index arrays built at staging
                # time.
                gs, ss = staged_gs, staged_ss
                t_gs = term[gs, ss]
                adm_l = np.where((state[gs, ss] == _LEADER)
                                 & (t_gs == self.h_term[gs, ss]),
                                 last[gs, ss] - self.h_last[gs, ss],
                                 0).tolist()
                self._admit_staged(rec, adm_l, t_gs.tolist(),
                                   self.h_last[gs, ss].tolist())

            self.h_term, self.h_vote, self.h_commit = term, vote, commit
            self.h_state, self.h_last, self.h_ring = state, last, ring
            self._force_full = False   # mirrors == device state again
            t_now = time.perf_counter()
            d_record = t_now - t_ph
            ph["record"] = ph.get("record", 0.0) + d_record
            t_ph = t_now

        # -- 5b. read plane: pop the snapshotted reads of every group
        # whose ReadIndex confirmation landed into the ripe queue at the
        # captured commit index (read rounds always take the full
        # readback above, so the mirrors the confirmation consults are
        # this round's).
        if conf_d is not None:
            self._confirm_reads(read_take, np.asarray(conf_d),
                                np.asarray(rc_d))

        # -- 6. persist, then apply+ack. WAL fsync strictly precedes the
        # acks of everything this round committed (doc.go:31-39 ordering)
        # — by GATING, not by inline ordering: the record is handed to
        # the writer compartment (which group-commits it with its queue
        # neighbors on its own thread) and the applier workers withhold
        # waiter wakeups until the writer's durability watermark passes
        # this round's ticket. Applies may run ahead of the fsync; acks
        # may not. Membership flips committed this round must be in the
        # SAME durable record as the round that commits them (replay
        # re-applies them) — and conf traffic forces the SYNCHRONOUS
        # path: applying a conf performs device-state surgery that must
        # precede the next dispatch, so the record is appended+fsynced
        # before the inline apply below (append_sync).
        if o:
            o.h_phase["dispatch"].observe(d_dispatch)
            o.h_phase["readback"].observe(d_readback)
            o.h_step.observe(d_dispatch + d_readback)
            o.h_phase["record"].observe(d_record)
            o.flight.mark(r_no, obs_mod.STEPPED, t_stepped)
            if self._staged:
                o.h_batch.observe(self._last_admitted)
        rec.confs.extend(self._collect_committed_confs())
        sync_round = bool(rec.confs or self._confs_outstanding
                          or not self.cfg.pipeline_applies)
        if not rec.is_empty():
            t0 = time.perf_counter()
            if sync_round or not self.cfg.pipeline_wal:
                self.wal.append_sync(rec)
            else:
                self.wal.submit(rec)
            ph["wal_submit"] = ph.get("wal_submit", 0.0) + \
                (time.perf_counter() - t0)
            if o:
                o.h_phase["wal_submit"].observe(time.perf_counter() - t0)
                o.flight.mark(r_no, obs_mod.WAL_SUBMITTED)
            tr = self.obs.tracer
            if tr.every and self._trace_rids:
                for rid in self._trace_rids:
                    tr.mark(rid, "wal_submit", ticket=self.wal.ticket)
            self._recent_recs.append(rec)
        if sync_round:
            self._drain_applies()
            t0 = time.perf_counter()
            a0 = self._acks.acked
            self._apply_committed(trigger=True)
            ph["apply"] = ph.get("apply", 0.0) + (time.perf_counter() - t0)
            if o:
                o.flight.mark(r_no, obs_mod.APPLIED)
                o.flight.mark(r_no, obs_mod.ACKED)
                if self._acks.acked > a0:
                    o.c_acked.inc(self._acks.acked - a0)
        else:
            self._enqueue_apply(self._commit_view())

        # -- 6b. read plane: serve every ripe read whose group has
        # applied past its read index. Sync rounds serve their own reads
        # immediately (the inline apply above advanced the cursor);
        # pipelined rounds serve reads the applier shards ripened while
        # the device step ran — at most one round of extra latency.
        if self._ripe_waiting:
            self._serve_ripe_reads()

        # -- 7. need_host: snapshot-install lagging followers (violations
        # already failed the round before anything was persisted or
        # acked). need_host is None on a compact round — the device
        # already attested any_need_host == False for it.
        if need_host is not None and need_host.any():
            self._service_need_host(need_host)

        ph["tail"] = ph.get("tail", 0.0) + (time.perf_counter() - t_ph)
        if o:
            o.h_phase["tail"].observe(time.perf_counter() - t_ph)
            o.c_rounds.inc()
        self.round_no += 1
        if (self.cfg.mask_check_rounds
                and self.round_no % self.cfg.mask_check_rounds == 0):
            self._check_mask()
        ms = (time.perf_counter() - t_round) * 1000.0
        if self.round_ms_ewma == 0.0:
            self.round_ms_ewma = ms      # seed with the first sample
        else:
            self.round_ms_ewma += 0.05 * (ms - self.round_ms_ewma)
        if self.round_no % self.cfg.checkpoint_rounds == 0:
            self._drain_applies()    # checkpoint state must be consistent
            self._checkpoint()
            self._gc_payloads()

    def _admit_staged(self, rec: RoundRecord, adm_l: list, t_l: list,
                      base_l: list) -> None:
        """Turn this round's staged entries into payload-store entries +
        WAL records (admitted) or requeue them (rejected: the group's
        leader changed or throttled admission). Shared by the full- and
        compact-readback tails; iteration order is self._staged's
        insertion order, which both tails' scalar lists follow."""
        requeue: List[Tuple[int, List[Tuple[int, bytes]]]] = []
        tr = self.obs.tracer
        n_admitted = 0
        for (g, (_, ents)), admitted, t, base in zip(
                self._staged.items(), adm_l, t_l, base_l):
            for j, items in enumerate(ents):
                if j < admitted:
                    i = base + 1 + j
                    payload = _pack_entry(items)
                    self.payloads[(g, i, t)] = payload
                    if payload[0] != P_CONF:
                        reqs = [it[2] for it in items]
                        if None not in reqs:
                            self.payload_reqs[(g, i, t)] = reqs
                    n_admitted += len(items)
                    if tr.every:
                        for it in items:
                            if tr.sampled(it[0]):
                                tr.mark(it[0], "admitted", g=g,
                                        round=rec.round_no)
                                self._trace_rids.append(it[0])
                    rec.entries.append((g, i, t, payload))
                else:
                    requeue.append(
                        (g, [it for e in ents[j:] for it in e]))
                    break
        self._last_admitted = n_admitted
        if requeue:
            with self._lock:
                for g, rest in requeue:
                    self._pending[g].extendleft(reversed(rest))
                    self._dirty.add(g)

    def _compact_record_admit(self, flags: np.ndarray,
                              staged_gs, staged_ss
                              ) -> Optional[RoundRecord]:
        """The compact-readback round tail: build the SAME durable round
        record (byte-identical; tests/test_engine_compact.py pins it)
        and run the same admission as the full tail, from a bounded
        gather of only the rows the device flagged as changed. Returns
        None when the round changed more rows than the cap — the caller
        then falls back to the full readback (saturation: the bulk
        transfer is amortized by the batch it carries)."""
        kernel = self._kernel
        jnp = self._jnp
        G, P, W = self.cfg.groups, self.cfg.peers, self.cfg.window
        chg_g, chg_p = np.nonzero(flags)
        lin = chg_g.astype(np.int64) * P + chg_p
        if staged_gs is not None:
            lin = np.unique(np.concatenate(
                [lin, staged_gs * P + staged_ss]))
        K = len(lin)
        if K > self._compact_cap:
            return None
        rec = RoundRecord(round_no=self.round_no)
        if K == 0:
            return rec
        gi = (lin // P).astype(np.int32)
        pi = (lin % P).astype(np.int32)
        # Pad to a size bucket so gather_rows retraces O(log K) times,
        # not per distinct K. Padding rows read (0, 0) — discarded.
        Kp = 256
        while Kp < K:
            Kp <<= 1
        gi_p = np.zeros(Kp, np.int32)
        pi_p = np.zeros(Kp, np.int32)
        gi_p[:K], pi_p[:K] = gi, pi
        t_k, v_k, c_k, s_k, l_k, r_k = (
            np.asarray(a)[:K] for a in kernel.gather_rows(
                self.st, jnp.asarray(gi_p), jnp.asarray(pi_p)))

        def rows(bit):
            g, p = np.nonzero((flags & bit) != 0)
            return g, p, np.searchsorted(lin, g.astype(np.int64) * P + p)

        g0, p0, pos0 = rows(kernel.CHG_HS)
        rec.hs_g = g0.astype(np.uint32)
        rec.hs_p = p0.astype(np.uint16)
        rec.hs_term = t_k[pos0].astype(np.uint32)
        rec.hs_vote = v_k[pos0].astype(np.uint16)
        rec.hs_commit = c_k[pos0].astype(np.uint32)

        g1, p1, pos1 = rows(kernel.CHG_LAST)
        rec.last_g = g1.astype(np.uint32)
        rec.last_p = p1.astype(np.uint16)
        rec.last_v = l_k[pos1].astype(np.uint32)

        g2, p2, pos2 = rows(kernel.CHG_RING)
        if len(g2):
            new_rows = r_k[pos2]                    # (n2, W)
            sub = new_rows != self.h_ring[g2, p2]
            ai, wi = np.nonzero(sub)
            lastv = l_k[pos2][ai]
            absi = lastv - ((lastv - wi) % W)
            keep = absi >= 1
            rec.ring_g = g2[ai][keep].astype(np.uint32)
            rec.ring_p = p2[ai][keep].astype(np.uint16)
            rec.ring_i = absi[keep].astype(np.uint32)
            rec.ring_t = new_rows[ai, wi][keep].astype(np.uint32)

        if self._staged:
            pos_s = np.searchsorted(lin, staged_gs * P + staged_ss)
            t_gs = t_k[pos_s]
            adm_l = np.where((s_k[pos_s] == _LEADER)
                             & (t_gs == self.h_term[staged_gs, staged_ss]),
                             l_k[pos_s]
                             - self.h_last[staged_gs, staged_ss],
                             0).tolist()
            self._admit_staged(
                rec, adm_l, t_gs.tolist(),
                self.h_last[staged_gs, staged_ss].tolist())

        # Mirror update LAST (admission reads the pre-round mirrors).
        # Gathered values are authoritative for every union row —
        # writing back an unchanged staged row is a no-op.
        self.h_term[gi, pi] = t_k
        self.h_vote[gi, pi] = v_k
        self.h_commit[gi, pi] = c_k
        self.h_state[gi, pi] = s_k
        self.h_last[gi, pi] = l_k
        self.h_ring[gi, pi] = r_k
        return rec

    # ------------------------------------------------------------------
    # apply
    # ------------------------------------------------------------------

    def _group_commit(self) -> np.ndarray:
        c = np.where(self.h_mask, self.h_commit, 0)
        return c.max(axis=1)

    def _committed_span(self, g: int):
        """(slot, lo, hi] apply span for group g using the slot that has
        the highest commit (its ring covers the span: the admission
        throttle keeps last-commit <= W/2, so hi > last - W)."""
        row = np.where(self.h_mask[g], self.h_commit[g], 0)
        s = int(row.argmax())
        return s, int(self.applied[g]), int(row[s])

    def _collect_committed_confs(self) -> List[Tuple[int, int, int]]:
        """Scan newly committed spans for conf payloads WITHOUT applying —
        their mask flips must be in the same durable record as the round
        that commits them."""
        out = []
        if self._confs_outstanding == 0:
            # Common case: no membership change in flight anywhere — skip
            # re-scanning every committed span (the apply loop scans them
            # again right after; this scan only exists to bind mask flips
            # into the committing round's durable record).
            return out
        # The scan spans applied..commit, and `applied` is applier-owned:
        # settle it first (conf rounds are rare; the drain is the price of
        # binding flips into the right record).
        self._drain_applies()
        gc = self._group_commit()
        for g in np.nonzero(gc > self.applied)[0]:
            s, lo, hi = self._committed_span(int(g))
            for i in range(lo + 1, hi + 1):
                t = int(self.h_ring[g, s, i % self.cfg.window])
                payload = self.payloads.get((int(g), i, t))
                if payload and payload[0] == P_CONF:
                    d = json.loads(payload[1:].decode())
                    op = CONF_ADD if d["op"] == "add" else CONF_REMOVE
                    out.append((int(g), d["slot"], op))
        return out

    def _apply_committed(self, trigger: bool, hist=None, view=None,
                         g_lo: int = 0, g_hi: Optional[int] = None,
                         acct: Optional[_AckCounter] = None,
                         sink: Optional[_AckBatch] = None) -> None:
        """Apply every newly committed entry (applied..commit per group)
        to its tenant store and trigger waiters. `view` is an immutable
        (gc, s_vec, ring, last, ticket) snapshot when called from an
        applier worker; None applies against the live mirrors
        (synchronous callers + replay). [g_lo, g_hi) restricts the pass
        to one shard's tenant range (workers touch only their own slice
        of self.applied and their own stores); acct is the ack tally to
        charge — the worker's own, or the engine's synchronous one.
        With `sink` set, waiter wakeups and the ack tally are DEFERRED
        into it instead of fired inline — the worker releases them after
        the view's durability ticket clears the WAL watermark."""
        W = self.cfg.window
        tr = self.obs.tracer
        if acct is None:
            acct = self._acks
        if view is None:
            view = self._commit_view()
        gc, s_vec, h_ring, h_last = view[:4]
        if g_hi is None:
            g_hi = len(gc)
        changed = np.nonzero(gc[g_lo:g_hi] > self.applied[g_lo:g_hi])[0]
        for g in changed:
            g = int(g) + g_lo
            s, lo, hi = int(s_vec[g]), int(self.applied[g]), int(gc[g])
            ring_row = h_ring[g, s]
            last_gs = int(h_last[g, s])
            for i in range(lo + 1, hi + 1):
                t = 0
                if i > last_gs - W:
                    t = int(ring_row[i % W])
                if t == 0 and hist is not None:
                    # Restore path: the span slot's ring can hold the 0
                    # sentinel INSIDE the window — a slot removed and
                    # later re-added had its ring zeroed at the join, so
                    # indices below its join point are unresolvable from
                    # it even though other slots know them. hist (built
                    # from every slot's replayed log history) supplies
                    # the committed term; without this fallback those
                    # entries would silently apply as leader no-ops and
                    # ACKED WRITES WOULD VANISH on restart (soak-found).
                    t = hist.get((g, i), 0)
                if t == 0:
                    # Live path: unreachable (applies are incremental, so
                    # the span never reaches below a re-added slot's join
                    # point or the ring window); refusing beats
                    # misapplying.
                    log.error("engine: no term for committed entry g=%d "
                              "i=%d (slot=%d last=%d)", g, i, s, last_gs)
                    continue
                key = (g, i, t)
                payload = self.payloads.get(key)
                if payload is None:
                    continue  # leader no-op
                if payload[0] in (P_REQ, P_MULTI):
                    # Coalesced entries: each request applies independently
                    # in order, with its own result/error and its own
                    # waiter trigger — semantically identical to one entry
                    # per request. The live path reuses the Requests
                    # decoded at proposal time (payload_reqs sidecar);
                    # replay decodes from the durable bytes.
                    reqs = self.payload_reqs.pop(key, None)
                    if reqs is None:
                        if payload[0] == P_REQ:
                            reqs = (Request.decode(payload[1:]),)
                        else:
                            reqs = [Request.decode(b)
                                    for b in _unpack_multi(payload)]
                    if not trigger and tr.every:
                        # Restart replay: sampled rids ride the durable
                        # Request payloads, so the trace picks them back
                        # up in the new process.
                        for r0 in reqs:
                            tr.mark(r0.id, "replayed", g=g)
                    # Batched fast path: runs of plain-file PUTs with no
                    # conditions and no TTL apply through ONE
                    # GIL-releasing C call per run
                    # (NativeStore.set_applied_many) instead of a full
                    # Python dispatch per request — the apply loop's
                    # throughput ceiling at scale. Waiter-held plain PUTs
                    # ride the batch too: their positions go in `need`,
                    # the C call returns raw node descriptors for them,
                    # and the waiter is woken with a LazyWriteEvent (the
                    # Event/JSON churn happens on the HTTP thread that
                    # resolves it, not here — the ack/waiter stage of the
                    # compartmentalized path). A request that carries
                    # conditions/TTL or isn't a plain PUT flushes the run
                    # and applies through the scalar path, preserving log
                    # order exactly. Runs never span log entries (the
                    # per-entry cursor advance below must stay exact).
                    # Fast-path requests are client writes (SYNC never
                    # qualifies: its method is not PUT); their per-op
                    # store errors count as served, same as a scalar
                    # error result.
                    st = self.store(g)
                    many = getattr(st, "set_applied_many", None)
                    is_reg = self.wait.is_registered
                    fp, fv, fneed, frids = [], [], [], []
                    for r in reqs:
                        if (many is not None and r.method == METHOD_PUT
                                and not r.dir and not r.refresh
                                and r.prev_exist is None
                                and not r.prev_index and not r.prev_value
                                and r.expiration is None):
                            if is_reg(r.id):
                                fneed.append(len(fp))
                                frids.append(r.id)
                            fp.append(r.path)
                            fv.append(r.val or "")
                            continue
                        if fp:
                            self._flush_many(st, fp, fv, fneed, frids,
                                             trigger, acct, sink)
                            fp, fv, fneed, frids = [], [], [], []
                        try:
                            result = self._apply_request(g, r)
                        except errors.EtcdError as err:
                            result = err
                        if trigger:
                            if tr.every:
                                tr.mark(r.id, "applied")
                            if sink is not None:
                                if r.method != METHOD_SYNC:
                                    sink.acked += 1
                                sink.items.append((r.id, result))
                            else:
                                if r.method != METHOD_SYNC:
                                    acct.acked += 1
                                self.wait.trigger(r.id, result)
                                if tr.every:
                                    tr.mark(r.id, "acked")
                    if fp:
                        self._flush_many(st, fp, fv, fneed, frids,
                                         trigger, acct, sink)
                elif payload[0] == P_CONF:
                    d = json.loads(payload[1:].decode())
                    self._apply_conf(g, d["op"], d["slot"])
                    if trigger:
                        self.wait.trigger(
                            d["id"],
                            [int(x) for x in np.nonzero(self.h_mask[g])[0]])
                # Advance the cursor PER ENTRY, not at span end: if an
                # apply raises mid-span, a retry (or post-mortem) must
                # resume after the last applied entry, never re-apply it
                # (duplicate watch events / double store mutations).
                self.applied[g] = i
            self.applied[g] = hi

    def _flush_many(self, st, fp: list, fv: list, fneed: list,
                    frids: list, trigger: bool, acct: _AckCounter,
                    sink: Optional[_AckBatch] = None) -> None:
        """Apply one batched run of plain-file PUTs. Positions listed in
        fneed hold waiters: the C call returns their raw node
        descriptors, and each waiter is woken with a LazyWriteEvent (or
        the per-op EtcdError) — Event materialization is deferred to the
        HTTP thread that resolves it in do(). With `sink`, wakeups and
        the tally are deferred for post-watermark release instead."""
        if not fneed:
            st.set_applied_many(fp, fv)
            if trigger:
                if sink is not None:
                    sink.acked += len(fp)
                else:
                    acct.acked += len(fp)
            return
        now = st.clock()
        _, descs = st.set_applied_many(fp, fv, need=fneed)
        if trigger:
            tr = self.obs.tracer
            if sink is not None:
                sink.acked += len(fp)
            else:
                acct.acked += len(fp)
            for (pos, nd, pd, idx), rid in zip(descs, frids):
                if nd is None:
                    code, cause = pd
                    res: Any = errors.EtcdError(code, cause=cause,
                                                index=idx)
                else:
                    res = LazyWriteEvent(nd, pd, idx, now)
                if tr.every:
                    tr.mark(rid, "applied")
                if sink is not None:
                    sink.items.append((rid, res))
                else:
                    self.wait.trigger(rid, res)
                    if tr.every:
                        tr.mark(rid, "acked")

    def _apply_request(self, g: int, r: Request):
        """Deterministic request->store mapping (reference applyRequest
        server.go:766-820), against the group's own tenant store."""
        st = self.store(g)
        exp = r.expiration
        if r.method == METHOD_POST:
            return st.create(r.path, is_dir=r.dir, value=r.val, unique=True,
                             expire_time=exp)
        if r.method == METHOD_PUT:
            if r.refresh:
                return st.update(r.path, None, exp, refresh=True)
            if r.prev_exist is not None:
                if r.prev_exist:
                    if r.prev_index or r.prev_value:
                        return st.compare_and_swap(r.path, r.prev_value,
                                                   r.prev_index, r.val, exp)
                    return st.update(r.path, r.val, exp)
                return st.create(r.path, is_dir=r.dir, value=r.val,
                                 expire_time=exp)
            if r.prev_index or r.prev_value:
                return st.compare_and_swap(r.path, r.prev_value,
                                           r.prev_index, r.val, exp)
            if not r.dir:
                # Unconditional file PUT — the apply loop's dominant op.
                # The native store skips Event materialization entirely
                # unless a watcher is live; a waiter-held id gets the raw
                # descriptors (LazyWriteEvent) and the HTTP thread that
                # consumes the result materializes the Event in do().
                if self.wait.is_registered(r.id):
                    lazy = getattr(st, "set_applied_lazy", None)
                    if lazy is not None:
                        return lazy(r.path, r.val, exp)
                    return st.set_applied(r.path, r.val, exp, True)
                return st.set_applied(r.path, r.val, exp, False)
            return st.set(r.path, is_dir=r.dir, value=r.val, expire_time=exp)
        if r.method == METHOD_DELETE:
            if r.prev_index or r.prev_value:
                return st.compare_and_delete(r.path, r.prev_value,
                                             r.prev_index)
            return st.delete(r.path, is_dir=r.dir, recursive=r.recursive)
        if r.method == METHOD_QGET:
            return st.get(r.path, r.recursive, r.sorted)
        if r.method == METHOD_SYNC:
            st.delete_expired_keys(r.time)
            self._sync_pending.pop(g, None)
            return None
        raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                               cause=f"bad method {r.method}")

    # ------------------------------------------------------------------
    # host surgery: conf changes + snapshot install
    # ------------------------------------------------------------------

    def _check_mask(self) -> None:
        """Liveness watchdog (EngineConfig.mask_check_rounds): the device
        peer_mask must ALWAYS equal the host h_mask — membership flows
        only host -> device through _apply_conf/_restore, in the round
        thread, with h_mask written first. Any divergence is therefore
        device buffer corruption. Observed mode (CPU backend, donated
        multi-hop step; disabling donation makes it vanish): the mask
        buffer comes back holding the step's is-leader intermediate —
        one active slot per group — which silences every cross-slot send
        AND suppresses campaigns, a permanent wedge since the corrupt
        value feeds the next round's donated step. Repair from the host
        copy (a fresh buffer: jnp.asarray of a live numpy array may be
        zero-copy, and the repaired mask enters the donated chain);
        recovery then needs no further help — the next tick's heartbeat
        timeout resumes the leader's paused probes and replication
        catches up."""
        m = np.asarray(self.st.peer_mask)
        if np.array_equal(m, self.h_mask):
            return
        self.mask_repairs += 1
        bad = int((m != self.h_mask).any(axis=1).sum())
        log.warning("device peer_mask diverged from host mask in %d "
                    "group(s) at round %d (repair #%d) — restoring",
                    bad, self.round_no, self.mask_repairs)
        self.st = self.st._replace(
            peer_mask=self._dev("peer_mask", self.h_mask.copy()))

    def _apply_conf(self, g: int, op: str, slot: int,
                    admin: bool = False) -> None:
        """Flip a membership bit at a committed boundary and reset the
        affected progress/vote columns (reference raft.go addNode/
        removeNode + multinode.go:181-218). admin=True flips come from the
        tenant-lifecycle path, which never incremented the outstanding-conf
        counter — decrementing would steal a concurrent real conf change's
        count and disable its committed-conf binding scan."""
        add = (op == "add")
        if not admin:
            with self._lock:   # pairs with conf_change's locked increment
                self._confs_outstanding = max(0, self._confs_outstanding - 1)
        self.h_mask[g, slot] = add
        mask = self._dev("peer_mask", self.h_mask)

        st = self.st
        if add:
            # Fresh empty follower state in the slot.
            def zero_at(name, a):
                arr = np.asarray(a).copy()
                arr[g, slot] = 0
                return self._dev(name, arr)

            ring = np.asarray(st.log_term).copy()
            ring[g, slot] = 0
            nxt = np.asarray(st.next).copy()
            nxt[g, :, slot] = 1        # every potential leader probes from 1
            match = np.asarray(st.match).copy()
            match[g, :, slot] = 0
            prs = np.asarray(st.pr_state).copy()
            prs[g, :, slot] = 0        # PR_PROBE
            paused = np.asarray(st.paused).copy()
            paused[g, :, slot] = False
            votes = np.asarray(st.votes).copy()
            votes[g, :, slot] = 0
            self.st = st._replace(
                peer_mask=mask,
                term=zero_at("term", st.term), vote=zero_at("vote", st.vote),
                commit=zero_at("commit", st.commit),
                lead=zero_at("lead", st.lead),
                state=zero_at("state", st.state),
                elapsed=zero_at("elapsed", st.elapsed),
                last_index=zero_at("last_index", st.last_index),
                log_term=self._dev("log_term", ring),
                next=self._dev("next", nxt),
                match=self._dev("match", match),
                pr_state=self._dev("pr_state", prs),
                paused=self._dev("paused", paused),
                votes=self._dev("votes", votes))
            self.h_ring[g, slot] = 0
            self.h_last[g, slot] = 0
            self.h_term[g, slot] = 0
            self.h_vote[g, slot] = 0
            self.h_commit[g, slot] = 0
            self.h_state[g, slot] = 0
        else:
            # Freeze the removed slot as an inert follower so a stale
            # LEADER row can never win leader_slot() again.
            stat = np.asarray(st.state).copy()
            stat[g, slot] = 0
            lead = np.asarray(st.lead).copy()
            lead[g, slot] = 0
            self.st = st._replace(peer_mask=mask,
                                  state=self._dev("state", stat),
                                  lead=self._dev("lead", lead))
            self.h_state[g, slot] = 0

    def _fail_violation(self, viol: np.ndarray) -> None:
        """NH_VIOLATION is a protocol-violation DETECTOR (an append
        conflicted at/below a committed index — reference log.go
        maybeAppend panics on this). Dump the flagged groups' full device
        state plus the recent WAL rounds for offline diagnosis, then
        refuse to continue: papering over it would let diverged state
        serve reads as if committed."""
        import os
        flagged = [int(g) for g in np.nonzero(viol.any(axis=1))[0]]
        arrays = self._jax.device_get({
            "term": self.st.term, "vote": self.st.vote,
            "commit": self.st.commit, "lead": self.st.lead,
            "state": self.st.state, "last_index": self.st.last_index,
            "log_term": self.st.log_term, "match": self.st.match,
            "next": self.st.next, "pr_state": self.st.pr_state,
            "need_host": self.st.need_host})
        dump = {
            "round": self.round_no,
            "flagged": {str(g): {
                "slots": [int(p) for p in np.nonzero(viol[g])[0]],
                "applied": int(self.applied[g]),
                "mask": np.asarray(self.h_mask[g]).tolist(),
                **{k: np.asarray(v[g]).tolist() for k, v in arrays.items()},
            } for g in flagged},
            "recent_rounds": [{
                "round": r.round_no,
                "hs": [[int(a), int(b), int(c), int(d), int(e)]
                       for a, b, c, d, e in zip(r.hs_g, r.hs_p, r.hs_term,
                                                r.hs_vote, r.hs_commit)],
                "ring": [[int(a), int(b), int(c), int(d)]
                         for a, b, c, d in zip(r.ring_g, r.ring_p,
                                               r.ring_i, r.ring_t)],
                "entries": [[g, i, t, len(p)] for g, i, t, p in r.entries],
                "confs": list(r.confs),
            } for r in self._recent_recs],
        }
        ddir = os.path.join(self.cfg.data_dir, "diagnostics")
        os.makedirs(ddir, exist_ok=True)
        path = os.path.join(ddir, f"violation-{self.round_no:016x}.json")
        with open(path, "w") as f:
            json.dump(dump, f)
        log.critical("engine: CONSENSUS SAFETY VIOLATION in groups %s "
                     "(conflict at/below commit); state dumped to %s",
                     flagged, path)
        # Flight-recorder auto-dump: the last <ring> rounds' stage
        # timeline, beside the state dump.
        self.obs.flight.dump(self.cfg.data_dir,
                             f"violation-{self.round_no:016x}")
        raise EngineViolation(
            f"conflict at/below commit in groups {flagged}; dump: {path}")

    def _service_need_host(self, need_host: np.ndarray) -> None:
        """Consume need_host flags: for each flagged group with a live
        leader, snapshot-install every active follower whose needed entries
        fell below the leader's ring window (the host side of MsgSnap,
        reference raft.go:246-260 + etcdserver snapshot catch-up §3.5)."""
        jax, jnp = self._jax, self._jnp
        st = self.st
        W = self.cfg.window
        flagged = np.nonzero(need_host.any(axis=1))[0]
        if not len(flagged):
            return
        nxt = np.asarray(st.next).copy()
        match = np.asarray(st.match).copy()
        prs = np.asarray(st.pr_state).copy()
        paused = np.asarray(st.paused).copy()
        term = self.h_term.copy()
        vote = self.h_vote.copy()
        commit = self.h_commit.copy()
        lastv = self.h_last.copy()
        ring = self.h_ring.copy()
        lead = np.asarray(st.lead).copy()
        stat = self.h_state.copy()
        elapsed = np.asarray(st.elapsed).copy()
        touched = False
        for g in flagged:
            g = int(g)
            s = self.leader_slot(g)
            if s < 0:
                continue
            c = int(commit[g, s])
            for f in np.nonzero(self.h_mask[g])[0]:
                f = int(f)
                if f == s:
                    continue
                # Lagging = the kernel's need_snap condition: entries from
                # next are no longer resolvable from the leader's ring
                # (next <= last - W; see kernel ents_ok/sendable).
                if nxt[g, s, f] > lastv[g, s] - W:
                    continue  # still reachable by appends
                if term[g, f] > term[g, s]:
                    continue  # follower is ahead in term; let raft sort it
                log.info("engine: snapshot-install g=%d slot=%d from "
                         "leader=%d commit=%d", g, f, s, c)
                if term[g, f] < term[g, s]:
                    vote[g, f] = 0
                term[g, f] = term[g, s]
                # Copy the leader's ring, but zero slots holding leader
                # entries ABOVE the install point: on the follower those
                # positions alias indices c-W..c and would otherwise carry
                # wrong terms (the device never reads them below commit,
                # but the WAL ring-diff would record the junk).
                row = ring[g, s].copy()
                l_s = int(lastv[g, s])
                for w in range(W):
                    if l_s - ((l_s - w) % W) > c:
                        row[w] = 0
                ring[g, f] = row
                lastv[g, f] = c
                commit[g, f] = c
                stat[g, f] = 0
                lead[g, f] = s + 1
                elapsed[g, f] = 0
                match[g, s, f] = c
                nxt[g, s, f] = c + 1
                prs[g, s, f] = 1       # PR_REPLICATE
                paused[g, s, f] = False
                touched = True
        nh = np.zeros_like(need_host)
        if touched:
            # Mirrors stay pre-surgery (see NOTE below); the next round
            # must therefore run the FULL readback so its diff journals
            # the install — a compact (device-vs-device) diff cannot see
            # surgery that happened between rounds.
            self._force_full = True
            self.st = st._replace(
                term=self._dev("term", term), vote=self._dev("vote", vote),
                commit=self._dev("commit", commit),
                last_index=self._dev("last_index", lastv),
                log_term=self._dev("log_term", ring),
                lead=self._dev("lead", lead),
                state=self._dev("state", stat),
                elapsed=self._dev("elapsed", elapsed),
                match=self._dev("match", match), next=self._dev("next", nxt),
                pr_state=self._dev("pr_state", prs),
                paused=self._dev("paused", paused),
                need_host=self._dev("need_host", nh))
            # NOTE: the h_* mirrors deliberately KEEP their pre-surgery
            # values — the next round's WAL diff then records the install's
            # term/commit/ring/last changes, making it durable.
        else:
            self.st = st._replace(need_host=self._dev("need_host", nh))

    # ------------------------------------------------------------------
    # checkpoint
    # ------------------------------------------------------------------

    def _checkpoint(self) -> None:
        import base64 as _b64
        state = {
            "round": self.round_no - 1,
            "term": np_b64(self.h_term), "vote": np_b64(self.h_vote),
            "commit": np_b64(self.h_commit), "last": np_b64(self.h_last),
            "ring": np_b64(self.h_ring), "mask": np_b64(self.h_mask),
            "applied": np_b64(self.applied),
            "stores": {str(g): s.save().decode()
                       for g, s in self._stores.items()},
            "payloads": [
                (g, i, t, _b64.b64encode(p).decode())
                for (g, i, t), p in self.payloads.items()
                if i > self.applied[g]],
        }
        self.wal.save_checkpoint(self.round_no - 1, state)

    def _gc_payloads(self) -> None:
        dead = [k for k in self.payloads if k[1] <= self.applied[k[0]]]
        for k in dead:
            del self.payloads[k]
            self.payload_reqs.pop(k, None)
        # Reconcile the conf counter: a conf entry superseded by leader
        # turnover never applies (so never decrements) and would pin the
        # committed-conf scan on forever. Recompute from ground truth —
        # un-applied admitted conf payloads PLUS confs still queued
        # (enqueued but unadmitted ones aren't in the payload store yet).
        with self._lock:
            self._confs_outstanding = sum(
                1 for (g, i, t), p in self.payloads.items()
                if p and p[0] == P_CONF and i > self.applied[g]) + sum(
                1 for dq in self._pending
                for it in dq if it[1] and it[1][0] == P_CONF)
