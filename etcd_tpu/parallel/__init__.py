"""Device-mesh parallelism for the batched consensus kernel."""

from etcd_tpu.parallel.mesh import (make_mesh, shard_state, state_sharding,
                                    mailbox_sharding)

__all__ = ["make_mesh", "shard_state", "state_sharding", "mailbox_sharding"]
