"""Batched DCN frame transport between engine hosts.

This is the host-to-host control plane of the multi-host MultiEngine
(server/hostengine.py): the consensus HOT path (votes, appends, acks,
commit metadata) rides the kernel's all_to_all collective over the mesh
peers axis and never touches this module — what remains is exactly what
the reference moves over rafthttp (rafthttp/transport.go:36-70):

  PROPOSE   client requests forwarded to the leader slot's host
  PAYLOAD   entry payloads fanned out by the admitting host (each host
            applies every group's store, like a reference member)
  PULL/RESP payload catch-up after drops or restarts

Transport semantics mirror the reference's peer transport (peer.go:87-190):
one ordered stream per peer pair, nonblocking sends into a bounded queue
with DROP on overflow plus a report_unreachable callback (peer.go:156-165;
the protocol retries via timeouts/pulls), background reconnect. Framing is
length-prefixed: u32 header-length + JSON header + u32 blob-length + blob.
"""
from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

log = logging.getLogger("etcd_tpu.frames")

_HDR = struct.Struct("<II")  # json length, blob length
_MAX_QUEUE = 4096


class FrameTransport:
    """Frames between N engine hosts on a static peer map."""

    def __init__(self, host_id: int, listen_addr: Tuple[str, int],
                 peers: Dict[int, Tuple[str, int]],
                 on_frame: Callable[[int, dict, bytes], None],
                 report_unreachable: Optional[Callable[[int], None]] = None
                 ) -> None:
        self.host_id = host_id
        self.peers = {int(h): tuple(a) for h, a in peers.items()
                      if int(h) != host_id}
        self.on_frame = on_frame
        self.report_unreachable = report_unreachable or (lambda h: None)
        # Partition injection (the reference's iptables isolation,
        # pkg/netutil/isolate_linux.go:23-44 / etcd-tester failure.go
        # isolate classes): host ids here are ALIVE BUT UNREACHABLE —
        # outgoing frames to them are dropped at enqueue and incoming
        # frames from them are dropped at delivery, both directions,
        # while the processes keep running. Tests/chaos flip this set.
        self.blocked: set = set()
        self.blocked_dropped = 0
        self._stop = threading.Event()
        self._qs: Dict[int, deque] = {h: deque(maxlen=_MAX_QUEUE)
                                      for h in self.peers}
        self._evs: Dict[int, threading.Event] = {h: threading.Event()
                                                 for h in self.peers}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(listen_addr)
        self._srv.listen(16)
        self.addr = self._srv.getsockname()
        self._threads = [threading.Thread(target=self._accept_loop,
                                          daemon=True, name="frames-accept")]
        for h in self.peers:
            self._threads.append(threading.Thread(
                target=self._send_loop, args=(h,), daemon=True,
                name=f"frames-send-{h}"))
        for t in self._threads:
            t.start()

    # -- send side ----------------------------------------------------------

    def send(self, to: int, header: dict, blob: bytes = b"") -> None:
        """Nonblocking: enqueue or drop-oldest (bounded queue). Loss is
        legal — PROPOSE loss surfaces as a client timeout, PAYLOAD loss is
        repaired by PULL."""
        if to in self.blocked:
            self.blocked_dropped += 1
            return
        q = self._qs.get(to)
        if q is None:
            return
        if len(q) == q.maxlen:
            self.report_unreachable(to)
        q.append((header, blob))
        self._evs[to].set()

    def broadcast(self, header: dict, blob: bytes = b"") -> None:
        for h in self.peers:
            self.send(h, header, blob)

    def _send_loop(self, h: int) -> None:
        sock = None
        addr = self.peers[h]
        while not self._stop.is_set():
            if sock is None:
                try:
                    sock = socket.create_connection(addr, timeout=2.0)
                    sock.sendall(struct.pack("<I", self.host_id))
                except OSError:
                    sock = None
                    self.report_unreachable(h)
                    # Drop what piled up while unreachable; the protocol
                    # heals via pulls/timeouts (reference drop-on-full).
                    self._qs[h].clear()
                    if self._stop.wait(0.2):
                        return
                    continue
            ev = self._evs[h]
            if not self._qs[h]:
                ev.wait(0.1)
                ev.clear()
                continue
            try:
                header, blob = self._qs[h].popleft()
            except IndexError:
                continue
            try:
                hj = json.dumps(header).encode()
                sock.sendall(_HDR.pack(len(hj), len(blob)) + hj + blob)
            except OSError:
                try:
                    sock.close()
                finally:
                    sock = None
                self.report_unreachable(h)
        if sock is not None:
            sock.close()

    # -- receive side -------------------------------------------------------

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True, name="frames-recv").start()
        self._srv.close()

    def _recv_all(self, conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _recv_loop(self, conn: socket.socket) -> None:
        conn.settimeout(None)
        hello = self._recv_all(conn, 4)
        if hello is None:
            conn.close()
            return
        (frm,) = struct.unpack("<I", hello)
        while not self._stop.is_set():
            hdr = self._recv_all(conn, _HDR.size)
            if hdr is None:
                break
            hlen, blen = _HDR.unpack(hdr)
            hj = self._recv_all(conn, hlen)
            blob = self._recv_all(conn, blen) if blen else b""
            if hj is None or (blen and blob is None):
                break
            if frm in self.blocked:
                self.blocked_dropped += 1
                continue     # partition injection: read, never deliver
            try:
                self.on_frame(frm, json.loads(hj.decode()), blob or b"")
            except Exception:  # noqa: BLE001 — a bad frame must not kill rx
                log.exception("frame handler failed (from host %d)", frm)
        conn.close()

    def stop(self) -> None:
        self._stop.set()
        for ev in self._evs.values():
            ev.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2)


def wait_peers(tr: FrameTransport, probe_interval: float = 0.1,
               timeout: float = 30.0) -> bool:
    """Best-effort wait until every peer accepts connections (boot
    barrier convenience for launchers/tests)."""
    deadline = time.time() + timeout
    missing = dict(tr.peers)
    while missing and time.time() < deadline:
        for h, addr in list(missing.items()):
            try:
                s = socket.create_connection(addr, timeout=1.0)
                s.close()
                del missing[h]
            except OSError:
                pass
        if missing:
            time.sleep(probe_interval)
    return not missing
