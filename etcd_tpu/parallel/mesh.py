"""Mesh + sharding layout for the consensus kernel.

The domain's parallelism axes (SURVEY.md §2.2) map onto a 2-D device mesh:

- "groups": the multi-tenant batch axis — independent Raft groups, the moral
  equivalent of data parallelism. Arbitrarily shardable: groups never
  communicate with each other, so XLA inserts NO collectives along it.
- "peers": the replication axis — peer slots of each group, the moral
  equivalent of model parallelism. When sharded, the per-round message
  routing (outbox[g, from, to] -> inbox[g, to, from], a transpose of the two
  peer axes) becomes an all_to_all that XLA lays onto ICI; this is the
  TPU-native replacement for the reference's rafthttp streams
  (rafthttp/stream.go, pipeline.go).

In a real multi-host deployment each host is a failure domain holding one
peer slot of every group (peers axis sharded across hosts over DCN); on a
single pod/chip both axes are just throughput axes.

The multi-host shape is executable TODAY without TPU pods:
scripts/multihost_dryrun.py boots N OS processes into one global mesh via
jax.distributed (gloo CPU collectives) with the peers axis crossing
process boundaries, and runs elections + commits through cross-process
routing collectives (tests/test_multihost.py keeps it green).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from etcd_tpu.ops.state import GroupState


def make_mesh(devices=None, peers_axis: int = 1) -> Mesh:
    """A ("groups", "peers") mesh. peers_axis devices are dedicated to the
    replication axis (1 = all devices on the groups axis)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % peers_axis != 0:
        raise ValueError(f"{n} devices not divisible by peers_axis={peers_axis}")
    arr = np.array(devices).reshape(n // peers_axis, peers_axis)
    return Mesh(arr, axis_names=("groups", "peers"))


def state_sharding(mesh: Mesh) -> GroupState:
    """NamedSharding pytree matching GroupState: every array is sharded on
    its leading group axis and (where present) the first peer axis; the
    target-peer axis and the log window stay replicated within a shard."""
    gp = NamedSharding(mesh, P("groups", "peers"))
    gpx = NamedSharding(mesh, P("groups", "peers", None))
    return GroupState(
        term=gp, vote=gp, commit=gp, lead=gp, state=gp, elapsed=gp, prng=gp,
        log_term=gpx, last_index=gp,
        match=gpx, next=gpx, pr_state=gpx, paused=gpx, ack_age=gpx,
        votes=gpx,
        peer_mask=gp, need_host=gp,
    )


def mailbox_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for inbox/outbox (G, P, P, F): shard groups + first peer
    axis. Routing (swapaxes 1<->2) then compiles to an all_to_all over the
    "peers" mesh axis."""
    return NamedSharding(mesh, P("groups", "peers", None, None))


def shard_state(st: GroupState, mesh: Mesh) -> GroupState:
    """Place a host-built GroupState onto the mesh."""
    sh = state_sharding(mesh)
    return jax.tree.map(jax.device_put, st, sh)
