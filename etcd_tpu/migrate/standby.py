"""v0.4 standby-info conversion (reference migrate/standby.go).

A v0.4 "standby" was a non-voting node that tracked the cluster through a
`standby_info` JSON file and could be promoted later; v2 dropped the
concept in favor of the stateless PROXY. The conversion therefore reads
the v0.4 file and produces what a v2 proxy needs to start in its place:
the member map for `--initial-cluster` and the `<data-dir>/proxy/cluster`
endpoint file the ProxyServer boots from (etcdmain/etcd.py ProxyServer).

File format (reference StandbyInfo4, migrate/standby.go:24-37):
    {"Running": bool, "SyncInterval": float,
     "Cluster": [{"name", "state", "clientURL", "peerURL"}, ...]}
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List

STANDBY_INFO_NAME = "standby_info"


@dataclass
class Machine:
    """One registry entry (reference MachineMessage)."""

    name: str = ""
    state: str = ""
    client_url: str = ""
    peer_url: str = ""


@dataclass
class StandbyInfo:
    running: bool = False
    sync_interval: float = 0.0
    cluster: List[Machine] = field(default_factory=list)

    def client_urls(self) -> List[str]:
        """reference StandbyInfo4.ClientURLs (standby.go:38-44)."""
        return [m.client_url for m in self.cluster]

    def peer_urls(self) -> List[str]:
        return [m.peer_url for m in self.cluster]

    def initial_cluster(self) -> str:
        """name=peerURL comma list (reference InitialCluster,
        standby.go:46-57)."""
        return ",".join(f"{m.name}={m.peer_url}" for m in self.cluster)


def decode_standby_info(path: str) -> StandbyInfo:
    """reference DecodeStandbyInfo4FromFile (standby.go:59-70)."""
    with open(path) as f:
        d = json.load(f)
    return StandbyInfo(
        running=bool(d.get("Running", False)),
        sync_interval=float(d.get("SyncInterval", 0.0)),
        cluster=[Machine(name=m.get("name", ""), state=m.get("state", ""),
                         client_url=m.get("clientURL", ""),
                         peer_url=m.get("peerURL", ""))
                 for m in d.get("Cluster") or []])


def standby_to_proxy(src_dir: str, dst_data_dir: str) -> StandbyInfo:
    """Convert a v0.4 standby data dir into a bootable v2 PROXY data dir:
    reads `<src>/standby_info` and writes `<dst>/proxy/cluster` (the
    ProxyServer's persisted endpoint view), so
    `etcd --proxy on --data-dir <dst>` resumes exactly where the standby
    stood. Returns the decoded info (initial_cluster()/client_urls() feed
    flags or tooling)."""
    from etcd_tpu.proxy import write_cluster_file
    info = decode_standby_info(os.path.join(src_dir, STANDBY_INFO_NAME))
    write_cluster_file(dst_data_dir, info.peer_urls())
    return info
