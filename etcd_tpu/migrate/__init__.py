from etcd_tpu.migrate.etcd4 import (decode_config4, decode_log4,
                                    decode_latest_snapshot4, migrate_4_to_2)

__all__ = ["decode_config4", "decode_log4", "decode_latest_snapshot4",
           "migrate_4_to_2"]
