from etcd_tpu.migrate.etcd4 import (decode_config4, decode_log4,
                                    decode_latest_snapshot4, migrate_4_to_2)
from etcd_tpu.migrate.standby import (StandbyInfo, decode_standby_info,
                                      standby_to_proxy)

__all__ = ["decode_config4", "decode_log4", "decode_latest_snapshot4",
           "migrate_4_to_2", "StandbyInfo", "decode_standby_info",
           "standby_to_proxy"]
