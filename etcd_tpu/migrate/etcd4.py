"""Offline v0.4 -> v2 data-dir converter (reference migrate/:
etcd4.go:55-145 Migrate4To2, log.go:75-129 log decode + 11 command
conversions, snapshot.go Snapshot4/Store4, config.go Config4, member.go
NewMember id hashing).

v0.4 on-disk layout (all formats reproduced here exactly):
    <dir>/log           entries framed as "%8x\n"-length + protobuf
                        LogEntry{1:index u64, 2:term u64, 3:command_name
                        string, 4:command bytes(JSON)}
    <dir>/conf          JSON {"commitIndex": N, "peers": [...]}
    <dir>/snapshot/     "<lastIndex>_<lastTerm>.ss" JSON {state(b64),
                        lastIndex, lastTerm, peers}

Output: this framework's v2 member layout — member/wal (our WAL format,
JSON metadata {"id","clusterId"}) + member/snap — ready for EtcdServer's
restart path. Terms are shifted by +1 (reference termOffset4to2,
etcd4.go:33) so post-migration terms never collide with v0.4 ones.
"""
from __future__ import annotations

import base64
import json
import logging
import os
import struct
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Dict, List, Optional, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import (ConfChange, ConfChangeType, ConfState, Entry,
                             EntryType, HardState, Snapshot,
                             SnapshotMetadata)
from etcd_tpu.server.cluster import Member, member_store_key
from etcd_tpu.server.request import Request
from etcd_tpu.snap import Snapshotter
from etcd_tpu.store import Store
from etcd_tpu.utils.fileutil import touch_dir_all
from etcd_tpu.wal import WAL, WalSnapshot

log = logging.getLogger("etcd_tpu.migrate")

TERM_OFFSET_4_TO_2 = 1          # reference etcd4.go:33
MIGRATED_CLUSTER_ID = 0x04ADD5  # reference etcd4.go:85


# ---------------------------------------------------------------------------
# v0.4 log decoding
# ---------------------------------------------------------------------------

@dataclass
class LogEntry4:
    index: int
    term: int
    command_name: str
    command: bytes


def _read_varint(b: bytes, off: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        x = b[off]
        off += 1
        out |= (x & 0x7F) << shift
        if not x & 0x80:
            return out, off
        shift += 7


def _decode_log_entry_pb(b: bytes) -> LogEntry4:
    """Minimal protobuf decode of etcd4pb.LogEntry (log_entry.proto)."""
    index = term = 0
    name = ""
    command = b""
    off = 0
    while off < len(b):
        tag, off = _read_varint(b, off)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, off = _read_varint(b, off)
            if fnum == 1:
                index = val
            elif fnum == 2:
                term = val
        elif wtype == 2:
            ln, off = _read_varint(b, off)
            data = b[off:off + ln]
            off += ln
            if fnum == 3:
                name = data.decode()
            elif fnum == 4:
                command = data
        else:
            raise ValueError(f"unsupported wire type {wtype} in v0.4 entry")
    return LogEntry4(index, term, name, command)


def encode_log_entry4(e: LogEntry4) -> bytes:
    """Inverse of the decoder — used by tests and etcd-dump-logs fixtures."""
    def varint(v):
        out = b""
        while True:
            x = v & 0x7F
            v >>= 7
            if v:
                out += bytes([x | 0x80])
            else:
                return out + bytes([x])

    body = (bytes([1 << 3]) + varint(e.index)
            + bytes([2 << 3]) + varint(e.term)
            + bytes([(3 << 3) | 2]) + varint(len(e.command_name))
            + e.command_name.encode())
    if e.command:
        body += bytes([(4 << 3) | 2]) + varint(len(e.command)) + e.command
    return f"{len(body):08x}\n".encode() + body


def decode_log4(path: str) -> List[LogEntry4]:
    """reference DecodeLog4/DecodeNextEntry4 (log.go:110-129): '%8x\\n'
    length prefix then the protobuf body, until EOF."""
    out: List[LogEntry4] = []
    with open(path, "rb") as f:
        while True:
            hdr = f.read(9)
            if not hdr:
                break
            if len(hdr) != 9 or hdr[8:9] != b"\n":
                raise ValueError(f"corrupt v0.4 log framing at entry "
                                 f"{len(out)}")
            ln = int(hdr[:8], 16)
            body = f.read(ln)
            if len(body) != ln:
                raise ValueError("truncated v0.4 log entry")
            out.append(_decode_log_entry_pb(body))
    return out


# ---------------------------------------------------------------------------
# v0.4 config + snapshot
# ---------------------------------------------------------------------------

def decode_config4(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def decode_latest_snapshot4(snapdir: str) -> Optional[dict]:
    """Newest '<index>_<term>.ss' file (reference FindLatestFile
    snapshot.go:260-287: numeric sort on the index prefix)."""
    if not os.path.isdir(snapdir):
        return None
    best = None
    for name in os.listdir(snapdir):
        if not name.endswith(".ss"):
            continue
        try:
            idx = int(name.split("_")[0])
        except ValueError:
            continue
        if best is None or idx > best[0]:
            best = (idx, name)
    if best is None:
        return None
    with open(os.path.join(snapdir, best[1])) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# command conversion (reference log.go:139-455)
# ---------------------------------------------------------------------------

def _store_path(key: str) -> str:
    return "/1/" + key.strip("/") if key.strip("/") else "/1"


_PERMANENT = ("0001-01-01T00:00:00Z", "")


def _expiration(expire_time) -> Optional[float]:
    """v0.4 JSON time.Time -> absolute unix seconds; zero time = permanent
    (reference UnixTimeOrPermanent log.go:36-42)."""
    if not expire_time or expire_time in _PERMANENT:
        return None
    ts = expire_time.replace("Z", "+00:00")
    # Go emits nanosecond fractions; Python wants <= microseconds.
    if "." in ts:
        head, frac = ts.split(".", 1)
        tz = ""
        for sep in ("+", "-"):
            if sep in frac:
                frac, tz = frac.split(sep, 1)
                tz = sep + tz
                break
        ts = f"{head}.{frac[:6]}{tz}"
    dt = datetime.fromisoformat(ts)
    if dt.timestamp() <= 0:
        return None
    return dt.timestamp()


def _member_from_join(d: dict, cluster_name: str = "etcd-cluster") -> Member:
    """reference generateNodeMember: id = sha1(sorted peer urls + cluster
    name) — reproduced via our Member.new (same scheme)."""
    return Member.new(d.get("name", ""), [d.get("raftURL", "")],
                      [d.get("etcdURL", "")] if d.get("etcdURL") else (),
                      cluster_token=cluster_name)


def convert_entry(e: LogEntry4, raft_map: Dict[str, int]) -> Entry:
    """One v0.4 command -> one v2 entry (reference toEntry2 + the Command4
    implementations, log.go:144-455)."""
    name = e.command_name
    d = json.loads(e.command.decode()) if e.command else {}
    etype = EntryType.NORMAL
    data = b""

    if name == "etcd:join":
        m = _member_from_join(d)
        raft_map[d.get("name", "")] = m.id
        cc = ConfChange(type=ConfChangeType.ADD_NODE, node_id=m.id,
                        context=json.dumps(m.to_dict()).encode())
        etype, data = EntryType.CONF_CHANGE, raftpb.encode_conf_change(cc)
    elif name == "etcd:remove":
        mid = raft_map.pop(d.get("name", ""), None)
        if mid is None:
            raise ValueError(
                f"removing node {d.get('name')!r} before it joined")
        cc = ConfChange(type=ConfChangeType.REMOVE_NODE, node_id=mid)
        etype, data = EntryType.CONF_CHANGE, raftpb.encode_conf_change(cc)
    elif name == "etcd:set":
        data = Request(method="PUT", path=_store_path(d["key"]),
                       val=d.get("value", ""), dir=d.get("dir", False),
                       expiration=_expiration(d.get("expireTime"))).encode()
    elif name == "etcd:create":
        if d.get("unique"):
            data = Request(method="POST", path=_store_path(d["key"]),
                           val=d.get("value", ""), dir=d.get("dir", False),
                           expiration=_expiration(d.get("expireTime"))
                           ).encode()
        else:
            data = Request(method="PUT", path=_store_path(d["key"]),
                           val=d.get("value", ""), dir=d.get("dir", False),
                           prev_exist=True,
                           expiration=_expiration(d.get("expireTime"))
                           ).encode()
    elif name == "etcd:update":
        data = Request(method="PUT", path=_store_path(d["key"]),
                       val=d.get("value", ""), prev_exist=True,
                       expiration=_expiration(d.get("expireTime"))).encode()
    elif name == "etcd:compareAndSwap":
        data = Request(method="PUT", path=_store_path(d["key"]),
                       val=d.get("value", ""),
                       prev_value=d.get("prevValue", ""),
                       prev_index=d.get("prevIndex", 0),
                       expiration=_expiration(d.get("expireTime"))).encode()
    elif name == "etcd:delete":
        data = Request(method="DELETE", path=_store_path(d["key"]),
                       dir=d.get("dir", False),
                       recursive=d.get("recursive", False)).encode()
    elif name == "etcd:compareAndDelete":
        data = Request(method="DELETE", path=_store_path(d["key"]),
                       prev_value=d.get("prevValue", ""),
                       prev_index=d.get("prevIndex", 0)).encode()
    elif name == "etcd:sync":
        t = _expiration(d.get("time")) or 0.0
        data = Request(method="SYNC", time=t).encode()
    elif name == "etcd:setClusterConfig":
        data = Request(method="PUT", path="/v2/admin/config",
                       val=json.dumps(d.get("config") or {})).encode()
    elif name == "raft:nop":
        data = b""
    elif name in ("raft:join", "raft:leave"):
        raise ValueError(
            "found a raft join/leave command; these shouldn't be in an "
            "etcd log")
    else:
        raise ValueError(f"unregistered command type {name}")

    return Entry(type=etype, term=e.term + TERM_OFFSET_4_TO_2,
                 index=e.index, data=data)


# ---------------------------------------------------------------------------
# snapshot conversion (reference snapshot.go Snapshot2)
# ---------------------------------------------------------------------------

def _walk_node4(store: Store, n: dict) -> None:
    """Replay a v0.4 store node tree into our Store under /1 (keyspace
    only; the _etcd machine registry becomes ConfState/membership).
    A v0.4 node is a directory iff Children is non-null (Go map != nil)."""
    path = n.get("Path", "/")
    if path.lstrip("/").startswith("_etcd"):
        return
    children = n.get("Children")
    if path not in ("/", ""):
        target = _store_path(path)
        if children is not None:
            if not children:
                store.set(target, is_dir=True)   # empty dir needs a node
        else:
            store.set(target, value=n.get("Value", ""),
                      expire_time=_expiration(n.get("ExpireTime")))
    for c in (children or {}).values():
        _walk_node4(store, c)


def machines_from_snapshot4(snap4: dict) -> Dict[str, Member]:
    """Membership from /_etcd/machines (reference pullNodesFromEtcd):
    each machine's value is a query string "raft=...&etcd=..."."""
    from urllib.parse import parse_qs
    state = json.loads(base64.b64decode(snap4["state"]))
    root = state.get("Root") or {}
    machines = (root.get("Children") or {}).get("_etcd", {})
    machines = (machines.get("Children") or {}).get("machines", {})
    out: Dict[str, Member] = {}
    for name, c in (machines.get("Children") or {}).items():
        q = parse_qs(c.get("Value", ""))
        short = name.rsplit("/", 1)[-1]
        out[short] = _member_from_join({
            "name": short,
            "raftURL": (q.get("raft") or [""])[0],
            "etcdURL": (q.get("etcd") or [""])[0]})
    return out


def snapshot4_to_2(snap4: dict) -> Snapshot:
    state = json.loads(base64.b64decode(snap4["state"]))
    root = state.get("Root") or {}
    store = Store()
    _walk_node4(store, root)

    members = machines_from_snapshot4(snap4)
    for m in members.values():
        store.set(member_store_key(m.id) + "/raftAttributes",
                  value=m.raft_attributes_json())

    return Snapshot(
        data=store.save(),
        metadata=SnapshotMetadata(
            index=snap4["lastIndex"],
            term=snap4["lastTerm"] + TERM_OFFSET_4_TO_2,
            conf_state=ConfState(
                nodes=tuple(sorted(m.id for m in members.values())))))


# ---------------------------------------------------------------------------
# the driver (reference Migrate4To2 etcd4.go:55-145)
# ---------------------------------------------------------------------------

def is_v04_data_dir(data_dir: str) -> bool:
    """v0.4 layout detection (reference version.DetectDataDir sniffing,
    version/version.go:35-88): top-level `log` + `conf`."""
    return (os.path.isfile(os.path.join(data_dir, "log"))
            and os.path.isfile(os.path.join(data_dir, "conf")))


def migrate_4_to_2(data_dir: str, name: str) -> None:
    snap4 = decode_latest_snapshot4(os.path.join(data_dir, "snapshot"))
    cfg4 = decode_config4(os.path.join(data_dir, "conf"))
    ents4 = decode_log4(os.path.join(data_dir, "log"))

    # Monotonic index check (reference Entries4To2:465-473).
    for i, e in enumerate(ents4[1:]):
        if e.index != ents4[0].index + i + 1:
            raise ValueError(f"skipped log index {ents4[0].index + i + 1}")

    # The node's id can come from its join entry in the live log OR from
    # the snapshot's machine registry — a log compacted past cluster
    # formation only has the latter (reference GuessNodeID etcd4.go:77-83
    # consults snapshot, log and config in turn).
    raft_map: Dict[str, int] = {}
    if snap4 is not None:
        raft_map.update({nm: m.id
                         for nm, m in machines_from_snapshot4(snap4).items()})
    ents2 = [convert_entry(e, raft_map) for e in ents4]
    if not ents2 and snap4 is None:
        raise ValueError("nothing to migrate: empty v0.4 log, no snapshot")

    snap2 = snapshot4_to_2(snap4) if snap4 is not None else None
    node_id = raft_map.get(name, 0)
    if node_id == 0:
        raise ValueError(
            f"couldn't find node {name!r} in the v0.4 log or snapshot, "
            f"cannot convert")

    commit = cfg4.get("commitIndex", 0)
    term = (ents2[-1].term if ents2 else snap2.metadata.term)
    if snap2 is not None:
        commit = max(commit, snap2.metadata.index)
    hs = HardState(term=term, vote=0, commit=commit)

    member_dir = os.path.join(data_dir, "member")
    touch_dir_all(os.path.join(member_dir, "snap"))
    metadata = json.dumps({"id": f"{node_id:x}",
                           "clusterId": f"{MIGRATED_CLUSTER_ID:x}"}).encode()
    w = WAL.create(os.path.join(member_dir, "wal"), metadata)
    try:
        walsnap = WalSnapshot()
        if snap2 is not None:
            walsnap = WalSnapshot(index=snap2.metadata.index,
                                  term=snap2.metadata.term)
            Snapshotter(os.path.join(member_dir, "snap")).save_snap(snap2)
            w.save_snapshot(walsnap)
            ents2 = [e for e in ents2 if e.index > walsnap.index]
        w.save(hs, ents2)
    finally:
        w.close()
    log.info("migrated v0.4 dir %s: %d entries, snapshot=%s, node=%x",
             data_dir, len(ents2), snap4 is not None, node_id)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="Migrate an etcd v0.4 data directory to the v2 layout")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--name", required=True,
                    help="this member's v0.4 node name")
    args = ap.parse_args(argv)
    migrate_4_to_2(args.data_dir, args.name)
    print(f"migration of {args.data_dir} successful")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
