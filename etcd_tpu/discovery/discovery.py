"""Cluster bootstrap via an etcd-compatible discovery service.

Behavioral equivalent of reference discovery/discovery.go: the discovery
URL is ``http://host[:port]/<token>``; the service exposes a v2 keyspace at
its root where ``/<token>/_config/size`` holds the intended cluster size
(checkCluster discovery.go:184-230), each member self-registers by creating
``/<token>/<member-id-hex>`` = "name=peerURL[,name=peerURL]"
(createSelf discovery.go:165-181), members beyond the size slots get
FullClusterError (discovery.go:219-224), and everyone watches the token dir
until ``size`` registrations exist (waitNodes discovery.go:277-308), then
joins them into an initial-cluster string (nodesToCluster discovery.go:314).
Connection timeouts retry with exponential backoff (discovery.go:232-239).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from etcd_tpu.client import Client, ClientError, KeysAPI, KeysError
from etcd_tpu.errors import ECODE_KEY_NOT_FOUND, ECODE_NODE_EXIST
from etcd_tpu.server.cluster import compute_member_id

log = logging.getLogger("discovery")


class DiscoveryError(Exception):
    pass


class InvalidURLError(DiscoveryError):
    pass


class SizeNotFoundError(DiscoveryError):
    pass


class BadSizeKeyError(DiscoveryError):
    pass


class DuplicateIDError(DiscoveryError):
    pass


class FullClusterError(DiscoveryError):
    pass


class TooManyRetriesError(DiscoveryError):
    pass


class _Discovery:
    def __init__(self, durl: str, self_id: int, proxy_url: str = "",
                 max_retries: int = 16,
                 backoff_base: float = 1.0) -> None:
        u = urlsplit(durl)
        if not u.scheme or not u.path.strip("/"):
            raise InvalidURLError(f"invalid discovery URL {durl!r}")
        self.token = u.path.strip("/")
        self.id = self_id
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.retries = 0
        endpoint = f"{u.scheme}://{u.netloc}"
        # proxy_url routes traffic through an HTTP proxy (reference
        # newProxyFunc discovery.go:75-93 → http.Transport.Proxy).
        self.kapi = KeysAPI(Client([endpoint], timeout=5.0, proxy=proxy_url),
                            prefix="")

    # -- retry/backoff (discovery.go:232-239) ------------------------------

    def _backoff(self, step: str) -> None:
        self.retries += 1
        if self.retries > self.max_retries:
            raise TooManyRetriesError(f"discovery: too many retries ({step})")
        # Exponential, capped at 32x base so a long outage fails in minutes
        # rather than sleeping for hours on the last doublings.
        wait = self.backoff_base * min(2 ** self.retries, 32)
        log.info("discovery: during %s connection timed out, retrying in %.0fs",
                 step, wait)
        time.sleep(wait)

    def _self_key(self) -> str:
        return f"{self.token}/{self.id:x}"

    # -- protocol ----------------------------------------------------------

    def check_cluster(self) -> Tuple[List, int, int]:
        """Returns (sorted registration nodes, size, current etcd index)."""
        while True:
            try:
                resp = self.kapi.get(f"{self.token}/_config/size")
            except KeysError as e:
                if e.code == ECODE_KEY_NOT_FOUND:
                    raise SizeNotFoundError("discovery: size key not found")
                raise
            except ClientError:
                self._backoff("cluster status check")
                continue
            try:
                size = int(resp.node.value)
            except (TypeError, ValueError):
                raise BadSizeKeyError("discovery: size key is bad")

            try:
                resp = self.kapi.get(self.token)
            except ClientError:
                self._backoff("cluster status check")
                continue
            nodes = [n for n in (resp.node.nodes if resp.node else [])
                     if n.key.rsplit("/", 1)[-1] != "_config"]
            nodes.sort(key=lambda n: n.created_index)

            # A member is admitted iff its slot is within the first `size`
            # registrations (discovery.go:213-224).
            self_base = self._self_key().rsplit("/", 1)[-1]
            for i, n in enumerate(nodes):
                if n.key.rsplit("/", 1)[-1] == self_base:
                    break
                if i >= size - 1:
                    raise FullClusterError("discovery: cluster is full")
            return nodes, size, resp.index

    def create_self(self, contents: str) -> None:
        try:
            resp = self.kapi.create(self._self_key(), contents)
        except KeysError as e:
            if e.code == ECODE_NODE_EXIST:
                raise DuplicateIDError("discovery: found duplicate id")
            raise
        # Observe our own registration before proceeding
        # (discovery.go:176-180).
        w = self.kapi.watcher(self._self_key(),
                              after_index=resp.node.created_index - 1)
        w.next(timeout=30.0)

    def wait_nodes(self, nodes: List, size: int, index: int) -> List:
        nodes = nodes[:size]
        w = self.kapi.watcher(self.token, after_index=index, recursive=True)
        all_nodes = list(nodes)
        seen = {n.key for n in all_nodes}
        while len(all_nodes) < size:
            log.info("discovery: found %d peer(s), waiting for %d more",
                     len(all_nodes), size - len(all_nodes))
            try:
                resp = w.next()
            except ClientError:
                self._backoff("waiting for other nodes")
                nodes, size, index = self.check_cluster()
                return self.wait_nodes(nodes, size, index)
            n = resp.node
            if n and n.key not in seen and n.key.rsplit("/", 1)[-1] != "_config":
                seen.add(n.key)
                all_nodes.append(n)
        all_nodes.sort(key=lambda n: n.created_index)
        return all_nodes[:size]

    def join(self, contents: str) -> str:
        self.check_cluster()
        self.create_self(contents)
        nodes, size, index = self.check_cluster()
        return nodes_to_cluster(self.wait_nodes(nodes, size, index))

    def get(self) -> str:
        try:
            nodes, size, index = self.check_cluster()
        except FullClusterError:
            # A proxy/latecomer just takes the full member set
            # (discovery.go:167-170).
            nodes, size, index = self._nodes_even_if_full()
            return nodes_to_cluster(nodes[:size])
        return nodes_to_cluster(self.wait_nodes(nodes, size, index))

    def _nodes_even_if_full(self) -> Tuple[List, int, int]:
        resp = self.kapi.get(f"{self.token}/_config/size")
        size = int(resp.node.value)
        resp = self.kapi.get(self.token)
        nodes = [n for n in (resp.node.nodes if resp.node else [])
                 if n.key.rsplit("/", 1)[-1] != "_config"]
        nodes.sort(key=lambda n: n.created_index)
        return nodes, size, resp.index


def nodes_to_cluster(nodes: Sequence) -> str:
    return ",".join(n.value for n in nodes if n.value)


def join_cluster(durl: str, name: str, peer_urls: Sequence[str],
                 proxy_url: str = "", self_id: Optional[int] = None,
                 max_retries: int = 16) -> str:
    """Register with the discovery service and wait for the full cluster;
    returns an initial-cluster string (reference JoinCluster
    discovery.go:53-59, called from etcdserver/server.go:224-238)."""
    if self_id is None:
        self_id = compute_member_id(peer_urls, durl)
    contents = ",".join(f"{name}={u}" for u in peer_urls)
    d = _Discovery(durl, self_id, proxy_url, max_retries=max_retries)
    return d.join(contents)


def get_cluster(durl: str, proxy_url: str = "",
                max_retries: int = 16) -> str:
    """Fetch the bootstrapped cluster without registering — proxy bootstrap
    (reference GetCluster discovery.go:63-69)."""
    d = _Discovery(durl, 0, proxy_url, max_retries=max_retries)
    return d.get()
