from etcd_tpu.discovery.discovery import (BadSizeKeyError, DiscoveryError,
                                          DuplicateIDError, FullClusterError,
                                          SizeNotFoundError, get_cluster,
                                          join_cluster)
from etcd_tpu.discovery.srv import srv_cluster

__all__ = ["DiscoveryError", "DuplicateIDError", "FullClusterError",
           "SizeNotFoundError", "BadSizeKeyError", "join_cluster",
           "get_cluster", "srv_cluster"]
