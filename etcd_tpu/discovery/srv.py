"""DNS SRV bootstrap (reference discovery/srv.go SRVGetCluster).

Looks up ``_etcd-server-ssl._tcp.<domain>`` (https peers) and
``_etcd-server._tcp.<domain>`` (http peers); each SRV target becomes one
initial-cluster entry, named ``name`` when the target matches one of our
advertised peer URLs and a running ordinal otherwise (srv.go:55-77).

The standard library has no SRV resolver, so the lookup function is
pluggable: pass ``lookup`` (service, proto, domain) -> [(target, port)],
or install dnspython. Zero-egress test environments inject a fake.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

log = logging.getLogger("discovery")

LookupSRV = Callable[[str, str, str], List[Tuple[str, int]]]


def _default_lookup(service: str, proto: str, domain: str
                    ) -> List[Tuple[str, int]]:
    try:
        import dns.resolver  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "SRV discovery needs a DNS resolver; install dnspython or pass "
            "an explicit lookup function") from e
    answers = dns.resolver.resolve(f"_{service}._{proto}.{domain}", "SRV")
    return [(str(r.target).rstrip("."), r.port) for r in answers]


def srv_cluster(domain: str, name: str, apurls: Sequence[str],
                lookup: Optional[LookupSRV] = None) -> str:
    """Return an initial-cluster string discovered from DNS SRV records."""
    lookup = lookup or _default_lookup
    self_hostports = set()
    for u in apurls:
        parts = urlsplit(u)
        self_hostports.add((parts.hostname, parts.port))

    entries: List[str] = []
    temp_name = 0

    def collect(service: str, scheme: str) -> bool:
        nonlocal temp_name
        try:
            addrs = lookup(service, "tcp", domain)
        except Exception as e:
            log.info("discovery: SRV lookup %s failed: %s", service, e)
            return False
        for target, port in addrs:
            n = name if (target, port) in self_hostports else str(temp_name)
            if n != name:
                temp_name += 1
            entries.append(f"{n}={scheme}://{target}:{port}")
            log.info("discovery: got bootstrap from DNS for %s at "
                     "%s://%s:%d", service, scheme, target, port)
        return True

    ok_ssl = collect("etcd-server-ssl", "https")
    ok = collect("etcd-server", "http")
    if not (ok_ssl or ok) or not entries:
        raise RuntimeError(
            f"discovery: no SRV records for cluster bootstrap under "
            f"{domain!r}")
    return ",".join(entries)
