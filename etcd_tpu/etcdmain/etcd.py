"""Process entry for `etcd-tpu` (python -m etcd_tpu).

Behavioral equivalent of reference etcdmain/etcd.go Main(): parse
flags/env, default the data dir from the member name (etcd.go:96-99),
identify whether the data dir was previously a member or a proxy
(identifyDataDirOrDie etcd.go:376-404) and start the matching mode;
discovery full-cluster errors fall back to proxy mode when configured
(etcd.go:99-107).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
from typing import List, Optional, Sequence

from etcd_tpu.embed import Etcd, EtcdConfig
from etcd_tpu.etcdhttp.web import HttpServer, Router
from etcd_tpu.utils.tlsutil import TLSInfo
from etcd_tpu.etcdmain.config import (ConfigError, MainConfig,
                                      PROXY_READONLY, parse_args)
from etcd_tpu.proxy import (Director, ReverseProxy, fetch_cluster_urls,
                            readonly, write_cluster_file)
from etcd_tpu.proxy.director import PROXY_DIR_NAME

log = logging.getLogger("etcdmain")

DIR_MEMBER, DIR_PROXY, DIR_ENGINE, DIR_EMPTY = ("member", PROXY_DIR_NAME,
                                                "engine", "empty")


def identify_data_dir(dir_: str) -> str:
    """Which mode this data dir was used for (reference etcd.go:376-404;
    engine/ is this framework's multi-tenant mode)."""
    try:
        names = os.listdir(dir_)
    except FileNotFoundError:
        return DIR_EMPTY
    present = [d for d in (DIR_MEMBER, DIR_PROXY, DIR_ENGINE)
               if d in names]
    if len(present) > 1:
        raise ConfigError(
            f"invalid datadir: {' and '.join(present)} directories both "
            "exist")
    return present[0] if present else DIR_EMPTY


def start_etcd(cfg: MainConfig) -> Etcd:
    """Launch a consensus member (reference startEtcd etcd.go:127-231)."""
    initial_cluster = dict(cfg.initial_cluster)
    token = cfg.initial_cluster_token
    if cfg.discovery or cfg.discovery_srv:
        from etcd_tpu.discovery import (join_cluster, srv_cluster)
        if not os.path.isdir(os.path.join(cfg.data_dir, "member")):
            if cfg.discovery:
                s = join_cluster(cfg.discovery, cfg.name,
                                 cfg.initial_advertise_peer_urls,
                                 proxy_url=cfg.discovery_proxy)
            else:
                s = srv_cluster(cfg.discovery_srv, cfg.name,
                                cfg.initial_advertise_peer_urls)
            from etcd_tpu.etcdmain.config import parse_initial_cluster
            initial_cluster = parse_initial_cluster(s)
            token = cfg.discovery or cfg.discovery_srv

    ecfg = EtcdConfig(
        name=cfg.name,
        data_dir=cfg.data_dir,
        initial_cluster=initial_cluster,
        listen_peer_urls=cfg.listen_peer_urls,
        listen_client_urls=cfg.listen_client_urls,
        advertise_client_urls=cfg.advertise_client_urls,
        cluster_token=token,
        snap_count=cfg.snapshot_count,
        tick_ms=cfg.heartbeat_interval,
        election_ticks=cfg.election_ticks,
        initial_cluster_state=cfg.initial_cluster_state,
        force_new_cluster=cfg.force_new_cluster,
        cors=cfg.cors,
        client_tls=TLSInfo(cert_file=cfg.cert_file, key_file=cfg.key_file,
                           ca_file=cfg.ca_file,
                           client_cert_auth=cfg.client_cert_auth),
        peer_tls=TLSInfo(cert_file=cfg.peer_cert_file,
                         key_file=cfg.peer_key_file,
                         ca_file=cfg.peer_ca_file,
                         client_cert_auth=cfg.peer_client_cert_auth),
    )
    e = Etcd(ecfg)
    e.start()
    log.info("etcd-tpu member %s listening: client=%s peer=%s",
             cfg.name, e.client_urls, e.peer_urls)
    return e


class EngineServer:
    """Multi-tenant engine mode: G consensus groups served from one
    batched kernel at /tenants/{g}/v2/keys (docs/deployment.md §2)."""

    def __init__(self, cfg: MainConfig) -> None:
        from etcd_tpu.etcdhttp.tenants import EngineHttp
        from etcd_tpu.server.engine import EngineConfig, MultiEngine

        mesh = None
        if cfg.engine_mesh_peers_axis > 0:
            import jax
            from etcd_tpu.parallel.mesh import make_mesh
            n = len(jax.devices())
            pa = cfg.engine_mesh_peers_axis
            # Fail with a flag-level message, not an opaque sharding error
            # from deep inside device placement.
            if n % pa != 0:
                raise ConfigError(
                    f"-engine-mesh-peers-axis {pa} does not divide the "
                    f"{n} visible devices")
            if cfg.engine_peers % pa != 0:
                raise ConfigError(
                    f"-engine-peers {cfg.engine_peers} must be divisible "
                    f"by -engine-mesh-peers-axis {pa}")
            if cfg.engine_groups % (n // pa) != 0:
                raise ConfigError(
                    f"-engine-groups {cfg.engine_groups} must be "
                    f"divisible by the groups mesh axis ({n // pa} = "
                    f"{n} devices / peers-axis {pa})")
            mesh = make_mesh(jax.devices(), peers_axis=pa)
            log.info("engine: sharding over mesh %s",
                     dict(zip(mesh.axis_names, mesh.devices.shape)))
        self.engine = MultiEngine(EngineConfig(
            groups=cfg.engine_groups, peers=cfg.engine_peers,
            window=cfg.engine_window,
            data_dir=os.path.join(cfg.data_dir, DIR_ENGINE),
            round_interval=cfg.engine_interval_ms / 1000.0,
            applier_shards=cfg.engine_applier_shards,
            wal_shards=cfg.engine_wal_shards,
            mesh=mesh))
        client_tls = TLSInfo(cert_file=cfg.cert_file, key_file=cfg.key_file,
                             ca_file=cfg.ca_file,
                             client_cert_auth=cfg.client_cert_auth)
        self.http = []
        from etcd_tpu.embed import _listen_addr
        for url in cfg.listen_client_urls:
            host, port = _listen_addr(url)
            self.http.append(EngineHttp(
                self.engine, host, port,
                cors=set(cfg.cors) if cfg.cors else None,
                tls_context=(client_tls.server_context()
                             if not client_tls.empty() else None)))

    @property
    def client_urls(self):
        return [h.url for h in self.http]

    def start(self) -> None:
        for h in self.http:
            h.start()
        self.engine.start()
        log.info("engine: %d tenant groups x %d peers listening on %s",
                 self.engine.cfg.groups, self.engine.cfg.peers,
                 self.client_urls)

    def stop(self) -> None:
        self.engine.stop()
        for h in self.http:
            h.stop()


class ProxyServer:
    """Proxy mode: stateless fan-out to cluster members, endpoint view
    persisted in <data-dir>/proxy/cluster (reference startProxy
    etcdmain/etcd.go:234-335)."""

    def __init__(self, cfg: MainConfig) -> None:
        self.cfg = cfg
        proxy_dir = os.path.join(cfg.data_dir, DIR_PROXY)
        os.makedirs(proxy_dir, exist_ok=True)
        self._clusterfile = os.path.join(proxy_dir, "cluster")

        if os.path.exists(self._clusterfile):
            with open(self._clusterfile) as f:
                self._peer_urls = json.load(f)["PeerURLs"]
            log.info("proxy: using peer urls %s from cluster file",
                     self._peer_urls)
        else:
            self._peer_urls = [u for urls in cfg.initial_cluster.values()
                               for u in urls]
            if cfg.discovery:
                from etcd_tpu.discovery import get_cluster
                from etcd_tpu.etcdmain.config import parse_initial_cluster
                s = get_cluster(cfg.discovery, proxy_url=cfg.discovery_proxy)
                self._peer_urls = [u for urls in
                                   parse_initial_cluster(s).values()
                                   for u in urls]

        # The proxy honors the same TLS + CORS flags as a member: the
        # client TLSInfo secures its listener AND its outbound transport to
        # the cluster (reference startProxy, etcdmain/etcd.go:234-335);
        # the peer TLSInfo authenticates the /members refresh against
        # mutual-TLS peer listeners.
        client_tls = TLSInfo(cert_file=cfg.cert_file, key_file=cfg.key_file,
                             ca_file=cfg.ca_file,
                             client_cert_auth=cfg.client_cert_auth)
        peer_tls = TLSInfo(cert_file=cfg.peer_cert_file,
                           key_file=cfg.peer_key_file,
                           ca_file=cfg.peer_ca_file,
                           client_cert_auth=cfg.peer_client_cert_auth)
        self._out_ctx = (client_tls.client_context()
                         if not client_tls.empty() else None)
        self._peer_ctx = (peer_tls.client_context()
                          if not peer_tls.empty() else None)
        self.director = Director(self._refresh_urls)
        rp = ReverseProxy(self.director, tls_context=self._out_ctx)
        handler = readonly(rp.handle) if cfg.is_readonly_proxy else rp.handle
        self.http: List[HttpServer] = []
        for url in cfg.listen_client_urls:
            from etcd_tpu.embed import _listen_addr
            host, port = _listen_addr(url)
            router = Router()
            router.add("/", handler)
            self.http.append(HttpServer(
                host, port, router,
                cors=set(cfg.cors) if cfg.cors else None,
                tls_context=(client_tls.server_context()
                             if not client_tls.empty() else None)))

    def _refresh_urls(self) -> List[str]:
        client_urls, peer_urls = fetch_cluster_urls(
            self._peer_urls, tls_context=self._peer_ctx)
        if peer_urls:
            self._peer_urls = peer_urls
            write_cluster_file(self.cfg.data_dir, peer_urls)
        return client_urls

    @property
    def client_urls(self) -> List[str]:
        return [h.url for h in self.http]

    def start(self) -> None:
        for h in self.http:
            h.start()
        log.info("proxy: listening on %s", self.client_urls)

    def stop(self) -> None:
        self.director.stop()
        for h in self.http:
            h.stop()


def main(argv: Optional[Sequence[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s: %(message)s")
    try:
        cfg = parse_args(sys.argv[1:] if argv is None else argv)
    except ConfigError as e:
        print(f"error verifying flags, {e}. See 'etcd-tpu --help'.",
              file=sys.stderr)
        return 1
    if cfg.debug:
        logging.getLogger().setLevel(logging.DEBUG)

    if not cfg.data_dir:
        cfg.data_dir = f"{cfg.name}.etcd"
        log.info("no data-dir provided, using default data-dir ./%s",
                 cfg.data_dir)

    try:
        which = identify_data_dir(cfg.data_dir)
    except ConfigError as e:
        print(str(e), file=sys.stderr)
        return 1
    if which != DIR_EMPTY:
        log.info("already initialized as %s before, starting as etcd %s...",
                 which, which)

    stop_ev = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop_ev.set())
        except ValueError:
            pass  # not the main thread (tests)

    if cfg.is_proxy and which == DIR_MEMBER:
        # Refuse rather than plant a proxy/ dir beside member/ — that would
        # make the data dir permanently unidentifiable.
        print(f"cannot start as proxy: data dir {cfg.data_dir} was "
              f"previously initialized as a member", file=sys.stderr)
        return 1
    if cfg.is_engine != (which == DIR_ENGINE) and which != DIR_EMPTY:
        requested = ("engine" if cfg.is_engine
                     else "proxy" if cfg.is_proxy else "member")
        print(f"cannot start as {requested}: data dir {cfg.data_dir} was "
              f"previously initialized as {which}", file=sys.stderr)
        return 1

    if cfg.is_engine:
        try:
            runner = EngineServer(cfg)
        except (ConfigError, ValueError) as e:
            # Flag/geometry-level refusals answer like other config
            # errors, not with a traceback.
            print(str(e), file=sys.stderr)
            return 1
        runner.start()
        try:
            stop_ev.wait()
        finally:
            runner.stop()
        return 0

    runner = None
    should_proxy = cfg.is_proxy or which == DIR_PROXY
    if not should_proxy:
        try:
            runner = start_etcd(cfg)
        except Exception as e:
            from etcd_tpu.discovery import FullClusterError
            if (isinstance(e, FullClusterError) and
                    cfg.should_fallback_to_proxy):
                log.info("discovery cluster full, falling back to proxy")
                should_proxy = True
            else:
                print(str(e), file=sys.stderr)
                return 1
    if should_proxy:
        runner = ProxyServer(cfg)
        runner.start()

    try:
        stop_ev.wait()
    finally:
        runner.stop()
    return 0
