from etcd_tpu.etcdmain.config import MainConfig, ConfigError, parse_args
from etcd_tpu.etcdmain.etcd import main

__all__ = ["MainConfig", "ConfigError", "parse_args", "main"]
