"""Flag/env configuration for the `etcd-tpu` process.

Behavioral equivalent of reference etcdmain/config.go + pkg/flags: the same
flag names, `ETCD_<UPPER_SNAKE>` environment fallback for any flag not given
on the command line (pkg/flags/flag.go:63-96), `name=url[,name=url]`
initial-cluster parsing (pkg/types/urlsmap.go), and the Parse-time
validations — mutually exclusive bootstrap flags (config.go:244-250),
advertise-client-urls required when listen-client-urls is set
(config.go:270-272), and election-timeout >= 5x heartbeat-interval
(config.go:275-277).
"""
from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from etcd_tpu import version as ver

DEFAULT_NAME = "default"
CLUSTER_STATE_NEW = "new"
CLUSTER_STATE_EXISTING = "existing"
PROXY_OFF, PROXY_READONLY, PROXY_ON = "off", "readonly", "on"
FALLBACK_EXIT, FALLBACK_PROXY = "exit", "proxy"

DEFAULT_LISTEN_PEER = "http://localhost:2380"
DEFAULT_LISTEN_CLIENT = "http://localhost:2379"


class ConfigError(Exception):
    pass


def parse_urls(s: str) -> Tuple[str, ...]:
    return tuple(u.strip().rstrip("/") for u in s.split(",") if u.strip())


def parse_initial_cluster(s: str) -> Dict[str, List[str]]:
    """``name=url,name=url2,other=url`` → {name: [urls]} (types/urlsmap.go)."""
    out: Dict[str, List[str]] = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ConfigError(
                f"invalid -initial-cluster entry {part!r}: expected name=url")
        name, url = part.split("=", 1)
        out.setdefault(name, []).append(url.rstrip("/"))
    return out


def initial_cluster_from_name(name: str) -> str:
    return f"{name or DEFAULT_NAME}={DEFAULT_LISTEN_PEER}"


@dataclass
class MainConfig:
    name: str = DEFAULT_NAME
    data_dir: str = ""
    listen_peer_urls: Tuple[str, ...] = (DEFAULT_LISTEN_PEER,)
    listen_client_urls: Tuple[str, ...] = (DEFAULT_LISTEN_CLIENT,)
    initial_advertise_peer_urls: Tuple[str, ...] = (DEFAULT_LISTEN_PEER,)
    advertise_client_urls: Tuple[str, ...] = (DEFAULT_LISTEN_CLIENT,)
    initial_cluster: Dict[str, List[str]] = field(default_factory=dict)
    initial_cluster_token: str = "etcd-cluster"
    initial_cluster_state: str = CLUSTER_STATE_NEW
    discovery: str = ""
    discovery_fallback: str = FALLBACK_PROXY
    discovery_proxy: str = ""
    discovery_srv: str = ""
    proxy: str = PROXY_OFF
    snapshot_count: int = 10000
    heartbeat_interval: int = 100          # ms
    election_timeout: int = 1000           # ms
    max_snapshots: int = 5
    max_wals: int = 5
    cors: Tuple[str, ...] = ()
    force_new_cluster: bool = False
    debug: bool = False
    # TLS (reference config.go:166-180).
    cert_file: str = ""
    key_file: str = ""
    ca_file: str = ""
    client_cert_auth: bool = False
    peer_cert_file: str = ""
    peer_key_file: str = ""
    peer_ca_file: str = ""
    peer_client_cert_auth: bool = False
    # Multi-tenant engine mode (the batched-kernel serving path).
    engine_groups: int = 0
    engine_peers: int = 5
    engine_window: int = 32
    engine_interval_ms: int = 1
    # 0 = single-device arrays; >0 = shard the kernel over a
    # ("groups", "peers") mesh of all visible devices, with this many on
    # the peers axis (1 = all devices on the groups axis).
    engine_mesh_peers_axis: int = 0
    # Compartment widths inside the engine process (engine.EngineConfig
    # applier_shards / wal_shards). Declared here — not just in _FLAGS —
    # so a MainConfig built directly (embed, tests) boots the engine.
    engine_applier_shards: int = 1
    engine_wal_shards: int = 1

    @property
    def is_proxy(self) -> bool:
        return self.proxy != PROXY_OFF

    @property
    def is_readonly_proxy(self) -> bool:
        return self.proxy == PROXY_READONLY

    @property
    def should_fallback_to_proxy(self) -> bool:
        return self.discovery_fallback == FALLBACK_PROXY

    @property
    def is_engine(self) -> bool:
        return self.engine_groups > 0

    @property
    def election_ticks(self) -> int:
        return self.election_timeout // self.heartbeat_interval


_FLAGS = [
    # (flag, kind, default, help)
    ("name", str, DEFAULT_NAME, "Unique human-readable name for this node"),
    ("data-dir", str, "", "Path to the data directory"),
    ("listen-peer-urls", "urls", DEFAULT_LISTEN_PEER,
     "List of URLs to listen on for peer traffic"),
    ("listen-client-urls", "urls", DEFAULT_LISTEN_CLIENT,
     "List of URLs to listen on for client traffic"),
    ("initial-advertise-peer-urls", "urls", DEFAULT_LISTEN_PEER,
     "List of this member's peer URLs to advertise to the cluster"),
    ("advertise-client-urls", "urls", DEFAULT_LISTEN_CLIENT,
     "List of this member's client URLs to advertise to the cluster"),
    ("initial-cluster", str, "",
     "Initial cluster configuration for bootstrapping"),
    ("initial-cluster-token", str, "etcd-cluster",
     "Initial cluster token for the etcd cluster during bootstrap"),
    ("initial-cluster-state", ("new", "existing"), CLUSTER_STATE_NEW,
     "Initial cluster state (new or existing)"),
    ("discovery", str, "",
     "Discovery service used to bootstrap the initial cluster"),
    ("discovery-fallback", (FALLBACK_EXIT, FALLBACK_PROXY), FALLBACK_PROXY,
     "Behavior when discovery fails (exit or proxy)"),
    ("discovery-proxy", str, "",
     "HTTP proxy to use for traffic to discovery service"),
    ("discovery-srv", str, "",
     "DNS domain used to bootstrap initial cluster"),
    ("proxy", (PROXY_OFF, PROXY_READONLY, PROXY_ON), PROXY_OFF,
     "Proxy mode (off, readonly, on)"),
    ("snapshot-count", int, 10000,
     "Number of committed transactions to trigger a snapshot"),
    ("heartbeat-interval", int, 100,
     "Time (in milliseconds) of a heartbeat interval"),
    ("election-timeout", int, 1000,
     "Time (in milliseconds) for an election to timeout"),
    ("max-snapshots", int, 5,
     "Maximum number of snapshot files to retain"),
    ("max-wals", int, 5, "Maximum number of wal files to retain"),
    ("cors", "urls", "",
     "Comma-separated whitelist of origins for CORS"),
    ("force-new-cluster", bool, False,
     "Force to create a new one-member cluster"),
    ("debug", bool, False, "Enable debug output to the logs"),
    # Client TLS (reference etcdmain/config.go:166-173 security flags).
    ("cert-file", str, "", "Path to the client server TLS cert file"),
    ("key-file", str, "", "Path to the client server TLS key file"),
    ("ca-file", str, "", "Path to the client server TLS trusted CA file"),
    ("client-cert-auth", bool, False,
     "Enable client cert authentication"),
    # Peer TLS (reference etcdmain/config.go:174-180).
    ("peer-cert-file", str, "", "Path to the peer server TLS cert file"),
    ("peer-key-file", str, "", "Path to the peer server TLS key file"),
    ("peer-ca-file", str, "", "Path to the peer server TLS trusted CA file"),
    ("peer-client-cert-auth", bool, False,
     "Enable peer client cert authentication"),
    # Multi-tenant engine mode (beyond the reference: the batched-kernel
    # serving path, docs/deployment.md §2).
    ("engine-groups", int, 0,
     "Multi-tenant engine mode: serve N consensus groups (tenants) from "
     "one batched kernel at /tenants/{g}/v2/keys (0 = off)"),
    ("engine-peers", int, 5, "Peer slots per engine group"),
    ("engine-window", int, 32, "On-device log ring length per engine slot"),
    ("engine-interval-ms", int, 1,
     "Milliseconds between engine rounds (0 = flat out)"),
    ("engine-mesh-peers-axis", int, 0,
     "Shard the engine over all visible devices: mesh peers-axis size "
     "(0 = no mesh, 1 = all devices on the groups axis)"),
    ("engine-applier-shards", int, 1,
     "Applier pool size: partition the post-commit apply/ack path by "
     "tenant range across N worker threads (1 = single applier)"),
    ("engine-wal-shards", int, 1,
     "WAL-writer pool size: shard the engine log into N per-tenant-range "
     "segment streams with parallel group-commit fsyncs (1 = single "
     "stream; an existing data dir may upgrade 1 -> N once)"),
]


def _env_name(flag: str) -> str:
    return "ETCD_" + flag.upper().replace("-", "_")


def parse_args(argv: Sequence[str],
               env: Optional[Dict[str, str]] = None) -> MainConfig:
    env = os.environ if env is None else env
    ap = argparse.ArgumentParser(
        prog="etcd-tpu", description=f"etcd-tpu {ver.VERSION}",
        allow_abbrev=False)
    ap.add_argument("--version", action="version",
                    version=f"etcd-tpu Version: {ver.VERSION}")
    for flag, kind, default, help_ in _FLAGS:
        dest = flag.replace("-", "_")
        if kind is bool:
            ap.add_argument(f"--{flag}", dest=dest, default=None,
                            action="store_true", help=help_)
        elif isinstance(kind, tuple):
            ap.add_argument(f"--{flag}", dest=dest, default=None,
                            choices=kind, help=help_)
        elif kind is int:
            ap.add_argument(f"--{flag}", dest=dest, default=None, type=int,
                            help=help_)
        else:
            ap.add_argument(f"--{flag}", dest=dest, default=None, help=help_)
    ns = ap.parse_args(list(argv))

    cfg = MainConfig()
    set_flags = set()
    for flag, kind, default, _ in _FLAGS:
        dest = flag.replace("-", "_")
        val = getattr(ns, dest)
        if val is None and _env_name(flag) in env:
            # Env fallback only for flags not set on the command line
            # (reference pkg/flags/flag.go:68-96).
            raw = env[_env_name(flag)]
            if kind is bool:
                val = raw.lower() in ("1", "true", "yes", "on")
            elif kind is int:
                try:
                    val = int(raw)
                except ValueError:
                    raise ConfigError(
                        f"invalid value {raw!r} for {_env_name(flag)}: "
                        f"expected an integer")
            else:
                val = raw
        if val is None:
            val = default
        else:
            set_flags.add(flag)
        if kind == "urls":
            val = parse_urls(val) if isinstance(val, str) else tuple(val)
        if flag == "initial-cluster":
            continue
        setattr(cfg, dest, val)

    # initial-cluster default derives from -name (etcdmain/etcd.go:82-85).
    raw_ic = getattr(ns, "initial_cluster") or env.get(
        _env_name("initial-cluster"))
    if raw_ic is None:
        raw_ic = initial_cluster_from_name(cfg.name)
    cfg.initial_cluster = parse_initial_cluster(raw_ic)

    # Validations (reference config.go:244-277).
    n_bootstrap = sum(1 for f in ("discovery", "initial-cluster",
                                  "discovery-srv") if f in set_flags)
    if n_bootstrap > 1:
        raise ConfigError(
            "-initial-cluster, -discovery and -discovery-srv are mutually "
            "exclusive")
    if ("listen-client-urls" in set_flags and
            "advertise-client-urls" not in set_flags and not cfg.is_proxy
            and not cfg.is_engine):
        raise ConfigError(
            "-advertise-client-urls is required when -listen-client-urls is "
            "set explicitly")
    if cfg.is_engine and (cfg.is_proxy or cfg.discovery or
                          cfg.discovery_srv):
        raise ConfigError(
            "-engine-groups is mutually exclusive with proxy and "
            "discovery modes")
    if cfg.engine_groups < 0:
        raise ConfigError("-engine-groups must be >= 0")
    if cfg.is_engine:
        if cfg.engine_peers < 1:
            raise ConfigError("-engine-peers must be >= 1")
        if cfg.engine_window < 4:
            raise ConfigError("-engine-window must be >= 4")
        if cfg.engine_interval_ms < 0:
            raise ConfigError("-engine-interval-ms must be >= 0")
        if cfg.engine_mesh_peers_axis < 0:
            raise ConfigError("-engine-mesh-peers-axis must be >= 0")
        if cfg.engine_applier_shards < 1:
            raise ConfigError("-engine-applier-shards must be >= 1")
        if cfg.engine_wal_shards < 1:
            raise ConfigError("-engine-wal-shards must be >= 1")
    if 5 * cfg.heartbeat_interval > cfg.election_timeout:
        raise ConfigError(
            f"-election-timeout[{cfg.election_timeout}ms] should be at least "
            f"5 times as -heartbeat-interval[{cfg.heartbeat_interval}ms]")
    return cfg
