"""Run a full etcd-tpu member (or proxy) in-process.

The assembly the reference does in etcdmain/etcd.go:127-231 startEtcd:
build the peer transport, the EtcdServer, and the peer + client HTTP
listeners, wired together. Used by the `etcdmain` CLI entry point, the
integration test tier (§4 T4) and the functional chaos tester.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence, Tuple
from urllib.parse import urlsplit

from etcd_tpu.etcdhttp.client import ClientAPI
from etcd_tpu.etcdhttp.peer import PeerAPI
from etcd_tpu.etcdhttp.web import HttpServer, Router
from etcd_tpu.rafthttp import HttpTransport
from etcd_tpu.server.server import EtcdServer, ServerConfig


def _listen_addr(url: str) -> Tuple[str, int]:
    u = urlsplit(url)
    return u.hostname or "127.0.0.1", u.port or 0


@dataclass
class EtcdConfig:
    """The subset of etcdmain flags an embedded member needs
    (reference etcdmain/config.go:139-208)."""
    name: str
    data_dir: str
    initial_cluster: Dict[str, Sequence[str]]
    listen_peer_urls: Sequence[str] = ()
    listen_client_urls: Sequence[str] = ()
    advertise_client_urls: Sequence[str] = ()
    cluster_token: str = "etcd-cluster"
    snap_count: int = 10000
    catch_up_entries: int = 5000   # log kept behind a snapshot (raft.go:38)
    tick_ms: int = 100
    election_ticks: int = 10
    request_timeout: float = 5.0
    initial_cluster_state: str = "new"   # "new" | "existing" (join)
    force_new_cluster: bool = False
    cors: Sequence[str] = ()             # client-listener CORS origins
    # TLS (reference etcdmain/etcd.go:133-180 listener setup +
    # pkg/transport): client_tls secures the client listeners; peer_tls
    # secures BOTH the peer listeners and the outgoing peer transport
    # (mutual TLS when its ca_file/client_cert_auth are set).
    client_tls: object = None            # Optional[tlsutil.TLSInfo]
    peer_tls: object = None              # Optional[tlsutil.TLSInfo]


class Etcd:
    """One running member: EtcdServer + peer listener + client listener(s)."""

    def __init__(self, cfg: EtcdConfig) -> None:
        if cfg.initial_cluster_state not in ("new", "existing"):
            raise ValueError(
                f"initial_cluster_state must be 'new' or 'existing', got "
                f"{cfg.initial_cluster_state!r}")
        self.cfg = cfg
        peer_urls = (tuple(cfg.listen_peer_urls) or
                     tuple(cfg.initial_cluster.get(cfg.name, ())))
        if not peer_urls:
            raise ValueError(f"no peer URLs for member {cfg.name!r}")
        client_urls = tuple(cfg.listen_client_urls)

        scfg = ServerConfig(
            name=cfg.name, data_dir=cfg.data_dir,
            initial_cluster={k: tuple(v)
                             for k, v in cfg.initial_cluster.items()},
            cluster_token=cfg.cluster_token,
            client_urls=tuple(cfg.advertise_client_urls) or client_urls,
            snap_count=cfg.snap_count, tick_ms=cfg.tick_ms,
            catch_up_entries=cfg.catch_up_entries,
            election_ticks=cfg.election_ticks,
            request_timeout=cfg.request_timeout,
            new_cluster=cfg.initial_cluster_state != "existing",
            force_new_cluster=cfg.force_new_cluster)

        peer_tls = cfg.peer_tls if (cfg.peer_tls is not None
                                    and not cfg.peer_tls.empty()) else None
        client_tls = cfg.client_tls if (cfg.client_tls is not None
                                        and not cfg.client_tls.empty()) \
            else None
        self.transport = HttpTransport(
            tls_context=peer_tls.client_context() if peer_tls else None)
        self.server = EtcdServer(scfg, self.transport)

        # Peer listener(s) — one per peer URL (reference etcd.go:133-160).
        self.peer_http = []
        papi = PeerAPI(self.server)
        for url in peer_urls:
            router = Router()
            papi.install(router)
            host, port = _listen_addr(url)
            self.peer_http.append(HttpServer(
                host, port, router,
                tls_context=peer_tls.server_context() if peer_tls else None))

        # Client listener(s) (reference etcd.go:163-180,211-229), with the
        # v2 security gate + /v2/security routes wired in.
        from etcd_tpu.etcdhttp.client_security import SecurityHandler
        self.client_http = []
        from etcd_tpu.etcdhttp.v3 import V3API
        self.security = SecurityHandler(self.server)
        self.client_api = ClientAPI(self.server, security=self.security)
        self.v3_api = V3API(self.server, security=self.security)
        for url in client_urls:
            router = Router()
            self.client_api.install(router)
            self.security.install(router)
            self.v3_api.install(router)
            host, port = _listen_addr(url)
            # CORS wraps only the CLIENT mux (reference etcd.go:218-229).
            self.client_http.append(
                HttpServer(host, port, router,
                           cors=set(cfg.cors) if cfg.cors else None,
                           tls_context=(client_tls.server_context()
                                        if client_tls else None)))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for h in self.peer_http + self.client_http:
            h.start()
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        for h in self.peer_http + self.client_http:
            h.stop()

    def wait_leader(self, timeout: float = 10.0) -> bool:
        return self.server.lead_elected_ev.wait(timeout)

    @property
    def client_urls(self) -> Tuple[str, ...]:
        return tuple(h.url for h in self.client_http)

    @property
    def peer_urls(self) -> Tuple[str, ...]:
        return tuple(h.url for h in self.peer_http)
