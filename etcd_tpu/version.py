"""Version constants and data-dir version detection.

Behavioral equivalent of reference version/version.go:26-88: the server
version string served at /version, the minimum cluster version this server
can join (rolling-upgrade gate, consumed by cluster version negotiation),
and sniffing what kind of data dir a path holds.
"""
from __future__ import annotations

import os

VERSION = "2.1.0"
SERVER_NAME = "etcd-tpu"
# Oldest cluster version a member at VERSION may serve in
# (reference version.go:27).
MIN_CLUSTER_VERSION = "2.0.0"

DATA_DIR_2_0 = "2.0"        # member/{wal,snap} layout
DATA_DIR_EMPTY = "empty"
DATA_DIR_UNKNOWN = "unknown"


def detect_data_dir(path: str) -> str:
    """Classify a data dir (reference version.go DetectDataDir:35-88)."""
    if not os.path.isdir(path):
        return DATA_DIR_EMPTY
    names = os.listdir(path)
    if not names:
        return DATA_DIR_EMPTY
    if "member" in names:
        return DATA_DIR_2_0
    return DATA_DIR_UNKNOWN


def parse(v: str) -> tuple:
    """'2.1.0' -> (2, 1, 0); tolerant of suffixes after '-'."""
    core = v.split("-", 1)[0]
    parts = core.split(".")
    return tuple(int(p) for p in parts[:3])


def minor_of(v: str) -> tuple:
    maj, mnr = parse(v)[:2]
    return (maj, mnr)
