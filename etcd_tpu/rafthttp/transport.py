"""HTTP peer transport — the distributed communication backend.

Behavioral equivalent of reference rafthttp/ (transport.go, peer.go,
pipeline.go): per-peer channels with the liveness contract the consensus
core depends on — sends NEVER block the raft run loop (bounded queue,
drop-on-full + ReportUnreachable, peer.go:156-165); per-peer ordering is
preserved (one sender thread per peer); huge MsgSnap rides a dedicated
side-channel whose outcome is reported back as ReportSnapshot
(peer.go:250-252); multiple endpoint URLs fail over (urlpick.go); Pausable
for fault-injection tests (transport.go:235-249).

Re-designed for this framework: instead of the reference's three channel
classes (msgApp stream / message stream / 4-way POST pipeline) each sender
drains its queue into ONE batched POST per flush — many messages per frame,
amortizing the HTTP round trip the way msgappv2 amortizes encoding
(msgappv2.go:29-63). Latency of successful APP batches feeds LeaderStats.
"""
from __future__ import annotations

import http.client
import queue
import threading
import time
from typing import Dict, Iterable, List, Optional
from urllib.parse import urlsplit

from etcd_tpu.raftpb import Message, MessageType
from etcd_tpu.etcdhttp.peer import RAFT_PREFIX, encode_frames
from etcd_tpu.server.transport import Transporter
from etcd_tpu.utils import metrics

# Reference pipeline.go:36-43: connPerPipeline=4, pipelineBufSize=64.
SEND_QUEUE_CAP = 4 * 64
SNAP_QUEUE_CAP = 4
_BATCH_MAX = 128          # messages drained into one POST
_RETRY_INTERVAL = 0.05    # back-off after a failed POST


class _Conn:
    """One keep-alive HTTP(S) connection to a peer URL."""

    def __init__(self, url: str, timeout: float, tls_context=None) -> None:
        u = urlsplit(url)
        self.host = u.hostname or "localhost"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.https = u.scheme == "https"
        self.tls_context = tls_context
        self.timeout = timeout
        self._c: Optional[http.client.HTTPConnection] = None

    def post(self, path: str, body: bytes, headers: Dict[str, str]) -> int:
        if self._c is None:
            if self.https:
                self._c = http.client.HTTPSConnection(
                    self.host, self.port, timeout=self.timeout,
                    context=self.tls_context)
            else:
                self._c = http.client.HTTPConnection(self.host, self.port,
                                                     timeout=self.timeout)
        try:
            self._c.request("POST", path, body=body, headers=headers)
            resp = self._c.getresponse()
            resp.read()
            return resp.status
        except Exception:
            self.close()
            raise

    def close(self) -> None:
        if self._c is not None:
            try:
                self._c.close()
            except Exception:
                pass
            self._c = None


class _Peer:
    """Sender side for one remote member (reference peer.go:87-190)."""

    def __init__(self, t: "HttpTransport", pid: int,
                 urls: Iterable[str]) -> None:
        self.t = t
        self.id = pid
        self.urls: List[str] = list(urls)
        self._url_idx = 0
        self.q: "queue.Queue[Message]" = queue.Queue(maxsize=SEND_QUEUE_CAP)
        self.snap_q: "queue.Queue[Message]" = queue.Queue(maxsize=SNAP_QUEUE_CAP)
        self._stop = threading.Event()
        self.active = False
        self._threads = [
            threading.Thread(target=self._send_loop, daemon=True,
                             name=f"rafthttp-send-{pid:x}"),
            threading.Thread(target=self._snap_loop, daemon=True,
                             name=f"rafthttp-snap-{pid:x}"),
        ]
        self._conn = _Conn(self.urls[0], t.dial_timeout, t.tls_context)
        self._snap_conn = _Conn(self.urls[0], t.snap_timeout, t.tls_context)
        for th in self._threads:
            th.start()

    # -- raft-facing side (runs on the raft loop thread; must not block) ----

    def send(self, m: Message) -> None:
        if m.type == MessageType.SNAP:
            try:
                self.snap_q.put_nowait(m)
            except queue.Full:
                self.t._report_snapshot(self.id, ok=False)
            return
        try:
            self.q.put_nowait(m)
        except queue.Full:
            # Reference peer.go:156-165: full buffer == congested/down link.
            self.t._report_unreachable(self.id)

    def update_urls(self, urls: Iterable[str]) -> None:
        urls = list(urls)
        if urls:
            self.urls = urls
            self._url_idx = 0

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=1)
        self._conn.close()
        self._snap_conn.close()

    # -- wire side ----------------------------------------------------------

    def _pick_url(self) -> str:
        return self.urls[self._url_idx % len(self.urls)]

    def _rotate_url(self) -> None:
        self._url_idx = (self._url_idx + 1) % max(len(self.urls), 1)
        self._conn = _Conn(self._pick_url(), self.t.dial_timeout,
                           self.t.tls_context)

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [first]
            while len(batch) < _BATCH_MAX:
                try:
                    batch.append(self.q.get_nowait())
                except queue.Empty:
                    break
            if self.t.paused:
                continue  # dropped, like the reference's Pausable
            body = encode_frames(batch)
            t0 = time.time()
            try:
                status = self._conn.post(RAFT_PREFIX, body,
                                         self.t._headers())
            except Exception:
                status = -1
            ms = (time.time() - t0) * 1000.0
            has_app = any(m.type == MessageType.APP for m in batch)
            if status in (200, 204):
                self.active = True
                if has_app:
                    self.t._app_sent(self.id, ms, len(body))
            else:
                self.active = False
                self._rotate_url()
                if has_app:
                    self.t._app_failed(self.id)
                self.t._report_unreachable(self.id)
                time.sleep(_RETRY_INTERVAL)

    def _snap_loop(self) -> None:
        while not self._stop.is_set():
            try:
                m = self.snap_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if self.t.paused:
                self.t._report_snapshot(self.id, ok=False)
                continue
            try:
                status = self._snap_conn.post(RAFT_PREFIX,
                                              encode_frames([m]),
                                              self.t._headers())
            except Exception:
                status = -1
            ok = status in (200, 204)
            if not ok:
                self._snap_conn = _Conn(self._pick_url(), self.t.snap_timeout,
                                        self.t.tls_context)
            self.t._report_snapshot(self.id, ok)


class HttpTransport(Transporter):
    """rafthttp.Transporter equivalent over HTTP POSTs. Bind to the server
    (for feedback + stats) via bind(); EtcdServer does this automatically."""

    def __init__(self, dial_timeout: float = 1.0,
                 snap_timeout: float = 30.0, tls_context=None) -> None:
        self.dial_timeout = dial_timeout
        self.snap_timeout = snap_timeout
        # ssl.SSLContext for https:// peer URLs (reference peer TLS,
        # pkg/transport.NewTransport + etcdmain/etcd.go:133-160).
        self.tls_context = tls_context
        self._peers: Dict[int, _Peer] = {}
        self._remotes: Dict[int, _Peer] = {}  # catch-up-only (remote.go)
        self._lock = threading.Lock()
        self.paused = False
        self._server = None

    def bind(self, server) -> None:
        self._server = server

    def member_version(self, mid: int, peer_urls: Iterable[str]):
        """GET /version from the member's peer listener with THIS
        transport's TLS context — a TLS-secured cluster must negotiate its
        version over the same mutual-TLS channel its raft traffic uses
        (reference getVersions uses the peer transport,
        cluster_util.go:118-137)."""
        import json as _json
        import ssl as _ssl
        import urllib.request
        for u in peer_urls:
            if not u.startswith(("http://", "https://")):
                continue
            try:
                with urllib.request.urlopen(
                        u.rstrip("/") + "/version", timeout=0.5,
                        context=self.tls_context if u.startswith("https://")
                        else None) as resp:
                    return _json.loads(resp.read()).get("etcdserver")
            except Exception:
                continue
        return None

    # -- Transporter ---------------------------------------------------------

    def send(self, msgs: Iterable[Message]) -> None:
        for m in msgs:
            if m.to == 0:
                continue
            with self._lock:
                p = self._peers.get(m.to) or self._remotes.get(m.to)
            if p is None:
                continue
            p.send(m)

    def add_peer(self, mid: int, urls: Iterable[str]) -> None:
        with self._lock:
            # Promote a catch-up remote to a full peer (reference
            # transport.go AddPeer removes the remote entry).
            old_remote = self._remotes.pop(mid, None)
            if mid in self._peers:
                self._peers[mid].update_urls(urls)
            else:
                self._peers[mid] = _Peer(self, mid, urls)
        if old_remote is not None:
            old_remote.stop()

    def add_remote(self, mid: int, urls: Iterable[str]) -> None:
        """A non-member we still replicate to while it catches up
        (reference rafthttp/remote.go)."""
        with self._lock:
            if mid in self._peers or mid in self._remotes:
                return
            self._remotes[mid] = _Peer(self, mid, urls)

    def remove_peer(self, mid: int) -> None:
        with self._lock:
            p = self._peers.pop(mid, None)
            r = self._remotes.pop(mid, None)
        for x in (p, r):
            if x is not None:
                x.stop()
        if self._server is not None:
            self._server.lstats.remove(mid)

    def update_peer(self, mid: int, urls: Iterable[str]) -> None:
        with self._lock:
            p = self._peers.get(mid)
        if p is not None:
            p.update_urls(urls)

    def stop(self) -> None:
        with self._lock:
            peers = list(self._peers.values()) + list(self._remotes.values())
            self._peers.clear()
            self._remotes.clear()
        for p in peers:
            p.stop()

    # -- fault injection (reference Pausable transport.go:235-249) ----------

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def active_since(self, mid: int) -> bool:
        with self._lock:
            p = self._peers.get(mid)
        return p.active if p is not None else False

    # -- feedback into the consensus core ------------------------------------

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/octet-stream"}
        if self._server is not None:
            h["X-Etcd-Cluster-ID"] = f"{self._server.cluster.cluster_id:x}"
            h["X-Server-From"] = f"{self._server.id:x}"
        return h

    def _report_unreachable(self, pid: int) -> None:
        if self._server is not None:
            self._server.report_unreachable(pid)

    def _report_snapshot(self, pid: int, ok: bool) -> None:
        if self._server is not None:
            self._server.report_snapshot(pid, ok)

    def _app_sent(self, pid: int, ms: float, nbytes: int) -> None:
        if self._server is not None:
            self._server.lstats.succ(pid, ms)
            self._server.stats.send_append_req(nbytes)
        metrics.msg_sent_latency.labels(
            "pipeline", f"{pid:x}", "MsgApp").observe(ms * 1e3)

    def _app_failed(self, pid: int) -> None:
        if self._server is not None:
            self._server.lstats.failed(pid)
        metrics.msg_sent_failed.labels("pipeline", f"{pid:x}",
                                       "MsgApp").inc()
