from etcd_tpu.rafthttp.transport import HttpTransport  # noqa: F401
