"""Typed v2 keys API.

Behavioral equivalent of reference client/keys.go: KeysAPI with
Get/Set/Create/CreateInOrder/Update/Delete and option structs collapsed to
keyword arguments, a Response{action, node, prevNode, index} triple, and a
Watcher whose next() re-issues the long-poll with waitIndex advancing past
each event (keys.go:401-424 httpWatcher.Next), recovering from 401
index-cleared by jumping to the current X-Etcd-Index.
"""
from __future__ import annotations

import json
from typing import Optional, Sequence
from urllib.parse import quote, urlencode

from etcd_tpu.client.client import Client, ClientError


class KeysError(ClientError):
    """An etcd API error body {errorCode, message, cause, index}."""

    def __init__(self, d: dict, status: int) -> None:
        self.code = d.get("errorCode", 0)
        self.message = d.get("message", "")
        self.cause = d.get("cause", "")
        self.index = d.get("index", 0)
        self.status = status
        super().__init__(f"{self.code}: {self.message} ({self.cause})")


class Node:
    def __init__(self, d: dict) -> None:
        self.key = d.get("key", "")
        self.value = d.get("value")
        self.dir = d.get("dir", False)
        self.created_index = d.get("createdIndex", 0)
        self.modified_index = d.get("modifiedIndex", 0)
        self.expiration = d.get("expiration")
        self.ttl = d.get("ttl", 0)
        self.nodes = [Node(n) for n in d.get("nodes") or []]

    def __repr__(self) -> str:
        return f"Node(key={self.key!r}, value={self.value!r})"


class Response:
    def __init__(self, d: dict, headers: dict) -> None:
        self.action = d.get("action", "")
        self.node = Node(d["node"]) if d.get("node") else None
        self.prev_node = Node(d["prevNode"]) if d.get("prevNode") else None
        self.index = int(headers.get("X-Etcd-Index", 0) or 0)
        self.raft_index = int(headers.get("X-Raft-Index", 0) or 0)
        self.raft_term = int(headers.get("X-Raft-Term", 0) or 0)


_FORM_HDR = {"Content-Type": "application/x-www-form-urlencoded"}


class KeysAPI:
    def __init__(self, client: Client, prefix: str = "/v2/keys") -> None:
        """prefix="" talks to services exposing the keyspace at the root,
        e.g. the public discovery service (reference keys.go
        NewKeysAPIWithPrefix, discovery.go:101)."""
        self.client = client
        self.prefix = prefix

    # -- plumbing -----------------------------------------------------------

    def _key_path(self, key: str) -> str:
        return self.prefix + quote("/" + key.strip("/"))

    def _call(self, method: str, key: str, params: dict,
              form: Optional[dict] = None,
              timeout: Optional[float] = None) -> Response:
        params = {k: v for k, v in params.items() if v not in (None, "")}
        path = self._key_path(key)
        if params:
            path += "?" + urlencode(params)
        body = urlencode(form).encode() if form else None
        resp = self.client.do(method, path, body,
                              _FORM_HDR if body else None, timeout=timeout)
        d = resp.json()
        if resp.status >= 400 or (isinstance(d, dict) and "errorCode" in d):
            raise KeysError(d if isinstance(d, dict) else {}, resp.status)
        return Response(d or {}, resp.headers)

    @staticmethod
    def _b(v: Optional[bool]) -> Optional[str]:
        return None if v is None else ("true" if v else "false")

    # -- API (reference keys.go:93-121) -------------------------------------

    def get(self, key: str, recursive: bool = False, sorted: bool = False,
            quorum: bool = False) -> Response:
        return self._call("GET", key, {
            "recursive": self._b(recursive) if recursive else None,
            "sorted": self._b(sorted) if sorted else None,
            "quorum": self._b(quorum) if quorum else None})

    def set(self, key: str, value: Optional[str] = None, ttl: int = 0,
            prev_value: str = "", prev_index: int = 0,
            prev_exist: Optional[bool] = None, dir: bool = False,
            refresh: bool = False) -> Response:
        params = {"prevValue": prev_value or None,
                  "prevIndex": prev_index or None,
                  "prevExist": self._b(prev_exist),
                  "dir": self._b(dir) if dir else None,
                  "refresh": self._b(refresh) if refresh else None}
        form = {}
        if value is not None:
            form["value"] = value
        if ttl:
            form["ttl"] = str(ttl)
        return self._call("PUT", key, params, form or None)

    def create(self, key: str, value: str, ttl: int = 0) -> Response:
        return self.set(key, value, ttl=ttl, prev_exist=False)

    def create_in_order(self, dir_key: str, value: str,
                        ttl: int = 0) -> Response:
        form = {"value": value}
        if ttl:
            form["ttl"] = str(ttl)
        return self._call("POST", dir_key, {}, form)

    def update(self, key: str, value: str, ttl: int = 0) -> Response:
        return self.set(key, value, ttl=ttl, prev_exist=True)

    def delete(self, key: str, recursive: bool = False, dir: bool = False,
               prev_value: str = "", prev_index: int = 0) -> Response:
        return self._call("DELETE", key, {
            "recursive": self._b(recursive) if recursive else None,
            "dir": self._b(dir) if dir else None,
            "prevValue": prev_value or None,
            "prevIndex": prev_index or None})

    def watcher(self, key: str, after_index: int = 0,
                recursive: bool = False) -> "Watcher":
        return Watcher(self, key, after_index, recursive)


class Watcher:
    """Repeated long-poll watcher (reference keys.go httpWatcher)."""

    def __init__(self, api: KeysAPI, key: str, after_index: int,
                 recursive: bool) -> None:
        self.api = api
        self.key = key
        self.recursive = recursive
        self.next_wait = after_index + 1 if after_index else 0

    def next(self, timeout: Optional[float] = None) -> Response:
        """Block until the next event. timeout=None blocks indefinitely,
        re-issuing the long-poll whenever a quiet period outlives the HTTP
        read timeout (reference httpWatcher.Next retry loop)."""
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        while True:
            if deadline is None:
                per_req = 60.0
            else:
                per_req = deadline - _time.time()
                if per_req <= 0:
                    raise ClientError("watch timed out")
            try:
                r = self.api._call("GET", self.key, {
                    "wait": "true",
                    "recursive": KeysAPI._b(self.recursive)
                                 if self.recursive else None,
                    "waitIndex": self.next_wait or None},
                    timeout=per_req)
            except KeysError as e:
                if e.code == 401:  # history window outran us: jump forward
                    self.next_wait = e.index + 1
                    continue
                raise
            except ClientError:
                # Idle long-poll outlived the read timeout — re-issue with
                # the same waitIndex; nothing is lost (history ring).
                if deadline is not None and _time.time() >= deadline:
                    raise
                _time.sleep(0.1)  # don't spin if the cluster is down
                continue
            if r.node is None:  # empty answer (server shutdown / broken poll)
                continue
            self.next_wait = r.node.modified_index + 1
            return r
