"""Multi-endpoint HTTP client core.

Behavioral equivalent of reference client/client.go:112-244: a list of
endpoints tried in order until one answers (httpClusterClient.Do), with
Sync() refreshing the endpoint list from /v2/members and a pinned endpoint
moved to front on success. Transport is stdlib urllib — the SDK talks only
the public HTTP API, never server internals.
"""
from __future__ import annotations

import json
import random
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple


class ClientError(Exception):
    pass


class ClusterError(ClientError):
    """All endpoints failed (reference client.go ClusterError)."""

    def __init__(self, errors_: List[Exception]) -> None:
        self.errors = errors_
        super().__init__(
            "; ".join(f"{type(e).__name__}: {e}" for e in errors_)
            or "no endpoints")


class HttpResponse:
    def __init__(self, status: int, headers: Dict[str, str],
                 body: bytes) -> None:
        self.status = status
        self.headers = headers
        self.body = body

    def json(self):
        return json.loads(self.body) if self.body else None


class Client:
    """A cluster-aware HTTP client; thread-safe."""

    def __init__(self, endpoints: Sequence[str], timeout: float = 5.0,
                 username: str = "", password: str = "",
                 proxy: str = "", tls=None) -> None:
        """proxy: optional HTTP proxy URL all requests are routed through
        (reference discovery newProxyFunc + http.Transport.Proxy).
        tls: a utils.tlsutil.TLSInfo (or ready ssl.SSLContext) for
        https:// endpoints — CA verification + optional client cert
        (reference client TLS flags, etcdmain/config.go:166-180)."""
        if not endpoints:
            raise ValueError("at least one endpoint required")
        self._lock = threading.Lock()
        self._endpoints = [e.rstrip("/") for e in endpoints]
        self.timeout = timeout
        self.username = username
        self.password = password
        if proxy and "://" not in proxy:
            proxy = "http://" + proxy
        self.proxy = proxy
        import ssl as _ssl
        if tls is None or isinstance(tls, _ssl.SSLContext):
            self.tls_context = tls
        else:
            from etcd_tpu.utils.tlsutil import client_context_or_none
            self.tls_context = client_context_or_none(tls)

    @property
    def endpoints(self) -> List[str]:
        with self._lock:
            return list(self._endpoints)

    def set_endpoints(self, endpoints: Sequence[str]) -> None:
        with self._lock:
            if endpoints:
                self._endpoints = [e.rstrip("/") for e in endpoints]

    def sync(self) -> None:
        """Refresh endpoints from the cluster itself (reference
        client.go:179-215 Sync)."""
        resp = self.do("GET", "/v2/members")
        if resp.status != 200:
            raise ClientError(f"sync failed: HTTP {resp.status}")
        eps: List[str] = []
        for m in resp.json().get("members", []):
            eps.extend(m.get("clientURLs") or [])
        self.set_endpoints(eps)

    # -- request plumbing ---------------------------------------------------

    def _request_one(self, endpoint: str, method: str, path: str,
                     body: Optional[bytes], headers: Dict[str, str],
                     timeout: float) -> HttpResponse:
        r = urllib.request.Request(endpoint + path, data=body,
                                   method=method, headers=headers)
        if self.proxy:
            from urllib.parse import urlsplit
            pu = urlsplit(self.proxy)
            host = pu.hostname + (f":{pu.port}" if pu.port else "")
            r.set_proxy(host, urlsplit(endpoint).scheme or "http")
            if pu.username:
                import base64
                cred = base64.b64encode(
                    f"{pu.username}:{pu.password or ''}".encode()).decode()
                r.add_header("Proxy-Authorization", f"Basic {cred}")
        if self.username:
            import base64
            cred = base64.b64encode(
                f"{self.username}:{self.password}".encode()).decode()
            r.add_header("Authorization", f"Basic {cred}")
        try:
            with urllib.request.urlopen(r, timeout=timeout,
                                        context=self.tls_context) as resp:
                return HttpResponse(resp.status, dict(resp.headers),
                                    resp.read())
        except urllib.error.HTTPError as e:
            return HttpResponse(e.code, dict(e.headers), e.read())

    def do(self, method: str, path: str, body: Optional[bytes] = None,
           headers: Optional[Dict[str, str]] = None,
           timeout: Optional[float] = None) -> HttpResponse:
        """Try every endpoint in order; first HTTP answer wins. 5xx answers
        rotate to the next endpoint too (reference httpClusterClient.Do
        retries on network error and 50x)."""
        headers = dict(headers or {})
        timeout = self.timeout if timeout is None else timeout
        failures: List[Exception] = []
        last: Optional[HttpResponse] = None
        for ep in self.endpoints:
            try:
                resp = self._request_one(ep, method, path, body, headers,
                                         timeout)
            except Exception as e:
                failures.append(e)
                continue
            if resp.status >= 500:
                last = resp
                continue
            self._pin(ep)
            return resp
        if last is not None:
            return last
        raise ClusterError(failures)

    def _pin(self, endpoint: str) -> None:
        with self._lock:
            if self._endpoints and self._endpoints[0] != endpoint and \
                    endpoint in self._endpoints:
                self._endpoints.remove(endpoint)
                self._endpoints.insert(0, endpoint)
