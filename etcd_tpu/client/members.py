"""Typed membership API (reference client/members.go:96-105)."""
from __future__ import annotations

import json
from typing import List, Optional, Sequence

from etcd_tpu.client.client import Client, ClientError

_JSON_HDR = {"Content-Type": "application/json"}


class MemberInfo:
    def __init__(self, d: dict) -> None:
        self.id = d.get("id", "")
        self.name = d.get("name", "")
        self.peer_urls = list(d.get("peerURLs") or [])
        self.client_urls = list(d.get("clientURLs") or [])

    def __repr__(self) -> str:
        return f"MemberInfo(id={self.id}, name={self.name!r})"


class MembersError(ClientError):
    def __init__(self, status: int, message: str) -> None:
        self.status = status
        self.message = message
        super().__init__(f"HTTP {status}: {message}")


class MembersAPI:
    def __init__(self, client: Client) -> None:
        self.client = client

    def list(self) -> List[MemberInfo]:
        resp = self.client.do("GET", "/v2/members")
        if resp.status != 200:
            raise MembersError(resp.status, resp.body.decode())
        return [MemberInfo(m) for m in resp.json().get("members", [])]

    def add(self, peer_urls: Sequence[str]) -> MemberInfo:
        body = json.dumps({"peerURLs": list(peer_urls)}).encode()
        resp = self.client.do("POST", "/v2/members", body, _JSON_HDR)
        if resp.status != 201:
            d = resp.json() or {}
            raise MembersError(resp.status, d.get("message",
                                                  resp.body.decode()))
        return MemberInfo(resp.json())

    def remove(self, member_id: str) -> None:
        resp = self.client.do("DELETE", f"/v2/members/{member_id}")
        if resp.status not in (204, 200):
            raise MembersError(resp.status, resp.body.decode())

    def update(self, member_id: str, peer_urls: Sequence[str]) -> None:
        body = json.dumps({"peerURLs": list(peer_urls)}).encode()
        resp = self.client.do("PUT", f"/v2/members/{member_id}", body,
                              _JSON_HDR)
        if resp.status not in (204, 200):
            raise MembersError(resp.status, resp.body.decode())

    def leader(self) -> Optional[MemberInfo]:
        """The member currently serving /v2/stats/leader (reference
        members.go Leader)."""
        for m in self.list():
            for ep in m.client_urls:
                try:
                    resp = self.client._request_one(
                        ep.rstrip("/"), "GET", "/v2/stats/leader", None, {},
                        self.client.timeout)
                except Exception:
                    continue
                if resp.status == 200:
                    return m
        return None
