"""Client SDK (reference client/): multi-endpoint failover HTTP client,
typed KeysAPI / MembersAPI, and discovery helpers."""
from etcd_tpu.client.client import Client, ClientError, ClusterError  # noqa: F401
from etcd_tpu.client.keys import KeysAPI, KeysError, Node, Response, Watcher  # noqa: F401
from etcd_tpu.client.members import MembersAPI  # noqa: F401
