"""etcdctl command set.

Behavioral equivalent of reference etcdctl/main.go + etcdctl/command/*.go:
ls/mk/mkdir/rm/rmdir/get/set/setdir/update/updatedir/watch/exec-watch,
member list|add|remove, cluster-health, backup (disaster-recovery WAL copy
with fresh node identity, backup_command.go:33-) and import. Peers come
from --peers / ETCDCTL_PEERS; output shapes follow the reference commands.

Beyond the reference: `v3 put|get|del|compact|txn` drive the served v3 KV
preview (/v3/kv gateway; the reference ships only the RFC).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from etcd_tpu.client import Client, KeysAPI, KeysError, MembersAPI
from etcd_tpu.client.client import ClientError

DEFAULT_PEERS = "http://127.0.0.1:4001,http://127.0.0.1:2379"


def _client(args) -> Client:
    peers = (args.peers or os.environ.get("ETCDCTL_PEERS") or
             DEFAULT_PEERS).split(",")
    return Client([p.strip() for p in peers if p.strip()],
                  timeout=args.timeout,
                  username=(args.username or "").split(":")[0],
                  password=(args.username.split(":", 1)[1]
                            if args.username and ":" in args.username
                            else ""))


def _keys(args) -> KeysAPI:
    return KeysAPI(_client(args))


def _die(msg: str, code: int = 1) -> int:
    print(f"Error: {msg}", file=sys.stderr)
    return code


# -- v3 commands (the served v3 preview; reference ships only the RFC) -------

def _v3_call(args, path: str, body: dict):
    """POST one v3 op through the shared Client (endpoint failover, 5xx
    rotation and Basic auth all come from Client.do — one code path with
    the v2 commands)."""
    resp = _client(args).do("POST", f"/v3/kv/{path}",
                            json.dumps(body).encode(),
                            headers={"Content-Type": "application/json"})
    try:
        parsed = json.loads(resp.body) if resp.body else None
    except json.JSONDecodeError:
        parsed = None
    if not isinstance(parsed, dict):
        # Non-gateway answer (v2-only member, proxy error page): a clean
        # CLI error, not a traceback.
        parsed = {"error": (resp.body or b"").decode(errors="replace")
                  [:200] or f"HTTP {resp.status}", "code": 13}
    return resp.status, parsed


def _b64s(s: str) -> str:
    import base64 as _b64
    return _b64.b64encode(s.encode()).decode()


def _b64d(s: str) -> str:
    import base64 as _b64
    return _b64.b64decode(s).decode(errors="replace")


def _prefix_end_b64(key: str) -> str:
    """base64 of the smallest byte string greater than every key with this
    prefix. Computed on RAW bytes and base64'd directly — a bytes->str
    round-trip would mangle the (often invalid-UTF-8) end bytes and make
    --prefix match/delete keys OUTSIDE the prefix."""
    import base64 as _b64
    b = bytearray(key.encode())
    for i in range(len(b) - 1, -1, -1):
        if b[i] < 0xFF:
            b[i] += 1
            return _b64.b64encode(bytes(b[:i + 1])).decode()
    return _b64.b64encode(b"\x00").decode()   # whole keyspace


def cmd_v3_put(args) -> int:
    st, b = _v3_call(args, "put", {"key": _b64s(args.key),
                                   "value": _b64s(args.value)})
    if st != 200:
        return _die(b.get("error", str(b)))
    print("OK")
    return 0


def cmd_v3_get(args) -> int:
    body = {"key": _b64s(args.key)}
    if args.prefix:
        body["range_end"] = _prefix_end_b64(args.key)
    if args.rev:
        body["revision"] = args.rev
    if args.limit:
        body["limit"] = args.limit
    if args.serializable:
        body["serializable"] = True
    st, b = _v3_call(args, "range", body)
    if st != 200:
        return _die(b.get("error", str(b)))
    for kv in b.get("kvs", []):
        print(_b64d(kv["key"]))
        print(_b64d(kv["value"]))
    return 0


def cmd_v3_del(args) -> int:
    body = {"key": _b64s(args.key)}
    if args.prefix:
        body["range_end"] = _prefix_end_b64(args.key)
    st, b = _v3_call(args, "deleterange", body)
    if st != 200:
        return _die(b.get("error", str(b)))
    print(b.get("deleted", 0))
    return 0


def cmd_v3_compact(args) -> int:
    st, b = _v3_call(args, "compact", {"revision": args.revision})
    if st != 200:
        return _die(b.get("error", str(b)))
    print(f"compacted revision {args.revision}")
    return 0


def cmd_v3_txn(args) -> int:
    """Reads a TxnRequest as JSON from stdin (compare/success/failure with
    base64 bytes fields, the gateway encoding) and prints the response."""
    try:
        body = json.loads(sys.stdin.read() or "{}")
    except json.JSONDecodeError as e:
        return _die(f"bad txn JSON on stdin: {e}")
    st, b = _v3_call(args, "txn", body)
    print(json.dumps(b, indent=2))
    return 0 if st == 200 else 1


# -- key commands (reference etcdctl/command/*_command.go) -------------------

def cmd_get(args) -> int:
    try:
        r = _keys(args).get(args.key, sorted=args.sort,
                            quorum=args.quorum)
    except KeysError as e:
        return _die(e.message if e.code else str(e))
    if r.node.dir:
        return _die(f"{args.key}: is a directory")
    print(r.node.value)
    return 0


def cmd_set(args) -> int:
    try:
        r = _keys(args).set(args.key, args.value, ttl=args.ttl,
                            prev_value=args.swap_with_value or "",
                            prev_index=args.swap_with_index)
    except KeysError as e:
        return _die(e.message)
    print(r.node.value)
    return 0


def cmd_mk(args) -> int:
    try:
        r = _keys(args).create(args.key, args.value, ttl=args.ttl)
    except KeysError as e:
        return _die(e.message)
    print(r.node.value)
    return 0


def cmd_mkdir(args) -> int:
    try:
        _keys(args).set(args.key, dir=True, ttl=args.ttl, prev_exist=False)
    except KeysError as e:
        return _die(e.message)
    return 0


def cmd_setdir(args) -> int:
    try:
        _keys(args).set(args.key, dir=True, ttl=args.ttl)
    except KeysError as e:
        return _die(e.message)
    return 0


def cmd_update(args) -> int:
    try:
        r = _keys(args).update(args.key, args.value, ttl=args.ttl)
    except KeysError as e:
        return _die(e.message)
    print(r.node.value)
    return 0


def cmd_updatedir(args) -> int:
    try:
        _keys(args).set(args.key, dir=True, ttl=args.ttl, prev_exist=True)
    except KeysError as e:
        return _die(e.message)
    return 0


def cmd_rm(args) -> int:
    try:
        if args.recursive:
            _keys(args).delete(args.key, recursive=True)
        elif args.dir:
            _keys(args).delete(args.key, dir=True)
        else:
            _keys(args).delete(args.key,
                               prev_value=args.with_value or "",
                               prev_index=args.with_index)
    except KeysError as e:
        return _die(e.message)
    return 0


def cmd_rmdir(args) -> int:
    try:
        _keys(args).delete(args.key, dir=True)
    except KeysError as e:
        return _die(e.message)
    return 0


def cmd_ls(args) -> int:
    try:
        r = _keys(args).get(args.key, recursive=args.recursive,
                            sorted=args.sort)
    except KeysError as e:
        return _die(e.message)

    def walk(node, depth=0):
        for n in node.nodes:
            suffix = "/" if n.dir else ""
            if args.p and n.dir:
                print(n.key + "/")
            else:
                print(n.key + (suffix if args.p else ""))
            if args.recursive and n.dir:
                walk(n, depth + 1)

    if r.node.dir:
        walk(r.node)
    else:
        print(r.node.key)
    return 0


def cmd_watch(args) -> int:
    k = _keys(args)
    w = k.watcher(args.key, after_index=args.after_index,
                  recursive=args.recursive)
    try:
        while True:
            r = w.next()
            print(r.node.value if r.node and r.node.value is not None
                  else "")
            if not args.forever:
                return 0
    except KeyboardInterrupt:
        return 0


def cmd_exec_watch(args) -> int:
    k = _keys(args)
    w = k.watcher(args.key, recursive=args.recursive)
    cmdline = args.cmd
    try:
        while True:
            r = w.next()
            env = dict(os.environ)
            env["ETCD_WATCH_ACTION"] = r.action
            env["ETCD_WATCH_KEY"] = r.node.key if r.node else ""
            env["ETCD_WATCH_VALUE"] = (r.node.value or ""
                                       if r.node else "")
            env["ETCD_WATCH_MODIFIED_INDEX"] = str(
                r.node.modified_index if r.node else 0)
            subprocess.call(cmdline, env=env)
    except KeyboardInterrupt:
        return 0


# -- member commands ---------------------------------------------------------

def cmd_member_list(args) -> int:
    for m in MembersAPI(_client(args)).list():
        print(f"{m.id}: name={m.name} peerURLs={','.join(m.peer_urls)} "
              f"clientURLs={','.join(m.client_urls)}")
    return 0


def cmd_member_add(args) -> int:
    mapi = MembersAPI(_client(args))
    m = mapi.add(args.peer_urls.split(","))
    print(f"Added member named {args.name} with ID {m.id} to cluster")
    existing = mapi.list()
    names = [f"{x.name or args.name}={u}"
             for x in existing for u in x.peer_urls]
    print(f'ETCD_NAME="{args.name}"')
    print(f'ETCD_INITIAL_CLUSTER="{",".join(names)}"')
    print('ETCD_INITIAL_CLUSTER_STATE="existing"')
    return 0


def cmd_member_remove(args) -> int:
    MembersAPI(_client(args)).remove(args.member_id)
    print(f"Removed member {args.member_id} from cluster")
    return 0


def cmd_cluster_health(args) -> int:
    """reference etcdctl/command/cluster_health.go: per-member /health."""
    import urllib.request
    c = _client(args)
    try:
        members = MembersAPI(c).list()
    except ClientError as e:
        print("cluster may be unhealthy: failed to list members")
        return _die(str(e))
    unhealthy = 0
    for m in members:
        ok = False
        for u in m.client_urls:
            try:
                with urllib.request.urlopen(u.rstrip("/") + "/health",
                                            timeout=args.timeout) as resp:
                    ok = json.loads(resp.read()).get("health") == "true"
                    break
            except Exception:
                continue
        status = "healthy" if ok else "unhealthy"
        if not ok:
            unhealthy += 1
        print(f"member {m.id} is {status}: got {status} result from "
              f"{m.client_urls[0] if m.client_urls else '<none>'}")
    if unhealthy == 0:
        print("cluster is healthy")
        return 0
    print("cluster is degraded" if unhealthy < len(members)
          else "cluster is unavailable")
    return 5


# -- backup (reference etcdctl/command/backup_command.go:33-) ----------------

def cmd_backup(args) -> int:
    from etcd_tpu import raftpb
    from etcd_tpu.snap import Snapshotter
    from etcd_tpu.utils.fileutil import touch_dir_all
    from etcd_tpu.wal import WAL, WalSnapshot

    src_snap = os.path.join(args.data_dir, "member", "snap")
    src_wal = args.wal_dir or os.path.join(args.data_dir, "member", "wal")
    dst_snap = os.path.join(args.backup_dir, "member", "snap")
    dst_wal = (args.backup_wal_dir or
               os.path.join(args.backup_dir, "member", "wal"))

    touch_dir_all(dst_snap)
    ss = Snapshotter(src_snap)
    snap = ss.load_or_none()
    walsnap = WalSnapshot()
    if snap is not None:
        walsnap = WalSnapshot(index=snap.metadata.index,
                              term=snap.metadata.term)
        Snapshotter(dst_snap).save_snap(snap)

    # Read-only open: the source member may still be running and holding
    # its segment locks (reference uses wal.OpenNotInUse).
    with WAL.open(src_wal, walsnap, write=False) as w:
        metadata, hs, ents = w.read_all()
    # Strip the node identity so the restored member forms a NEW cluster
    # (reference backup_command.go rewrites metadata with fresh ids).
    md = json.loads(metadata.decode()) if metadata else {}
    md["id"] = "0"
    md["clusterId"] = "0"
    neww = WAL.create(dst_wal, json.dumps(md).encode())
    try:
        neww.save_snapshot(walsnap)
        neww.save(hs, list(ents))
    finally:
        neww.close()
    print(f"backup saved to {args.backup_dir} "
          f"({len(ents)} entries, snapshot "
          f"{'yes' if snap is not None else 'no'})")
    return 0


def cmd_import(args) -> int:
    """Bulk-load a JSON dump of key->value pairs (moral of
    import_snap_command.go without the legacy 0.4 snap format)."""
    k = _keys(args)
    with open(args.snap_file) as f:
        data = json.load(f)
    n = 0
    for key, value in data.items():
        k.set(key, value)
        n += 1
    print(f"imported {n} keys")
    return 0


# -- argument wiring ---------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="etcdctl", description="A simple command line client for etcd.")
    ap.add_argument("--peers", "-C", default=None,
                    help="comma-separated machine addresses")
    ap.add_argument("--timeout", type=float, default=2.0)
    ap.add_argument("--username", "-u", default=None,
                    help="user:password for auth")
    ap.add_argument("--debug", action="store_true")
    sub = ap.add_subparsers(dest="command", required=True)

    def add(name, fn, **kw):
        p = sub.add_parser(name, **kw)
        p.set_defaults(fn=fn)
        return p

    p = add("get", cmd_get)
    p.add_argument("key")
    p.add_argument("--sort", action="store_true")
    p.add_argument("--quorum", action="store_true")

    p = add("set", cmd_set)
    p.add_argument("key")
    p.add_argument("value")
    p.add_argument("--ttl", type=int, default=0)
    p.add_argument("--swap-with-value", default=None)
    p.add_argument("--swap-with-index", type=int, default=0)

    p = add("mk", cmd_mk)
    p.add_argument("key")
    p.add_argument("value")
    p.add_argument("--ttl", type=int, default=0)

    for name, fn in (("mkdir", cmd_mkdir), ("setdir", cmd_setdir),
                     ("updatedir", cmd_updatedir)):
        p = add(name, fn)
        p.add_argument("key")
        p.add_argument("--ttl", type=int, default=0)

    p = add("update", cmd_update)
    p.add_argument("key")
    p.add_argument("value")
    p.add_argument("--ttl", type=int, default=0)

    p = add("rm", cmd_rm)
    p.add_argument("key")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--dir", action="store_true")
    p.add_argument("--with-value", default=None)
    p.add_argument("--with-index", type=int, default=0)

    p = add("rmdir", cmd_rmdir)
    p.add_argument("key")

    p = add("ls", cmd_ls)
    p.add_argument("key", nargs="?", default="/")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--sort", action="store_true")
    p.add_argument("-p", action="store_true",
                   help="append / to directories")

    p = add("watch", cmd_watch)
    p.add_argument("key")
    p.add_argument("--forever", action="store_true")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--after-index", type=int, default=0)

    p = add("exec-watch", cmd_exec_watch)
    p.add_argument("key")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    p.add_argument("--recursive", action="store_true")

    pm = sub.add_parser("member")
    msub = pm.add_subparsers(dest="member_command", required=True)
    p = msub.add_parser("list")
    p.set_defaults(fn=cmd_member_list)
    p = msub.add_parser("add")
    p.add_argument("name")
    p.add_argument("peer_urls")
    p.set_defaults(fn=cmd_member_add)
    p = msub.add_parser("remove")
    p.add_argument("member_id")
    p.set_defaults(fn=cmd_member_remove)

    add("cluster-health", cmd_cluster_health)

    pv3 = sub.add_parser("v3", help="v3 KV preview (served /v3/kv gateway)")
    v3sub = pv3.add_subparsers(dest="v3_command", required=True)
    p = v3sub.add_parser("put")
    p.add_argument("key")
    p.add_argument("value")
    p.set_defaults(fn=cmd_v3_put)
    p = v3sub.add_parser("get")
    p.add_argument("key")
    p.add_argument("--prefix", action="store_true")
    p.add_argument("--rev", type=int, default=0)
    p.add_argument("--limit", type=int, default=0)
    p.add_argument("--serializable", action="store_true")
    p.set_defaults(fn=cmd_v3_get)
    p = v3sub.add_parser("del")
    p.add_argument("key")
    p.add_argument("--prefix", action="store_true")
    p.set_defaults(fn=cmd_v3_del)
    p = v3sub.add_parser("compact")
    p.add_argument("revision", type=int)
    p.set_defaults(fn=cmd_v3_compact)
    p = v3sub.add_parser("txn", help="TxnRequest JSON on stdin")
    p.set_defaults(fn=cmd_v3_txn)

    p = add("backup", cmd_backup)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--wal-dir", default=None)
    p.add_argument("--backup-dir", required=True)
    p.add_argument("--backup-wal-dir", default=None)

    p = add("import", cmd_import)
    p.add_argument("--snap-file", required=True)

    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except ClientError as e:
        return _die(str(e))


if __name__ == "__main__":
    sys.exit(main())
