"""etcdctl — the command-line client (reference etcdctl/)."""
