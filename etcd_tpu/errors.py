"""The v2 API numeric error space.

Behavioral equivalent of reference error/error.go:28-150: stable numeric
codes (100s command errors, 200s post-form errors, 300s raft, 400s etcd),
their default messages, and the HTTP status each maps to. The JSON body shape
{errorCode, message, cause, index} is part of the public API surface.
"""
from __future__ import annotations

import json

# Command-related errors.
ECODE_KEY_NOT_FOUND = 100
ECODE_TEST_FAILED = 101
ECODE_NOT_FILE = 102
ECODE_NOT_DIR = 104
ECODE_NODE_EXIST = 105
ECODE_ROOT_RONLY = 107
ECODE_DIR_NOT_EMPTY = 108
ECODE_UNAUTHORIZED = 110

# Post-form errors.
ECODE_PREV_VALUE_REQUIRED = 201
ECODE_TTL_NAN = 202
ECODE_INDEX_NAN = 203
ECODE_INVALID_FIELD = 209
ECODE_INVALID_FORM = 210
ECODE_REFRESH_VALUE = 212
ECODE_REFRESH_TTL_REQUIRED = 213

# Raft-related errors.
ECODE_RAFT_INTERNAL = 300
ECODE_LEADER_ELECT = 301

# Etcd-related errors.
ECODE_WATCHER_CLEARED = 400
ECODE_EVENT_INDEX_CLEARED = 401

_MESSAGES = {
    ECODE_KEY_NOT_FOUND: "Key not found",
    ECODE_TEST_FAILED: "Compare failed",
    ECODE_NOT_FILE: "Not a file",
    ECODE_NOT_DIR: "Not a directory",
    ECODE_NODE_EXIST: "Key already exists",
    ECODE_ROOT_RONLY: "Root is read only",
    ECODE_DIR_NOT_EMPTY: "Directory not empty",
    ECODE_UNAUTHORIZED: "The request requires user authentication",
    ECODE_PREV_VALUE_REQUIRED: "PrevValue is Required in POST form",
    ECODE_TTL_NAN: "The given TTL in POST form is not a number",
    ECODE_INDEX_NAN: "The given index in POST form is not a number",
    ECODE_INVALID_FIELD: "Invalid field",
    ECODE_INVALID_FORM: "Invalid POST form",
    ECODE_REFRESH_VALUE: "Value provided on refresh",
    ECODE_REFRESH_TTL_REQUIRED: "A TTL must be provided on refresh",
    ECODE_RAFT_INTERNAL: "Raft Internal Error",
    ECODE_LEADER_ELECT: "During Leader Election",
    ECODE_WATCHER_CLEARED: "watcher is cleared due to etcd recovery",
    ECODE_EVENT_INDEX_CLEARED: "The event in requested index is outdated and cleared",
}

# HTTP status mapping (reference error.go:116-130): defaults to 400; these
# are the exceptions.
_STATUS = {
    ECODE_KEY_NOT_FOUND: 404,
    ECODE_TEST_FAILED: 412,
    ECODE_NODE_EXIST: 412,
    ECODE_NOT_FILE: 403,
    ECODE_DIR_NOT_EMPTY: 403,
    ECODE_UNAUTHORIZED: 401,
    ECODE_RAFT_INTERNAL: 500,
    ECODE_LEADER_ELECT: 500,
}


class EtcdError(Exception):
    """An API-visible error carrying a stable numeric code."""

    def __init__(self, code: int, cause: str = "", index: int = 0) -> None:
        self.code = code
        self.message = _MESSAGES.get(code, "unknown error")
        self.cause = cause
        self.index = index
        super().__init__(f"{self.code}: {self.message} ({cause}) [{index}]")

    @property
    def status_code(self) -> int:
        return _STATUS.get(self.code, 400)

    def to_dict(self) -> dict:
        return {
            "errorCode": self.code,
            "message": self.message,
            "cause": self.cause,
            "index": self.index,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
