"""Events and the bounded event history ring.

Behavioral equivalent of reference store/event.go:28-33, store/node_extern.go
and store/event_history.go:26-105: the external node representation
(NodeExtern) that the HTTP API serializes, the Event{action, node, prevNode}
triple, and a 1000-event ring that lets watchers resume from a recent index
(`since`) without holding per-watcher buffers.
"""
from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import List, Optional

from etcd_tpu import errors

# Actions (reference store/event.go:19-27).
GET = "get"
CREATE = "create"
SET = "set"
UPDATE = "update"
DELETE = "delete"
COMPARE_AND_SWAP = "compareAndSwap"
COMPARE_AND_DELETE = "compareAndDelete"
EXPIRE = "expire"

DEFAULT_HISTORY_CAPACITY = 1000  # reference store/store.go:79


def format_expiration(ts: float) -> str:
    """RFC3339Nano-style UTC timestamp, matching the reference's JSON."""
    dt = datetime.fromtimestamp(ts, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


@dataclass(slots=True)
class NodeExtern:
    """External (API-facing) view of a store node (reference
    store/node_extern.go:26-38). `value` is None for dirs; `nodes` is None
    unless the dir's children were materialized."""

    key: str = ""
    value: Optional[str] = None
    dir: bool = False
    nodes: Optional[List["NodeExtern"]] = None
    created_index: int = 0
    modified_index: int = 0
    expiration: Optional[float] = None  # absolute unix seconds
    ttl: int = 0                        # remaining seconds, rounded up

    def to_dict(self) -> dict:
        d: dict = {"key": self.key}
        if self.dir:
            d["dir"] = True
        if self.value is not None:
            d["value"] = self.value
        if self.expiration is not None:
            d["expiration"] = format_expiration(self.expiration)
            d["ttl"] = self.ttl
        if self.nodes is not None:
            d["nodes"] = [n.to_dict() for n in self.nodes]
        d["modifiedIndex"] = self.modified_index
        d["createdIndex"] = self.created_index
        return d


@dataclass
class Event:
    action: str
    node: Optional[NodeExtern] = None
    prev_node: Optional[NodeExtern] = None
    etcd_index: int = 0  # X-Etcd-Index at response time (not in the body)

    @property
    def index(self) -> int:
        return self.node.modified_index if self.node else 0

    def to_dict(self) -> dict:
        d: dict = {"action": self.action}
        if self.node is not None:
            d["node"] = self.node.to_dict()
        if self.prev_node is not None:
            d["prevNode"] = self.prev_node.to_dict()
        return d


class LazyWriteEvent:
    """Raw C write descriptors standing in for a materialized Event on the
    applier → waiter handoff. The applier records only the descriptor
    6-tuples the native store already built; the HTTP thread that consumes
    the waiter's result calls resolve() to pay for the NodeExtern/Event
    churn — moving ~40% of the per-ack Python work off the (serialized)
    apply stage onto the (parallel) serving threads. Only plain-file SETs
    take this path, so `action` is fixed."""

    __slots__ = ("nd", "pd", "etcd_index", "now")
    action = SET

    def __init__(self, nd, pd, etcd_index: int, now: float) -> None:
        self.nd = nd
        self.pd = pd
        self.etcd_index = etcd_index
        self.now = now

    def _extern(self, d) -> NodeExtern:
        key, value, is_dir, created, modified, exp = d
        return NodeExtern(key, value, is_dir, None, created, modified, exp,
                          ttl_of(exp, self.now))

    def resolve(self) -> Event:
        return Event(SET, node=self._extern(self.nd),
                     prev_node=(None if self.pd is None
                                else self._extern(self.pd)),
                     etcd_index=self.etcd_index)


class EventHistory:
    """Fixed-capacity ring of past events, scanned by watchers that join
    with a `since` index (reference store/event_history.go)."""

    def __init__(self, capacity: int = DEFAULT_HISTORY_CAPACITY) -> None:
        self.capacity = capacity
        # deque(maxlen): a full ring evicts in O(1) — list.pop(0) was a
        # 1000-element memmove on EVERY apply once warm (profiled as the
        # single hottest line of the engine apply path).
        self.events: deque = deque(maxlen=capacity)
        self.start_index = 0  # index of the oldest retained event
        self.last_index = 0

    def add(self, e: Event) -> Event:
        self.events.append(e)
        self.start_index = self.events[0].index
        self.last_index = e.index
        return e

    def scan(self, key: str, recursive: bool, since: int) -> Optional[Event]:
        """First event at index >= since touching `key` (or its subtree if
        recursive). Raises EventIndexCleared (401) when `since` predates the
        retained window (reference event_history.go:58-105)."""
        if not self.events:
            if since > 0:
                return None
            return None
        if since < self.start_index:
            raise errors.EtcdError(
                errors.ECODE_EVENT_INDEX_CLEARED,
                cause=(f"the requested history has been cleared "
                       f"[{self.start_index}/{since}]"),
                index=self.last_index)
        for e in self.events:
            if e.index < since:
                continue
            ekey = e.node.key if e.node else ""
            if ekey == key:
                return e
            if recursive and ekey.startswith(key.rstrip("/") + "/"):
                return e
        return None

    def clone(self) -> "EventHistory":
        eh = EventHistory(self.capacity)
        eh.events = deque(self.events, maxlen=self.capacity)
        eh.start_index = self.start_index
        eh.last_index = self.last_index
        return eh


def ttl_of(expiration: Optional[float], now: float) -> int:
    """Remaining TTL in whole seconds, rounding up (reference
    node_extern.go loadInternalNode: Sub/Second + 1)."""
    if expiration is None:
        return 0
    return max(int(math.ceil(expiration - now)), 0)
