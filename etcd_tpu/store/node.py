"""Internal tree node of the v2 store (reference store/node.go:39-).

A node is either a file (value, no children) or a dir (children, no value).
Hidden nodes — last path component starting with "_" — are excluded from
dir listings but fully addressable directly.
"""
from __future__ import annotations

import posixpath
from typing import Callable, Dict, List, Optional

from etcd_tpu import errors
from etcd_tpu.store.event import NodeExtern, ttl_of


def key_name(path: str) -> str:
    return posixpath.basename(path.rstrip("/")) or "/"


def is_hidden_name(name: str) -> bool:
    return name.startswith("_")


class Node:
    __slots__ = ("path", "created_index", "modified_index", "parent", "value",
                 "children", "expire_time")

    def __init__(self, path: str, created_index: int, modified_index: int,
                 parent: Optional["Node"], value: Optional[str] = None,
                 is_dir: bool = False,
                 expire_time: Optional[float] = None) -> None:
        self.path = path
        self.created_index = created_index
        self.modified_index = modified_index
        self.parent = parent
        self.value = value if not is_dir else None
        self.children: Optional[Dict[str, "Node"]] = {} if is_dir else None
        self.expire_time = expire_time

    @property
    def is_dir(self) -> bool:
        return self.children is not None

    @property
    def is_permanent(self) -> bool:
        return self.expire_time is None

    @property
    def name(self) -> str:
        return key_name(self.path)

    def is_hidden(self) -> bool:
        return is_hidden_name(self.name)

    # -- file ops ------------------------------------------------------------

    def read(self) -> str:
        if self.is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_FILE, cause=self.path)
        return self.value or ""

    def write(self, value: str, index: int) -> None:
        if self.is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_FILE, cause=self.path)
        self.value = value
        self.modified_index = index

    # -- dir ops -------------------------------------------------------------

    def get_child(self, name: str) -> Optional["Node"]:
        if not self.is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_DIR, cause=self.path)
        return self.children.get(name)

    def add(self, child: "Node") -> None:
        if not self.is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_DIR, cause=self.path)
        name = child.name
        if name in self.children:
            raise errors.EtcdError(errors.ECODE_NODE_EXIST, cause=child.path)
        self.children[name] = child

    def list_children(self) -> List["Node"]:
        if not self.is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_FILE, cause=self.path)
        return list(self.children.values())

    def remove(self, is_dir: bool, recursive: bool,
               callback: Optional[Callable[[str], None]] = None) -> None:
        """Detach this node from its parent (reference node.go Remove):
        files remove directly; dirs require dir=True, and non-empty dirs
        require recursive=True."""
        if not self.is_dir:
            self._detach(callback)
            return
        if not is_dir:
            raise errors.EtcdError(errors.ECODE_NOT_FILE, cause=self.path)
        if not recursive and self.children:
            raise errors.EtcdError(errors.ECODE_DIR_NOT_EMPTY, cause=self.path)
        for child in list(self.children.values()):
            child.remove(True, True, callback)
        self._detach(callback)

    def _detach(self, callback: Optional[Callable[[str], None]]) -> None:
        if callback is not None:
            callback(self.path)
        if self.parent is not None and self.parent.children is not None:
            self.parent.children.pop(self.name, None)
        self.parent = None

    # -- external view -------------------------------------------------------

    def as_extern(self, now: float, recursive: bool = False,
                  want_sorted: bool = False,
                  materialize_children: bool = True) -> NodeExtern:
        ex = NodeExtern(
            key=self.path,
            dir=self.is_dir,
            created_index=self.created_index,
            modified_index=self.modified_index,
            expiration=self.expire_time,
            ttl=ttl_of(self.expire_time, now),
        )
        if not self.is_dir:
            ex.value = self.value or ""
            return ex
        if materialize_children:
            kids = [c for c in self.children.values() if not c.is_hidden()]
            if want_sorted:
                kids.sort(key=lambda n: n.path)
            ex.nodes = [
                c.as_extern(now, recursive, want_sorted,
                            materialize_children=recursive)
                for c in kids
            ]
        return ex

    # -- persistence ---------------------------------------------------------

    def to_json(self) -> dict:
        d: dict = {
            "path": self.path,
            "createdIndex": self.created_index,
            "modifiedIndex": self.modified_index,
        }
        if self.expire_time is not None:
            d["expireTime"] = self.expire_time
        if self.is_dir:
            d["dir"] = True
            d["children"] = [c.to_json() for c in self.children.values()]
        else:
            d["value"] = self.value or ""
        return d

    @staticmethod
    def from_json(d: dict, parent: Optional["Node"]) -> "Node":
        n = Node(
            path=d["path"],
            created_index=d["createdIndex"],
            modified_index=d["modifiedIndex"],
            parent=parent,
            value=d.get("value"),
            is_dir=bool(d.get("dir")),
            expire_time=d.get("expireTime"),
        )
        if n.is_dir:
            for cd in d.get("children", []):
                c = Node.from_json(cd, n)
                n.children[c.name] = c
        return n

    def clone(self, parent: Optional["Node"] = None) -> "Node":
        n = Node(self.path, self.created_index, self.modified_index, parent,
                 self.value, self.is_dir, self.expire_time)
        if self.is_dir:
            for name, c in self.children.items():
                n.children[name] = c.clone(n)
        return n
