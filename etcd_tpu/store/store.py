"""The v2 state machine: a hierarchical, TTL-aware, watchable key tree.

Behavioral equivalent of reference store/store.go:66-677 (+ ttl_key_heap.go,
stats.go): Get/Set/Create/CreateInOrder/Update/CompareAndSwap/Delete/
CompareAndDelete/Watch, min-heap TTL expiry driven by the leader's SYNC
command, per-op stats counters, and whole-tree JSON Save/Recovery/Clone for
snapshots. Applied commands are deterministic: expiry uses absolute
timestamps carried in the replicated request, never local wall-clock, so
every replica transitions identically.
"""
from __future__ import annotations

import heapq
import json
import posixpath
import threading
import time
from typing import Callable, List, Optional, Tuple

from etcd_tpu import errors
from etcd_tpu.store import event as ev
from etcd_tpu.store.event import Event, NodeExtern
from etcd_tpu.store.node import Node, is_hidden_name
from etcd_tpu.store.watcher import Watcher, WatcherHub


def normalize(p: str) -> str:
    p = posixpath.normpath("/" + (p or ""))
    # POSIX normpath preserves a leading "//" as special; collapse it.
    if p.startswith("//"):
        p = p[1:]
    return p


class TtlKeyHeap:
    """Min-heap of nodes by expire time (reference store/ttl_key_heap.go).
    Entries are invalidated lazily: a (time, path) pair is stale if the
    node at that path no longer exists or has a different expire time."""

    def __init__(self) -> None:
        self._heap: List[Tuple[float, str]] = []

    def push(self, n: Node) -> None:
        if n.expire_time is not None:
            heapq.heappush(self._heap, (n.expire_time, n.path))

    def top(self, resolve: Callable[[str], Optional[Node]]
            ) -> Optional[Node]:
        while self._heap:
            t, path = self._heap[0]
            n = resolve(path)
            if n is None or n.expire_time != t:
                heapq.heappop(self._heap)  # stale
                continue
            return n
        return None

    def pop(self) -> None:
        if self._heap:
            heapq.heappop(self._heap)


class Stats:
    """Mutation/read counters (reference store/stats.go JSON field names)."""

    FIELDS = ("getsSuccess", "getsFail", "setsSuccess", "setsFail",
              "createSuccess", "createFail", "updateSuccess", "updateFail",
              "deleteSuccess", "deleteFail",
              "compareAndSwapSuccess", "compareAndSwapFail",
              "compareAndDeleteSuccess", "compareAndDeleteFail",
              "expireCount", "watchers")

    def __init__(self) -> None:
        for f in self.FIELDS:
            setattr(self, f, 0)

    def inc(self, field: str) -> None:
        setattr(self, field, getattr(self, field) + 1)

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in self.FIELDS}

    def clone(self) -> "Stats":
        s = Stats()
        for f in self.FIELDS:
            setattr(s, f, getattr(self, f))
        return s


class Store:
    """One consistent v2 keyspace. Thread-safe: the apply loop mutates while
    HTTP handler threads read/watch (reference worldLock RWMutex)."""

    def __init__(self, history_capacity: int = ev.DEFAULT_HISTORY_CAPACITY,
                 clock: Callable[[], float] = time.time,
                 namespaces: tuple = ()) -> None:
        """namespaces: permanent top-level dirs pre-created at boot and
        write-protected along with "/" (reference store.go:85-96 newStore —
        the server passes "/0" and "/1")."""
        self._lock = threading.RLock()
        self.clock = clock
        self.root = Node("/", 0, 0, None, is_dir=True)
        self.current_index = 0
        self.watcher_hub = WatcherHub(history_capacity)
        self.ttl_heap = TtlKeyHeap()
        self.stats = Stats()
        self.namespaces = tuple(namespaces)
        self._readonly = frozenset(self.namespaces) | {"/"}
        for ns in self.namespaces:
            n = Node(ns, 0, 0, self.root, is_dir=True)
            self.root.children[ns.lstrip("/")] = n

    # -- reads ---------------------------------------------------------------

    def get(self, node_path: str, recursive: bool = False,
            want_sorted: bool = False) -> Event:
        node_path = normalize(node_path)
        with self._lock:
            try:
                n = self._walk(node_path)
            except errors.EtcdError:
                self.stats.inc("getsFail")
                raise
            e = Event(ev.GET, node=n.as_extern(self.clock(), recursive,
                                               want_sorted),
                      etcd_index=self.current_index)
            self.stats.inc("getsSuccess")
            return e

    def watch(self, key: str, recursive: bool = False, stream: bool = False,
              since_index: int = 0) -> Watcher:
        key = normalize(key)
        with self._lock:
            w = self.watcher_hub.watch(key, recursive, stream, since_index,
                                       self.current_index)
            self.stats.watchers = self.watcher_hub.count
            return w

    # -- mutations -----------------------------------------------------------

    def create(self, node_path: str, is_dir: bool = False,
               value: str = "", unique: bool = False,
               expire_time: Optional[float] = None) -> Event:
        """Create a new node; fails with 105 if it exists (reference
        store.go:120-150). unique=True appends a zero-padded in-order key
        named by the creation index (reference CreateInOrder)."""
        with self._lock:
            try:
                e = self._internal_create(node_path, is_dir, value, unique,
                                          replace=False,
                                          action=ev.CREATE,
                                          expire_time=expire_time)
                self.stats.inc("createSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("createFail")
                raise

    def set(self, node_path: str, is_dir: bool = False, value: str = "",
            expire_time: Optional[float] = None) -> Event:
        """Create-or-replace (reference store.go:152-206): replacing a file
        returns prevNode.

        This is the apply loop's hottest store op (every engine PUT lands
        here), so it is fused into ONE tree traversal with an in-place
        node rewrite on the file-replaces-file path — semantically
        identical to the reference's remove-then-create (createdIndex
        resets), without the detach/alloc/attach churn."""
        with self._lock:
            try:
                path = normalize(node_path)
                if path in self._readonly:
                    raise errors.EtcdError(errors.ECODE_ROOT_RONLY,
                                           cause="/",
                                           index=self.current_index)
                next_index = self.current_index + 1
                dirname, name = posixpath.split(path)
                parent = self._make_dirs(dirname, next_index)
                existing = parent.children.get(name)
                now = self.clock()
                prev_ex = None
                if existing is not None:
                    if existing.is_dir:
                        # set over a dir is not allowed (reference 102) —
                        # with OR without dir=True.
                        raise errors.EtcdError(errors.ECODE_NOT_FILE,
                                               cause=path,
                                               index=self.current_index)
                    prev_ex = existing.as_extern(
                        now, materialize_children=False)
                if existing is not None and not is_dir:
                    # In-place replace (the hot path): a SET is a brand-new
                    # node in reference semantics, so BOTH indices move.
                    n = existing
                    n.value = value
                    n.created_index = n.modified_index = next_index
                    n.expire_time = expire_time
                else:
                    if existing is not None:
                        existing.remove(False, False, None)
                    n = Node(path, next_index, next_index, parent,
                             value=None if is_dir else value, is_dir=is_dir,
                             expire_time=expire_time)
                    parent.add(n)
                self.ttl_heap.push(n)
                self.current_index = next_index
                e = Event(ev.SET,
                          node=n.as_extern(now,
                                           materialize_children=False),
                          prev_node=prev_ex, etcd_index=next_index)
                self.watcher_hub.notify(e)
                self.stats.inc("setsSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("setsFail")
                raise

    def set_applied(self, node_path: str, value: str,
                    expire_time: Optional[float],
                    need_event: bool) -> Optional[Event]:
        """PUT-set on the engine apply loop. The NativeStore skips Event
        materialization when nobody consumes it (need_event False and no
        watchers); the Python reference store is always eager."""
        return self.set(node_path, value=value, expire_time=expire_time)

    def update(self, node_path: str, value: Optional[str] = None,
               expire_time: Optional[float] = None,
               refresh: bool = False) -> Event:
        """Update an EXISTING node in place: value (files only) and/or TTL;
        createdIndex is preserved (reference store.go:208-260). With
        refresh=True only the TTL moves: the stored value is kept and
        watchers are NOT notified (documented v2 refresh semantics)."""
        node_path = normalize(node_path)
        with self._lock:
            try:
                if node_path in self._readonly:
                    raise errors.EtcdError(errors.ECODE_ROOT_RONLY,
                                           cause="/",
                                           index=self.current_index)
                n = self._walk(node_path)
                now = self.clock()
                prev_ex = n.as_extern(now, materialize_children=False)
                next_index = self.current_index + 1
                if n.is_dir and value:
                    raise errors.EtcdError(errors.ECODE_NOT_FILE,
                                           cause=node_path,
                                           index=self.current_index)
                if not n.is_dir:
                    if refresh:
                        n.modified_index = next_index  # value untouched
                    else:
                        n.write(value or "", next_index)
                else:
                    n.modified_index = next_index
                n.expire_time = expire_time
                self.ttl_heap.push(n)
                self.current_index = next_index
                e = Event(ev.UPDATE,
                          node=n.as_extern(now, materialize_children=False),
                          prev_node=prev_ex, etcd_index=self.current_index)
                if not refresh:
                    self.watcher_hub.notify(e)
                self.stats.inc("updateSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("updateFail")
                raise

    def compare_and_swap(self, node_path: str, prev_value: str,
                         prev_index: int, value: str,
                         expire_time: Optional[float] = None) -> Event:
        """Conditional write (reference store.go:262-319): conditions on
        prevValue and/or prevIndex; 101 on mismatch, 102 on dirs."""
        node_path = normalize(node_path)
        with self._lock:
            try:
                if node_path in self._readonly:
                    raise errors.EtcdError(errors.ECODE_ROOT_RONLY, cause="/",
                                           index=self.current_index)
                n = self._walk(node_path)
                if n.is_dir:
                    raise errors.EtcdError(errors.ECODE_NOT_FILE,
                                           cause=node_path,
                                           index=self.current_index)
                self._check_compare(n, prev_value, prev_index)
                now = self.clock()
                prev_ex = n.as_extern(now, materialize_children=False)
                next_index = self.current_index + 1
                n.write(value, next_index)
                n.expire_time = expire_time
                self.ttl_heap.push(n)
                self.current_index = next_index
                e = Event(ev.COMPARE_AND_SWAP,
                          node=n.as_extern(now, materialize_children=False),
                          prev_node=prev_ex, etcd_index=self.current_index)
                self.watcher_hub.notify(e)
                self.stats.inc("compareAndSwapSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("compareAndSwapFail")
                raise

    def delete(self, node_path: str, is_dir: bool = False,
               recursive: bool = False) -> Event:
        """Remove a node (reference store.go:321-361): dirs need dir=True
        (recursive implies dir), non-empty dirs need recursive."""
        node_path = normalize(node_path)
        with self._lock:
            try:
                if node_path in self._readonly:
                    raise errors.EtcdError(errors.ECODE_ROOT_RONLY, cause="/",
                                           index=self.current_index)
                if recursive:
                    is_dir = True
                n = self._walk(node_path)
                now = self.clock()
                prev_ex = n.as_extern(now, materialize_children=False)
                next_index = self.current_index + 1
                node_ex = NodeExtern(key=node_path, dir=n.is_dir,
                                     created_index=n.created_index,
                                     modified_index=next_index)
                e = Event(ev.DELETE, node=node_ex, prev_node=prev_ex)
                callback = (lambda path:
                            self.watcher_hub.notify_with_path(e, path, True))
                n.remove(is_dir, recursive, callback)
                self.current_index = next_index
                e.etcd_index = self.current_index
                self.watcher_hub.notify(e)
                self.stats.inc("deleteSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("deleteFail")
                raise

    def compare_and_delete(self, node_path: str, prev_value: str,
                           prev_index: int) -> Event:
        node_path = normalize(node_path)
        with self._lock:
            try:
                n = self._walk(node_path)
                if n.is_dir:
                    raise errors.EtcdError(errors.ECODE_NOT_FILE,
                                           cause=node_path,
                                           index=self.current_index)
                self._check_compare(n, prev_value, prev_index)
                now = self.clock()
                prev_ex = n.as_extern(now, materialize_children=False)
                next_index = self.current_index + 1
                node_ex = NodeExtern(key=node_path,
                                     created_index=n.created_index,
                                     modified_index=next_index)
                e = Event(ev.COMPARE_AND_DELETE, node=node_ex,
                          prev_node=prev_ex)
                n.remove(False, False, None)
                self.current_index = next_index
                e.etcd_index = self.current_index
                self.watcher_hub.notify(e)
                self.stats.inc("compareAndDeleteSuccess")
                return e
            except errors.EtcdError:
                self.stats.inc("compareAndDeleteFail")
                raise

    def delete_expired_keys(self, cutoff: float) -> List[Event]:
        """Pop and delete every node expired at `cutoff` — invoked when a
        replicated SYNC command applies, so all replicas expire identically
        (reference store.go DeleteExpiredKeys + server SYNC path
        etcdserver/server.go:667-681,813-815)."""
        out: List[Event] = []
        with self._lock:
            while True:
                n = self.ttl_heap.top(self._resolve)
                if n is None or n.expire_time > cutoff:
                    break
                self.ttl_heap.pop()
                self.current_index += 1
                prev_ex = n.as_extern(cutoff, materialize_children=False)
                node_ex = NodeExtern(key=n.path, dir=n.is_dir,
                                     created_index=n.created_index,
                                     modified_index=self.current_index)
                e = Event(ev.EXPIRE, node=node_ex, prev_node=prev_ex,
                          etcd_index=self.current_index)
                callback = (lambda path:
                            self.watcher_hub.notify_with_path(e, path, True))
                n.remove(True, True, callback)
                self.watcher_hub.notify(e)
                self.stats.inc("expireCount")
                out.append(e)
        return out

    # -- persistence ---------------------------------------------------------

    def save(self) -> bytes:
        """Whole-tree JSON for snapshots (reference store.go:628-644)."""
        with self._lock:
            return json.dumps({
                "version": 2,
                "currentIndex": self.current_index,
                "root": self.root.to_json(),
                "stats": self.stats.to_dict(),
            }).encode()

    def clone(self) -> "Store":
        """Deep copy for async snapshot marshal (reference store.go:646)."""
        with self._lock:
            s = Store(self.watcher_hub.event_history.capacity, self.clock,
                      namespaces=self.namespaces)
            s.root = self.root.clone(None)
            s.current_index = self.current_index
            s.stats = self.stats.clone()
            stack = [s.root]
            while stack:
                n = stack.pop()
                s.ttl_heap.push(n)
                if n.is_dir:
                    stack.extend(n.children.values())
            return s

    def recovery(self, data: bytes) -> None:
        """Replace state from a snapshot; live watchers are cleared
        (reference store.go:662-677, watcher clear per ECODE 400)."""
        d = json.loads(data.decode())
        with self._lock:
            self.root = Node.from_json(d["root"], None)
            self.current_index = d["currentIndex"]
            self.stats = Stats()
            for k, v in d.get("stats", {}).items():
                if k in Stats.FIELDS:
                    setattr(self.stats, k, v)
            self.ttl_heap = TtlKeyHeap()
            stack = [self.root]
            while stack:
                n = stack.pop()
                self.ttl_heap.push(n)
                if n.is_dir:
                    stack.extend(n.children.values())
            self.watcher_hub.clear()

    def has_ttl_keys(self) -> bool:
        """True if any node may expire — gates the leader's SYNC proposals."""
        with self._lock:
            return self.ttl_heap.top(self._resolve) is not None

    def next_expiration(self) -> Optional[float]:
        """Earliest live expire time, or None. The multi-tenant engine
        stages a SYNC only for tenants with a DUE expiry (the reference
        proposes SYNC unconditionally on a 500ms ticker,
        etcdserver/server.go:667-681 — per-cluster that's one no-op entry,
        across 100k tenant groups it would be 100k)."""
        with self._lock:
            n = self.ttl_heap.top(self._resolve)
            return None if n is None else n.expire_time

    def json_stats(self) -> dict:
        with self._lock:
            self.stats.watchers = self.watcher_hub.count
            return self.stats.to_dict()

    # -- internals -----------------------------------------------------------

    def _resolve(self, path: str) -> Optional[Node]:
        try:
            return self._walk(path)
        except errors.EtcdError:
            return None

    def _walk(self, node_path: str) -> Node:
        """Resolve an existing node or raise 100 (reference internalGet)."""
        parts = [p for p in normalize(node_path).split("/") if p]
        cur = self.root
        for p in parts:
            if not cur.is_dir:
                raise errors.EtcdError(errors.ECODE_KEY_NOT_FOUND,
                                       cause=node_path,
                                       index=self.current_index)
            nxt = cur.children.get(p)
            if nxt is None:
                raise errors.EtcdError(errors.ECODE_KEY_NOT_FOUND,
                                       cause=node_path,
                                       index=self.current_index)
            cur = nxt
        return cur

    def _check_compare(self, n: Node, prev_value: str,
                       prev_index: int) -> None:
        """Both given conditions must hold (reference node Compare)."""
        value_ok = (not prev_value) or (n.value == prev_value)
        index_ok = (prev_index == 0) or (n.modified_index == prev_index)
        if value_ok and index_ok:
            return
        # Only the failing clause(s) appear (reference getCompareFailCause,
        # store/store.go:196-206): index-only, value-only, or both.
        if value_ok:
            cause = f"[{prev_index} != {n.modified_index}]"
        elif index_ok:
            cause = f"[{prev_value} != {n.value or ''}]"
        else:
            cause = (f"[{prev_value} != {n.value or ''}] "
                     f"[{prev_index} != {n.modified_index}]")
        raise errors.EtcdError(errors.ECODE_TEST_FAILED, cause=cause,
                               index=self.current_index)

    def _internal_create(self, node_path: str, is_dir: bool, value: str,
                         unique: bool, replace: bool, action: str,
                         expire_time: Optional[float] = None) -> Event:
        next_index = self.current_index + 1
        if unique:
            node_path = posixpath.join(normalize(node_path),
                                       f"{next_index:020d}")
        node_path = normalize(node_path)
        if node_path in self._readonly:
            raise errors.EtcdError(errors.ECODE_ROOT_RONLY, cause="/",
                                   index=self.current_index)
        dirname, name = posixpath.split(node_path)
        parent = self._make_dirs(dirname, next_index)
        existing = parent.children.get(name)
        prev_ex = None
        if existing is not None:
            if not replace:
                raise errors.EtcdError(errors.ECODE_NODE_EXIST,
                                       cause=node_path,
                                       index=self.current_index)
            if existing.is_dir:
                # set over a dir is not allowed (reference 102).
                raise errors.EtcdError(errors.ECODE_NOT_FILE,
                                       cause=node_path,
                                       index=self.current_index)
            existing.remove(False, False, None)
        n = Node(node_path, next_index, next_index, parent,
                 value=None if is_dir else value, is_dir=is_dir,
                 expire_time=expire_time)
        parent.add(n)
        self.ttl_heap.push(n)
        self.current_index = next_index
        e = Event(action,
                  node=n.as_extern(self.clock(), materialize_children=False),
                  etcd_index=self.current_index)
        self.watcher_hub.notify(e)
        return e

    def _make_dirs(self, dirname: str, index: int) -> Node:
        """Walk to `dirname`, creating missing intermediate dirs (reference
        walk with checkDir): an existing FILE on the path is 104 NotDir.
        `dirname` must already be normalized (both callers split a
        normalized path)."""
        parts = [p for p in dirname.split("/") if p]
        cur = self.root
        for p in parts:
            nxt = cur.children.get(p)
            if nxt is None:
                nxt = Node(posixpath.join(cur.path, p), index, index, cur,
                           is_dir=True)
                cur.children[p] = nxt
            elif not nxt.is_dir:
                raise errors.EtcdError(errors.ECODE_NOT_DIR, cause=nxt.path,
                                       index=self.current_index)
            cur = nxt
        return cur
