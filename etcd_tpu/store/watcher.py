"""Watchers and the watcher hub (reference store/watcher.go,
store/watcher_hub.go:33-165).

Re-designed for the synchronous apply loop + threaded HTTP frontend: a
Watcher owns a thread-safe queue the HTTP handler blocks on (the reference's
one-slot event channel), and the hub fans mutations out along the key's
ancestor chain. Non-stream watchers detach after the first event; stream
watchers stay registered.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

from etcd_tpu import errors
from etcd_tpu.store.event import Event, EventHistory


def _is_hidden(watch_path: str, key_path: str) -> bool:
    """True if `key_path` has a hidden component strictly below `watch_path`
    (reference watcher_hub.go isHidden): such events are invisible to
    recursive watchers above, but an exact watcher on the hidden key fires."""
    if len(watch_path) > len(key_path):
        return False
    after = "/" + key_path[len(watch_path):].lstrip("/")
    return "/_" in after


class Watcher:
    def __init__(self, hub: "WatcherHub", path: str, recursive: bool,
                 stream: bool, since_index: int) -> None:
        self._hub = hub
        self.path = path
        self.recursive = recursive
        self.stream = stream
        self.since_index = since_index
        self.removed = False
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._last_index = -1  # dedup guard for the delete double-walk

    def next_event(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Block until the next event (None on timeout or after remove())."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def _notify(self, e: Event, original_path: bool, deleted: bool) -> bool:
        """Deliver if this watcher cares (reference watcher.go:36-61):
        recursive watchers take the subtree, exact watchers their own path,
        and a deleted dir force-notifies watchers beneath it. Returns True
        if the (non-stream) watcher is now spent."""
        if not (self.recursive or original_path or deleted):
            return False
        if e.index < self.since_index:
            return False
        if e.index == self._last_index:
            return False  # already delivered via the other walk
        self._last_index = e.index
        self._q.put(e)
        return not self.stream

    def remove(self) -> None:
        self._hub.remove(self)
        self._q.put(None)  # wake any blocked reader


class WatcherHub:
    def __init__(self, history_capacity: int = 1000) -> None:
        self._lock = threading.Lock()
        self._watchers: Dict[str, List[Watcher]] = {}
        self.event_history = EventHistory(history_capacity)
        self.count = 0  # live watcher count (reference atomic count)

    def watch(self, key: str, recursive: bool, stream: bool,
              since_index: int, current_index: int) -> Watcher:
        """Register a watcher; if `since_index` falls inside the history
        window and a matching event already happened, deliver it immediately
        (reference watcher_hub.go:55-109)."""
        w = Watcher(self, key, recursive, stream, since_index)
        w.start_index = current_index  # X-Etcd-Index for the watch response
        with self._lock:
            if since_index > 0:
                e = self.event_history.scan(key, recursive, since_index)
                if e is not None:
                    e.etcd_index = current_index
                    w._last_index = e.index
                    w._q.put(e)
                    if not stream:
                        return w  # spent before registration
            self._watchers.setdefault(key, []).append(w)
            self.count += 1
        return w

    def remove(self, w: Watcher) -> None:
        with self._lock:
            self._remove_locked(w)

    def _remove_locked(self, w: Watcher) -> None:
        if w.removed:
            return
        lst = self._watchers.get(w.path)
        if lst and w in lst:
            lst.remove(w)
            if not lst:
                del self._watchers[w.path]
            self.count -= 1
        w.removed = True

    def _record(self, e: Event) -> Event:
        """History hook: the native store's hub overrides this to a no-op
        (its C core appends the ring record inside the mutation op)."""
        return self.event_history.add(e)

    def notify(self, e: Event) -> None:
        """Record the event and fire watchers along the ancestor chain
        (reference watcher_hub.go:111-133)."""
        with self._lock:
            e = self._record(e)
            if self.count == 0:
                # History is recorded either way (wait-index queries need
                # it); with no watchers registered, skip the ancestor
                # walk — it's pure overhead on every apply (profiled at
                # ~20% of a multi-tenant engine apply).
                return
            key = e.node.key if e.node else "/"
            segments = [s for s in key.split("/") if s]
            curr = "/"
            self._notify_watchers_locked(e, curr, deleted=False)
            for seg in segments:  # "/a", "/a/b", ...
                curr = curr.rstrip("/") + "/" + seg
                self._notify_watchers_locked(e, curr, deleted=False)

    def notify_with_path(self, e: Event, path: str, deleted: bool) -> None:
        """Force-notify watchers at `path` (used for each node removed by a
        recursive delete — reference watcher_hub.go notifyWatchers(deleted))."""
        with self._lock:
            self._notify_watchers_locked(e, path, deleted)

    def _notify_watchers_locked(self, e: Event, node_path: str,
                                deleted: bool) -> None:
        lst = self._watchers.get(node_path)
        if not lst:
            return
        key = e.node.key if e.node else "/"
        for w in list(lst):
            original = key == node_path
            if not (original or not _is_hidden(node_path, key)):
                continue
            if w._notify(e, original, deleted):
                self._remove_locked(w)

    def clear(self) -> None:
        """Drop all watchers (store Recovery): each pending reader is woken
        with a WATCHER_CLEARED sentinel (reference ECODE 400 semantics)."""
        with self._lock:
            for lst in list(self._watchers.values()):
                for w in list(lst):
                    self._remove_locked(w)
                    w._q.put(None)
            self._watchers = {}
