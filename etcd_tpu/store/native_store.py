"""NativeStore: the v2 store backed by the C node-tree core.

Drop-in replacement for `store.Store` on the multi-tenant engine's apply
hot path (reference store/store.go:66-677): the tree, TTL heap, op stats
AND the event-history ring live in `etcd_tpu.native.storecore` (one C
call per op, atomic under the GIL), while watcher registration/fan-out
stays in the unchanged Python `WatcherHub`. The C ring retains the
descriptor tuples every mutation already builds, so `watch ?waitIndex=`
scans replay history without the store ever materializing Event objects
for writes nobody is waiting on — that is what `set_applied` (the engine
apply loop's entry point) exploits. Semantics are pinned by running the
full Python-store test matrix against this class plus a randomized
differential test (tests/test_native_store.py).

Why the split: profiling the engine apply loop showed ~13 µs/request
in-situ spent in the Python store (tree-walk dict churn, dataclass
allocs, lock/stat overhead, cache misses across thousands of tenant
stores); the C core cuts the per-op tree work to <1 µs and the facade
only pays for Event objects when the API contract actually needs them.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional

from etcd_tpu.store import event as ev
from etcd_tpu.store.event import Event, LazyWriteEvent, NodeExtern, ttl_of
from etcd_tpu.store.store import Stats, normalize
from etcd_tpu.store.watcher import Watcher, WatcherHub

from etcd_tpu.native.storecore import Core  # type: ignore

# Action strings indexed by the C core's ACT_* codes.
_ACTIONS = (ev.SET, ev.CREATE, ev.UPDATE, ev.COMPARE_AND_SWAP, ev.DELETE,
            ev.COMPARE_AND_DELETE, ev.EXPIRE)


def _norm(p: str) -> str:
    """normalize() with a fast path for already-canonical paths (the apply
    loop's keys are normalized at parse time; full posixpath.normpath costs
    ~1 µs — a third of the native op budget)."""
    if (p and p[0] == "/" and (len(p) == 1 or p[-1] != "/")
            and "//" not in p and "/./" not in p and "/../" not in p
            and not p.endswith("/.") and not p.endswith("/..")):
        return p
    return normalize(p)


def _extern(d, now: float) -> NodeExtern:
    """desc 6-tuple -> NodeExtern (no children)."""
    key, value, is_dir, created, modified, exp = d
    return NodeExtern(key, value, is_dir, None, created, modified, exp,
                      ttl_of(exp, now))


def _extern_tree(t, now: float) -> NodeExtern:
    """get() 7-tuple -> NodeExtern with materialized children."""
    key, value, is_dir, created, modified, exp, kids = t
    ex = NodeExtern(key, value, is_dir, None, created, modified, exp,
                    ttl_of(exp, now))
    if kids is not None:
        ex.nodes = [_extern_tree(k, now) for k in kids]
    return ex


def _ring_event(rec) -> Event:
    """C ring record -> Event (same shape the Python store retained)."""
    action, nd, pd, idx, now = rec
    act = _ACTIONS[action]
    node = _extern(nd, now)
    return Event(act, node=node,
                 prev_node=None if pd is None else _extern(pd, now),
                 etcd_index=idx)


class _CHistory:
    """EventHistory facade over the C ring (scan/bounds only: records are
    appended inside the C mutation ops)."""

    def __init__(self, core, capacity: int) -> None:
        self._core = core
        self.capacity = capacity

    @property
    def start_index(self) -> int:
        return self._core.ring_bounds()[0]

    @property
    def last_index(self) -> int:
        return self._core.ring_bounds()[1]

    def __len__(self) -> int:
        return self._core.ring_bounds()[2]

    def scan(self, key: str, recursive: bool, since: int):
        rec = self._core.scan(key, recursive, since)
        return None if rec is None else _ring_event(rec)


class _NativeHub(WatcherHub):
    """WatcherHub whose history lives in the C ring: the record was
    appended by the C op itself, so the history hook is a no-op and
    `notify` inherits only the ancestor-walk fan-out."""

    def __init__(self, core, history_capacity: int) -> None:
        super().__init__(history_capacity)
        self.event_history = _CHistory(core, history_capacity)

    def _record(self, e: Event) -> Event:
        return e

    def quiet(self) -> bool:
        """True iff no watcher is registered, read under the hub lock.
        Callers use this AFTER the C mutation: a watch() in progress
        either completes registration first (we see it and notify) or
        starts its history scan after our ring append (it replays the
        event) — either way nothing is lost. An unlocked count read
        could interleave between a watcher's scan and its registration
        and drop the event forever."""
        with self._lock:
            return self.count == 0


class _NativeStats(Stats):
    """Stats view over the C counters; `watchers` stays Python-side."""

    def __init__(self, core) -> None:
        self._core = core
        self.watchers = 0

    def __getattr__(self, name: str):
        try:
            i = Stats.FIELDS.index(name)
        except ValueError:
            raise AttributeError(name) from None
        return self._core.stats()[i]

    def inc(self, field: str) -> None:  # used by tests / aux paths only
        i = Stats.FIELDS.index(field)
        vals = list(self._core.stats())
        vals[i] += 1
        self._core.set_stats(tuple(vals))

    def to_dict(self) -> dict:
        vals = self._core.stats()
        d = dict(zip(Stats.FIELDS, vals))
        d["watchers"] = self.watchers
        return d


class NativeStore:
    """Same public surface as `store.Store` (reference store.Store iface
    store/store.go:40-64); see module docstring for the C/Python split."""

    def __init__(self, history_capacity: int = ev.DEFAULT_HISTORY_CAPACITY,
                 clock: Callable[[], float] = time.time,
                 namespaces: tuple = ()) -> None:
        self.clock = clock
        self.namespaces = tuple(namespaces)
        self._core = Core(namespaces=self.namespaces,
                          history_capacity=history_capacity)
        self.watcher_hub = _NativeHub(self._core, history_capacity)
        self.stats = _NativeStats(self._core)
        # compound (multi-C-call) ops only; single ops are GIL-atomic
        self._biglock = threading.RLock()

    # -- index ---------------------------------------------------------------

    @property
    def current_index(self) -> int:
        return self._core.index

    @current_index.setter
    def current_index(self, v: int) -> None:
        self._core.index = v

    # -- reads ---------------------------------------------------------------

    def get(self, node_path: str, recursive: bool = False,
            want_sorted: bool = False) -> Event:
        t, idx = self._core.get(_norm(node_path), recursive, want_sorted)
        return Event(ev.GET, node=_extern_tree(t, self.clock()),
                     etcd_index=idx)

    def watch(self, key: str, recursive: bool = False, stream: bool = False,
              since_index: int = 0) -> Watcher:
        key = _norm(key)
        w = self.watcher_hub.watch(key, recursive, stream, since_index,
                                   self._core.index)
        self.stats.watchers = self.watcher_hub.count
        return w

    # -- the engine apply fast path ------------------------------------------

    def set_applied(self, node_path: str, value: str,
                    expire_time: Optional[float],
                    need_event: bool) -> Optional[Event]:
        """PUT-set on the apply loop: history is recorded by the C op
        either way; the Event (2 NodeExterns + dataclass churn) is built
        only when a waiter needs the result or a watcher needs the
        fan-out. Returns None when skipped. Mutate FIRST, decide after:
        the skip check must not race watch registration (see
        _NativeHub.quiet)."""
        now = self.clock()
        nd, pd, idx = self._core.set(_norm(node_path), False, value,
                                     expire_time, now)
        hub = self.watcher_hub
        if not need_event and hub.quiet():
            return None
        e = Event(ev.SET, node=_extern(nd, now),
                  prev_node=None if pd is None else _extern(pd, now),
                  etcd_index=idx)
        hub.notify(e)
        return e

    def set_applied_lazy(self, node_path: str, value: str,
                         expire_time: Optional[float]):
        """set_applied for a WAITER-HELD plain PUT: same C mutation and
        ring append, but when no watcher is live the waiter gets the raw
        descriptors wrapped in a LazyWriteEvent — the Event/NodeExtern
        churn moves onto the HTTP thread that resolves it (do()). With a
        live watcher the Event is built here anyway (fan-out needs it)
        and returned directly; callers treat both shapes uniformly."""
        now = self.clock()
        nd, pd, idx = self._core.set(_norm(node_path), False, value,
                                     expire_time, now)
        hub = self.watcher_hub
        if hub.quiet():
            return LazyWriteEvent(nd, pd, idx, now)
        e = Event(ev.SET, node=_extern(nd, now),
                  prev_node=None if pd is None else _extern(pd, now),
                  etcd_index=idx)
        hub.notify(e)
        return e

    def set_applied_many(self, paths: List[str], values: List[str],
                         need: Optional[List[int]] = None):
        """Batched plain-file PUTs for the engine apply loop: ONE
        GIL-atomic C call applies the whole batch (per-op etcd errors fail
        that op exactly like the scalar call — stats counted, index
        unmoved — and the batch continues). History is recorded per op in
        the C ring. Callers guarantee no waiter needs a per-op result
        (those requests take set_applied).

        Watchers: if any is live BEFORE the mutation, the C call collects
        per-op records and every event is notified from them in order —
        O(n), and immune to a batch larger than the history ring evicting
        its own earliest records. A watcher that registers in the window
        between the check and the GIL-atomic C call is caught by the
        post-check and notified from the ring; if that same oversized
        batch already evicted part of its own span from the ring, a live
        stream watcher could otherwise miss the evicted events with no
        signal (the reference notifies per-op, so a registered watcher
        never misses; its 401 EventIndexCleared only covers NEW waitIndex
        registrations, store/event_history.go) — so in that corner the
        hub is cleared: every raced watcher wakes with the
        WATCHER_CLEARED sentinel and re-registers, and a stale waitIndex
        then gets the honest 401.

        `need`, when given, lists batch positions whose callers hold a
        waiter: the C call returns a desc entry per listed position —
        `(pos, nd, pd|None, index)` for an applied op, or
        `(pos, None, (code, cause), index_at_failure)` for a per-op etcd
        failure — and the return becomes `(applied, descs)` so the
        applier can wake each waiter with raw descriptors instead of a
        materialized Event. Without `need`, returns the number applied
        (unchanged contract)."""
        now = self.clock()
        hub = self.watcher_hub
        want_recs = not hub.quiet()
        # Inline canonical-path fast check: one "//" scan + one "." scan
        # (no dots rules out every "." / ".." segment form at once)
        # instead of a _norm() call per request — the call alone was
        # ~35% of this method's time at deep-queue load (1 M calls/s).
        norm = _norm
        first, last, failed, recs, descs = self._core.set_many(
            [p if (p and p[0] == "/" and p[-1] != "/" and "//" not in p
                   and "." not in p) else norm(p) for p in paths],
            values, now, want_recs, need)
        applied = len(paths) - failed
        if last < first:
            return applied if need is None else (applied, descs)
        if recs is not None:
            if not hub.quiet():
                for nd, pd, idx in recs:
                    hub.notify(Event(
                        ev.SET, node=_extern(nd, now),
                        prev_node=None if pd is None else _extern(pd, now),
                        etcd_index=idx))
        elif not hub.quiet():
            # Registration raced the atomic batch; replay what the ring
            # still holds (single pass over the clamped span).
            lo = max(first, self._core.ring_bounds()[0])
            if lo > first:
                # The batch evicted part of its own span: a stream
                # watcher that registered mid-batch would silently skip
                # the evicted events. Resync instead of lying: wake every
                # watcher with the cleared sentinel (store Recovery
                # semantics); re-registration with a stale waitIndex gets
                # 401 EventIndexCleared from the next scan.
                hub.clear()
                return applied if need is None else (applied, descs)
            scan = hub.event_history.scan
            for i in range(lo, last + 1):
                e = scan("/", True, i)
                if e is not None and e.etcd_index <= last:
                    hub.notify(e)
        return applied if need is None else (applied, descs)

    # -- mutations -----------------------------------------------------------

    def set(self, node_path: str, is_dir: bool = False, value: str = "",
            expire_time: Optional[float] = None) -> Event:
        now = self.clock()
        nd, pd, idx = self._core.set(_norm(node_path), is_dir, value,
                                     expire_time, now)
        e = Event(ev.SET, node=_extern(nd, now),
                  prev_node=None if pd is None else _extern(pd, now),
                  etcd_index=idx)
        self.watcher_hub.notify(e)
        return e

    def create(self, node_path: str, is_dir: bool = False,
               value: str = "", unique: bool = False,
               expire_time: Optional[float] = None) -> Event:
        path = _norm(node_path)
        if unique:
            # in-order key named by the creation index (CreateInOrder)
            path = f"{path.rstrip('/') or ''}/{self._core.index + 1:020d}"
        now = self.clock()
        nd, _, idx = self._core.create(path, is_dir, value, expire_time,
                                       now)
        e = Event(ev.CREATE, node=_extern(nd, now), etcd_index=idx)
        self.watcher_hub.notify(e)
        return e

    def update(self, node_path: str, value: Optional[str] = None,
               expire_time: Optional[float] = None,
               refresh: bool = False) -> Event:
        now = self.clock()
        nd, pd, idx = self._core.update(_norm(node_path), value, refresh,
                                        expire_time, now)
        e = Event(ev.UPDATE, node=_extern(nd, now),
                  prev_node=_extern(pd, now), etcd_index=idx)
        if not refresh:  # refresh moves only the TTL: watchers stay silent
            self.watcher_hub.notify(e)
        return e

    def compare_and_swap(self, node_path: str, prev_value: str,
                         prev_index: int, value: str,
                         expire_time: Optional[float] = None) -> Event:
        now = self.clock()
        nd, pd, idx = self._core.cas(_norm(node_path), prev_value,
                                     prev_index or 0, value, expire_time,
                                     now)
        e = Event(ev.COMPARE_AND_SWAP, node=_extern(nd, now),
                  prev_node=_extern(pd, now), etcd_index=idx)
        self.watcher_hub.notify(e)
        return e

    def delete(self, node_path: str, is_dir: bool = False,
               recursive: bool = False) -> Event:
        hub = self.watcher_hub
        now = self.clock()
        # removed paths are ALWAYS collected: deciding by a pre-mutation
        # watcher-count read races watch registration (a watcher on a
        # child path registered mid-delete would miss its deleted=True
        # force-notify with no ring record to replay it). Deletes are
        # rare next to sets; the collection cost is acceptable.
        (nd, pd, idx), removed = self._core.delete(
            _norm(node_path), is_dir, recursive, True, now)
        key, _, was_dir, created, modified, _ = nd
        node_ex = NodeExtern(key=key, dir=was_dir, created_index=created,
                             modified_index=modified)
        e = Event(ev.DELETE, node=node_ex, prev_node=_extern(pd, now))
        e.etcd_index = idx
        if not hub.quiet():
            # per-removed-node force-notify (watcher_hub notifyWatchers
            # deleted=True); dedup in Watcher handles the double walk
            for path in removed:
                hub.notify_with_path(e, path, True)
        hub.notify(e)
        return e

    def compare_and_delete(self, node_path: str, prev_value: str,
                           prev_index: int) -> Event:
        now = self.clock()
        nd, pd, idx = self._core.cad(_norm(node_path), prev_value,
                                     prev_index or 0, now)
        key, _, _, created, modified, _ = nd
        node_ex = NodeExtern(key=key, created_index=created,
                             modified_index=modified)
        e = Event(ev.COMPARE_AND_DELETE, node=node_ex,
                  prev_node=_extern(pd, now))
        e.etcd_index = idx
        self.watcher_hub.notify(e)
        return e

    def delete_expired_keys(self, cutoff: float) -> List[Event]:
        out: List[Event] = []
        hub = self.watcher_hub
        for nd, pd, removed, idx in self._core.expire_keys(cutoff):
            key, _, was_dir, created, modified, _ = nd
            node_ex = NodeExtern(key=key, dir=was_dir, created_index=created,
                                 modified_index=modified)
            e = Event(ev.EXPIRE, node=node_ex,
                      prev_node=_extern(pd, cutoff), etcd_index=idx)
            if not hub.quiet():  # post-mutation check (see delete())
                for path in removed:
                    hub.notify_with_path(e, path, True)
            hub.notify(e)
            out.append(e)
        return out

    # -- persistence ---------------------------------------------------------

    def save(self) -> bytes:
        with self._biglock:
            return json.dumps({
                "version": 2,
                "currentIndex": self._core.index,
                "root": _json_of(self._core.dump()),
                "stats": self.stats.to_dict(),
            }).encode()

    def clone(self) -> "NativeStore":
        with self._biglock:
            s = NativeStore(self.watcher_hub.event_history.capacity,
                            self.clock, namespaces=self.namespaces)
            s._core = self._core.clone()
            s.stats = _NativeStats(s._core)
            s.watcher_hub = _NativeHub(
                s._core, self.watcher_hub.event_history.capacity)
            return s

    def recovery(self, data: bytes) -> None:
        d = json.loads(data.decode())
        with self._biglock:
            self._core.load(_tuple_of(d["root"]))
            self._core.index = d["currentIndex"]
            vals = [0] * len(Stats.FIELDS)
            for k, v in d.get("stats", {}).items():
                if k in Stats.FIELDS:
                    vals[Stats.FIELDS.index(k)] = v
            self._core.set_stats(tuple(vals))
            self.watcher_hub.clear()

    def has_ttl_keys(self) -> bool:
        return self._core.next_expiration() is not None

    def next_expiration(self) -> Optional[float]:
        return self._core.next_expiration()

    def json_stats(self) -> dict:
        self.stats.watchers = self.watcher_hub.count
        return self.stats.to_dict()


def _json_of(t) -> dict:
    """dump() 7-tuple -> the snapshot JSON shape (node.py to_json —
    identical key order so save() bytes match the Python store's)."""
    key, value, is_dir, created, modified, exp, kids = t
    d: dict = {"path": key, "createdIndex": created,
               "modifiedIndex": modified}
    if exp is not None:
        d["expireTime"] = exp
    if is_dir:
        d["dir"] = True
        d["children"] = [_json_of(k) for k in kids]
    else:
        d["value"] = value or ""
    return d


def _tuple_of(d: dict):
    """snapshot JSON node -> load() 7-tuple."""
    is_dir = bool(d.get("dir"))
    kids = (tuple(_tuple_of(c) for c in d.get("children", []))
            if is_dir else None)
    return (d["path"], None if is_dir else (d.get("value") or ""),
            is_dir, d["createdIndex"], d["modifiedIndex"],
            d.get("expireTime"), kids)
