import os as _os

from etcd_tpu.store.event import (Event, EventHistory, NodeExtern, GET, CREATE,
                                  SET, UPDATE, DELETE, COMPARE_AND_SWAP,
                                  COMPARE_AND_DELETE, EXPIRE)
from etcd_tpu.store.store import Store
from etcd_tpu.store.watcher import Watcher, WatcherHub

try:
    if _os.environ.get("ETCD_TPU_PYSTORE") == "1":
        raise ImportError("forced Python store")
    from etcd_tpu.store.native_store import NativeStore
    HAVE_NATIVE_STORE = True
except ImportError:
    NativeStore = None  # type: ignore[assignment,misc]
    HAVE_NATIVE_STORE = False


def new_store(history_capacity=None, clock=None, namespaces=()):
    """Store factory: the C-core NativeStore when `./build` has compiled
    it (the engine apply hot path — see native_store.py), else the pure
    Python reference implementation. ETCD_TPU_PYSTORE=1 forces Python."""
    import time

    from etcd_tpu.store import event as _ev
    cls = NativeStore if HAVE_NATIVE_STORE else Store
    return cls(history_capacity or _ev.DEFAULT_HISTORY_CAPACITY,
               clock or time.time, namespaces=namespaces)


__all__ = ["Store", "NativeStore", "HAVE_NATIVE_STORE", "new_store", "Event",
           "EventHistory", "NodeExtern", "Watcher", "WatcherHub", "GET",
           "CREATE", "SET", "UPDATE", "DELETE", "COMPARE_AND_SWAP",
           "COMPARE_AND_DELETE", "EXPIRE"]
