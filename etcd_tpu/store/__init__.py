from etcd_tpu.store.event import (Event, EventHistory, NodeExtern, GET, CREATE,
                                  SET, UPDATE, DELETE, COMPARE_AND_SWAP,
                                  COMPARE_AND_DELETE, EXPIRE)
from etcd_tpu.store.store import Store
from etcd_tpu.store.watcher import Watcher, WatcherHub

__all__ = ["Store", "Event", "EventHistory", "NodeExtern", "Watcher",
           "WatcherHub", "GET", "CREATE", "SET", "UPDATE", "DELETE",
           "COMPARE_AND_SWAP", "COMPARE_AND_DELETE", "EXPIRE"]
