"""Pallas TPU kernels for the batched consensus step's hottest op.

SURVEY §7 scopes Pallas as conditional: "Pallas kernels only if XLA
fusion is insufficient". Profiling on a real v5e chip showed the original
bottleneck (take_along_axis gathers, ~55% of a round) was eliminated by
reformulating ring reads as one-hot select-sums, which XLA fuses well —
so the jnp path remains the default. This module provides the same op as
an explicit Pallas kernel so the choice can be re-measured per backend
(scripts/pallas_bench.py) rather than assumed:

    ring_resolve(ring, idx): ring (G, P, W) terms, idx (G, P, T, E)
    absolute entry indices -> (G, P, T, E) terms, 0 outside each
    (g, p) row's window — the send-assembly / conflict-scan resolve
    (kernel.py _terms_at_many + the broadcast variant).

The kernel tiles the fused (G*P, T*E) problem over a grid of row blocks,
holding each block's ring rows (BR, W) and index rows (BR, TE) in VMEM
and computing the masked one-hot contraction in one pass — no HBM
intermediates regardless of how XLA would schedule the jnp version.

This module is a MEASURED-AND-REJECTED candidate, kept as the harness
for any future re-measurement: on real TPU v5 lite (2026-07-31,
G=100k P=5 W=16 E=4) the isolated op wins 2.3x over the jnp one-hot
path (0.022 ms vs 0.051 ms, scripts/pallas_bench.py), but wired into
`_terms_at_many` of the full hops=3 kernel round it LOSES 9.3x
(165.6 ms/round vs 17.7 ms, scripts/pallas_roundbench.py): the
pallas_call boundary blocks XLA from fusing the resolve into the
surrounding message-assembly ops, so every call site pays HBM
round-trips for operands the fused program never materializes. The
jnp path stays production; do not give this a call site without
beating scripts/pallas_roundbench.py first. On CPU it runs in
interpret mode (tests pin its windowed-resolve semantics).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _resolve_block(ring_ref, idx_ref, last_ref, out_ref, *, W: int):
    ring = ring_ref[...]          # (BR, W)
    idx = idx_ref[...]            # (BR, TE)
    last = last_ref[...]          # (BR, 1)
    # One-hot contraction over the ring axis: slot = idx mod W. The
    # scalar W is pinned to int32 where it meets arrays (x64 configs
    # would promote the Python int to int64) but stays a Python int in
    # shapes.
    w32 = jnp.int32(W)
    slot = jax.lax.rem(idx, w32)
    onehot = (slot[:, :, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (1, 1, W), 2))
    vals = jnp.sum(ring[:, None, :] * onehot.astype(jnp.int32), axis=2,
                   dtype=jnp.int32)
    in_win = (idx > last - w32) & (idx <= last) & (idx >= 1)
    out_ref[...] = jnp.where(in_win, vals, 0)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def ring_resolve(ring: jax.Array, idx: jax.Array, last: jax.Array,
                 block_rows: int = 512,
                 interpret: bool | None = None) -> jax.Array:
    """Pallas version of the windowed ring term resolve.

    ring: (G, P, W) int32 entry terms (entry i at slot i % W)
    idx:  (G, P, *T) int32 absolute indices (any trailing shape)
    last: (G, P) int32 last_index per row
    returns idx-shaped int32 terms; 0 for out-of-window / index < 1.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    G, P, W = ring.shape
    trailing = idx.shape[2:]
    R = G * P
    TE = 1
    for d in trailing:
        TE *= d
    ring2 = ring.reshape(R, W)
    idx2 = idx.reshape(R, TE)
    last2 = last.reshape(R, 1)

    BR = min(block_rows, R)
    # Pad rows to a multiple of the block.
    pad = (-R) % BR
    if pad:
        ring2 = jnp.pad(ring2, ((0, pad), (0, 0)))
        idx2 = jnp.pad(idx2, ((0, pad), (0, 0)))
        last2 = jnp.pad(last2, ((0, pad), (0, 0)))
    Rp = R + pad

    out = pl.pallas_call(
        functools.partial(_resolve_block, W=W),
        grid=(Rp // BR,),
        in_specs=[
            pl.BlockSpec((BR, W), lambda i: (i, 0)),
            pl.BlockSpec((BR, TE), lambda i: (i, 0)),
            pl.BlockSpec((BR, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BR, TE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Rp, TE), jnp.int32),
        interpret=interpret,
    )(ring2, idx2, last2)
    return out[:R].reshape((G, P) + trailing)
