"""Dense struct-of-arrays state for the batched consensus kernel.

This is the TPU-native re-expression of the reference's per-goroutine state
(raft struct raft/raft.go:125-155, Progress map raft/progress.go:37-67,
raftLog raft/log.go:24-39): G groups × P peer slots stepped as ONE XLA
program. Layout conventions:

- Arrays are shaped (G, P, ...) — group axis first (shardable over the mesh
  "groups" axis), peer-slot axis second (local in single-host mode, sharded
  over the mesh "peers" axis in the distributed deployment).
- Peer slots are 0-based; `vote`/`lead` fields store slot+1 with 0 = none
  (mirroring the reference's None=0 node id convention).
- The on-device log is a fixed ring of entry TERMS addressed by absolute
  index modulo WINDOW (entry i lives at slot i % W); entry payloads never
  touch the device — they stay in the host log store (the msgappv2 insight,
  reference rafthttp/msgappv2.go:29-63: the hot path is index bookkeeping).
- All state is int32 (uint32 for the xorshift PRNG lanes); indices are
  int32 which bounds a single group's log index at 2^31 — compaction keeps
  real indices far below this.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Roles (shared with etcd_tpu.raftpb.StateType).
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# Progress states (shared with etcd_tpu.raft.progress.ProgressState);
# SNAPSHOT transfers are host-side, so the device only tracks probe/replicate.
PR_PROBE, PR_REPLICATE = 0, 1

# Kernel message types (dense codes; NONE=0 means empty slot).
M_NONE, M_APP, M_APP_RESP, M_VOTE, M_VOTE_RESP, M_HB, M_HB_RESP = range(7)

# need_host bitmask values (see GroupState.need_host).
NH_SNAP = 1        # lagging peer: entries fell below the device ring window;
                   # host must ship a snapshot / resolve the append
NH_VIOLATION = 2   # conflict at/below commit: a PROTOCOL VIOLATION (the
                   # reference panics in log.maybeAppend) — the host engine
                   # must dump state and fail loudly, never paper over it

# Message field offsets in the last axis of inbox/outbox arrays.
F_TYPE, F_TERM, F_INDEX, F_LOGTERM, F_COMMIT, F_REJECT, F_HINT, F_NENT = range(8)
N_FIXED_FIELDS = 8


class KernelConfig(NamedTuple):
    """Static (compile-time) parameters of the batched kernel."""

    groups: int            # G
    peers: int             # P: padded peer-slot count (>= max group size)
    window: int = 16       # W: on-device log ring length (uncommitted tail cap)
    max_ents: int = 4      # E: max entries per append message
    election_tick: int = 10
    heartbeat_tick: int = 1
    # Max un-acked entries per follower before replication pauses
    # (entries-in-flight redesign of the reference inflights ring,
    # progress.go:172-237). 0 = derive window//2, so the pause always
    # engages BEFORE a silent follower's needed entries can fall off the
    # on-device log ring.
    flow_window: int = 0

    @property
    def fields(self) -> int:
        return N_FIXED_FIELDS + self.max_ents

    @property
    def effective_flow_window(self) -> int:
        return self.flow_window if self.flow_window > 0 else self.window // 2


class GroupState(NamedTuple):
    """SoA consensus state; a JAX pytree. Shapes in comments use
    G=groups, P=peer slots, W=window, E=max_ents."""

    # Per-instance HardState/SoftState (reference raftpb HardState +
    # raft.lead/state):
    term: jax.Array          # (G, P) int32
    vote: jax.Array          # (G, P) int32, slot+1, 0 = none
    commit: jax.Array        # (G, P) int32
    lead: jax.Array          # (G, P) int32, slot+1, 0 = none
    state: jax.Array         # (G, P) int32 in {FOLLOWER, CANDIDATE, LEADER}

    # Tick machinery (reference raft.go:149-152,765-771):
    elapsed: jax.Array       # (G, P) int32
    prng: jax.Array          # (G, P) uint32 xorshift32 lanes

    # On-device log: ring of entry terms + cursors (reference raftLog):
    log_term: jax.Array      # (G, P, W) int32; entry i at slot i % W
    last_index: jax.Array    # (G, P) int32

    # Leader replication state, per target slot (reference Progress):
    match: jax.Array         # (G, P, P) int32
    next: jax.Array          # (G, P, P) int32
    pr_state: jax.Array      # (G, P, P) int32 in {PR_PROBE, PR_REPLICATE}
    paused: jax.Array        # (G, P, P) bool (probe in-flight pause)
    # Rounds since the last append response from each target — the staleness
    # signal behind heartbeat-response retransmission (the dense form of the
    # reference's MsgHeartbeatResp -> sendAppend liveness rule,
    # raft.go:547-551).
    ack_age: jax.Array       # (G, P, P) int32

    # Candidate vote tally (reference raft.votes): 0 unknown / 1 granted /
    # 2 rejected, per voter slot:
    votes: jax.Array         # (G, P, P) int32

    # Membership: which peer slots are live. A device-side ConfChange is a
    # bit flip here (add = set a free slot, remove = clear it — the removed
    # slot's rows go inert, no compaction), applied by the host engine at a
    # committed boundary (reference multinode.go:181-218 CreateGroup/
    # RemoveGroup + raft.go:709-744 addNode/removeNode).
    peer_mask: jax.Array     # (G, P) bool

    # Host-escape flags: NH_* bitmask — why this instance needs the host
    # slow path (snapshot send, append below the device window) or, worse,
    # detected a safety violation (NH_VIOLATION).
    need_host: jax.Array     # (G, P) int32 bitmask of NH_*


def _seed(groups: int, peers: int) -> np.ndarray:
    """Per-(group, slot) xorshift32 seeds, identical to the scalar oracle's
    prng_seed(group, node_id=slot+1) (etcd_tpu/raft/core.py)."""
    g = np.arange(groups, dtype=np.uint64)[:, None]
    p = np.arange(1, peers + 1, dtype=np.uint64)[None, :]
    s = (g * np.uint64(0x9E3779B9) + p * np.uint64(0x85EBCA6B) + np.uint64(1))
    s = (s & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    s[s == 0] = 1
    return s


def init_state(cfg: KernelConfig, n_peers=None,
               stagger: bool = False) -> GroupState:
    """Fresh-boot state: every instance a follower at term 0 with an empty
    log. `n_peers` may be an int (uniform group size) or a (G,) array.

    `stagger=True` pre-ages exactly one instance per group (slot g mod n)
    past its election timeout so it campaigns on the FIRST tick and wins
    uncontested ~3 rounds later — the deterministic fast-boot the reference
    gets probabilistically from randomized timeouts (raft.go:765-771).
    Benchmarks and the multichip dryrun use this to reach steady state in
    O(1) rounds instead of O(election_tick) with tie retries."""
    G, P = cfg.groups, cfg.peers
    if n_peers is None:
        n_peers = P
    n_peers_np = np.broadcast_to(np.asarray(n_peers, np.int32), (G,))
    mask0 = np.arange(P, dtype=np.int32)[None, :] < n_peers_np[:, None]
    elapsed0 = np.zeros((G, P), np.int32)
    if stagger:
        g = np.arange(G)
        # Guard mod-by-zero: groups with n_peers == 0 are unprovisioned
        # pool slots (engine tenant lifecycle) — no staggered campaigner.
        slot = (g % np.maximum(n_peers_np, 1)).astype(np.int64)
        # After the first tick, d = 2*tick+1 - tick = tick+1 > any draw in
        # [0, tick-1] -> guaranteed immediate campaign (see kernel._tick).
        elapsed0[g, slot] = np.where(n_peers_np > 0,
                                     2 * cfg.election_tick, 0)

    # Each field gets its OWN buffer: step() donates the whole state pytree,
    # and XLA rejects donating one buffer twice.
    def zeros_gp():
        return jnp.zeros((G, P), jnp.int32)

    def zeros_gpp():
        return jnp.zeros((G, P, P), jnp.int32)

    return GroupState(
        term=zeros_gp(),
        vote=zeros_gp(),
        commit=zeros_gp(),
        lead=zeros_gp(),
        state=zeros_gp(),
        elapsed=jnp.asarray(elapsed0),
        prng=jnp.asarray(_seed(G, P)),
        log_term=jnp.zeros((G, P, cfg.window), jnp.int32),
        last_index=zeros_gp(),
        match=zeros_gpp(),
        next=jnp.ones((G, P, P), jnp.int32),
        pr_state=zeros_gpp(),
        paused=jnp.zeros((G, P, P), bool),
        ack_age=zeros_gpp(),
        votes=zeros_gpp(),
        peer_mask=jnp.asarray(mask0),
        need_host=jnp.zeros((G, P), jnp.int32),
    )


def active_mask(st: GroupState) -> jax.Array:
    """(G, P) bool: which peer slots exist."""
    return st.peer_mask


def quorum(st: GroupState) -> jax.Array:
    """(G,) int32: n//2 + 1 (reference raft.go:215)."""
    return jnp.sum(st.peer_mask.astype(jnp.int32), axis=1) // 2 + 1


def ring_lookup(ring: jax.Array, slot: jax.Array) -> jax.Array:
    """ring[..., W] indexed at slot[..., K] -> [..., K]. Backend-dispatched
    at trace time:

    - TPU: one-hot select-sum over the W axis — compiles to a fused
      broadcast-multiply-reduce on the vector unit; the equivalent
      take_along_axis gather lowers to serialized dynamic slices and
      dominated the whole kernel's round time (profiled: the two ring
      gathers were ~55% of a step at G=100k).
    - CPU (and other backends): take_along_axis — the one-hot form
      materializes an extra (..., K, W) intermediate (104MB at the G=4096
      bench shape in send assembly alone) that a CPU gather avoids.

    Both are elementwise-exact; the trajectory tests drive them against
    the same oracle."""
    if jax.default_backend() == "tpu":
        W = ring.shape[-1]
        iota = jnp.arange(W, dtype=slot.dtype)
        onehot = (slot[..., None] == iota).astype(ring.dtype)
        # dtype pinned: under x64 configs jnp.sum promotes int32 -> int64.
        return jnp.sum(ring[..., None, :] * onehot, axis=-1,
                       dtype=ring.dtype)
    shape = jnp.broadcast_shapes(ring.shape[:-1], slot.shape[:-1])
    ring_b = jnp.broadcast_to(ring, shape + ring.shape[-1:])
    slot_b = jnp.broadcast_to(slot, shape + slot.shape[-1:])
    return jnp.take_along_axis(ring_b, slot_b, axis=-1)


def term_at(st: GroupState, cfg: KernelConfig, index: jax.Array) -> jax.Array:
    """Term of entry `index` per instance; 0 for index 0 (the empty-log
    sentinel) and for indices outside the device window (callers must treat
    out-of-window as escape-to-host where it matters).

    index: (G, P) absolute entry indices. Returns (G, P) int32.
    """
    slot = jnp.mod(index, cfg.window)
    t = ring_lookup(st.log_term, slot[..., None])[..., 0]
    in_window = (index > st.last_index - cfg.window) & (index <= st.last_index)
    valid = in_window & (index >= 1)
    return jnp.where(valid, t, 0)


def in_window(st: GroupState, cfg: KernelConfig, index: jax.Array) -> jax.Array:
    """bool mask: entry `index` is resolvable on device (or is index 0).
    `index` may be (G, P) or carry extra trailing axes ((G, P, K))."""
    last = st.last_index
    while last.ndim < index.ndim:
        last = last[..., None]
    return ((index > last - cfg.window) & (index <= last)) | (index == 0)


def xorshift32(x: jax.Array) -> jax.Array:
    """Vectorized Marsaglia xorshift32, bit-identical to the scalar oracle
    (etcd_tpu/raft/core.py xorshift32)."""
    x = x ^ (x << 13)
    x = x ^ (x >> 17)
    x = x ^ (x << 5)
    return x
