"""The batched consensus kernel: G Raft groups × P peer slots stepped as one
XLA program.

This replaces the reference's per-group goroutine loops (raft.MultiNode,
raft/multinode.go:166-322 — including the O(groups) tick scan flagged at
multinode.go:265-267) with dense array transforms:

- tick scan            -> vectorized elapsed/timeout update over (G, P)
- Step(m) per message  -> masked updates, one unrolled pass per sender slot
- maybeCommit sort     -> lax.top_k over the peers axis (raft/raft.go:323-332)
- bcastAppend/sendAppend -> gap-driven send assembly over the (G, P, P)
                            progress matrix (raft/raft.go:239-321)
- message routing      -> a transpose of the (G, P_from, P_to) outbox
                          (single host) or an all_to_all over the "peers"
                          mesh axis (distributed; etcd_tpu/parallel)

Design rules (why this diverges from a line-for-line port):
1. Message LOSS is always legal in Raft, so the dense mailbox keeps exactly
   one slot per (sender, target) pair and drops lower-priority collisions
   (response > append > heartbeat > vote) — the protocol retries via
   timeouts. This is what makes the mailbox a fixed-shape tensor.
2. Sends are gap-driven rather than event-driven: at the end of each step a
   leader emits an append to any unpaused follower whose `next` lags. This
   subsumes the reference's bcast-on-propose / send-on-ack triggers and
   needs no per-event control flow.
3. Rare/heavy transitions (snapshot install+send, conf change application,
   appends below the device log window) escape to the host scalar oracle
   (etcd_tpu/raft/core.py) via `need_host` flags; the hot path stays static.
4. Flow control is entries-in-flight (`next-1-match >= flow_window`) instead
   of the reference's message-count ring (progress.go:172-237): with one
   coalesced append per (peer, round), window-by-entries is the natural
   dense form.

Election timing is bit-identical to the scalar oracle: same xorshift32
streams, same draw points (reference raft.go:765-771 semantics).
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple


def _donate_at_import(argnums):
    """donate_argnums for the module-level jitted steps, decided at
    IMPORT time: XLA:CPU has a donated-buffer race (see "CPU donation
    hazard" below), so when the process has already pinned a non-TPU
    platform via JAX_PLATFORMS (the test suite, ./test, CI, Procfile
    all export cpu) the decorators skip donation entirely — this is
    what keeps kernel-direct tests (and the whole shared pytest heap)
    safe. When JAX_PLATFORMS is unset the platform isn't knowable
    without initializing the backend (illegal at import: multihost
    scripts set distributed state after importing this module), so the
    decorators keep donation and serving engines re-decide per live
    backend via step_variant()/donate_safe(). ETCD_TPU_DONATE=on|off
    overrides both layers."""
    mode = os.environ.get("ETCD_TPU_DONATE", "auto")
    if mode in ("on", "1"):
        return tuple(argnums)
    if mode in ("off", "0"):
        return ()
    plats = os.environ.get("JAX_PLATFORMS", "").lower()
    if plats and "tpu" not in plats and "axon" not in plats:
        return ()
    return tuple(argnums)

import jax
import jax.numpy as jnp

from etcd_tpu.ops.state import (CANDIDATE, FOLLOWER, F_COMMIT, F_HINT,
                                F_INDEX, F_LOGTERM, F_NENT, F_REJECT, F_TERM,
                                F_TYPE, GroupState, KernelConfig, LEADER,
                                M_APP, M_APP_RESP, M_HB, M_HB_RESP, M_NONE,
                                M_VOTE, M_VOTE_RESP, N_FIXED_FIELDS,
                                NH_SNAP, NH_VIOLATION, PR_PROBE, PR_REPLICATE,
                                active_mask, in_window, quorum, ring_lookup,
                                term_at, xorshift32)


def _flag(need_host: jax.Array, mask: jax.Array, bit: int) -> jax.Array:
    """OR an NH_* bit into the (G, P) need_host bitmask where mask holds."""
    return need_host | jnp.where(mask, jnp.int32(bit), 0)


def _where(m, a, b):
    return jnp.where(m, a, b)


def _last_term(st: GroupState, cfg: KernelConfig) -> jax.Array:
    return term_at(st, cfg, st.last_index)


def _set_self_progress(st: GroupState) -> GroupState:
    """Leader's own match tracks its last index (reference appendEntry ->
    prs[self].maybeUpdate)."""
    G, P = st.term.shape
    eye = jnp.eye(P, dtype=bool)[None, :, :]
    is_ldr = (st.state == LEADER)[..., None]
    match = _where(eye & is_ldr, st.last_index[..., None], st.match)
    nxt = _where(eye & is_ldr, st.last_index[..., None] + 1, st.next)
    return st._replace(match=match, next=nxt)


def _become_follower(st: GroupState, mask: jax.Array, new_term: jax.Array,
                     new_lead: jax.Array) -> GroupState:
    """Masked becomeFollower(term, lead) (reference raft.go:384-391 +
    reset()); vote cleared only when the term actually changes."""
    term_changed = mask & (new_term != st.term)
    return st._replace(
        term=_where(mask, new_term, st.term),
        vote=_where(term_changed, 0, st.vote),
        lead=_where(mask, new_lead, st.lead),
        state=_where(mask, FOLLOWER, st.state),
        elapsed=_where(mask, 0, st.elapsed),
        votes=_where(mask[..., None], 0, st.votes),
    )


def _append_noop_and_lead(st: GroupState, cfg: KernelConfig,
                          win: jax.Array) -> GroupState:
    """Masked becomeLeader: reset progress, append the no-op entry of the new
    term (reference raft.go:406-427)."""
    G, P = st.term.shape
    new_last = st.last_index + 1
    # The no-op entry of the new term, via the shared ring-write primitive.
    st = _write_terms(st, cfg, anchor=st.last_index,
                      terms=st.term[..., None], lo=new_last,
                      count=win.astype(jnp.int32), mask=win)
    st = st._replace(
        state=_where(win, LEADER, st.state),
        lead=_where(win, jnp.arange(1, P + 1, dtype=jnp.int32)[None, :],
                    st.lead),
        elapsed=_where(win, 0, st.elapsed),
        last_index=_where(win, new_last, st.last_index),
        # Progress reset: probe from the PRE-no-op last+1 (= new_last), as
        # the reference's reset() runs before appendEntry — so the no-op
        # itself replicates to quiescent followers.
        match=_where(win[..., None], 0, st.match),
        next=_where(win[..., None], new_last[..., None], st.next),
        pr_state=_where(win[..., None], PR_PROBE, st.pr_state),
        paused=_where(win[..., None], False, st.paused),
        ack_age=_where(win[..., None], 0, st.ack_age),
    )
    return _set_self_progress(st)


# ---------------------------------------------------------------------------
# Phase 1: tick
# ---------------------------------------------------------------------------

def _tick(st: GroupState, cfg: KernelConfig, active: jax.Array,
          tick: jax.Array) -> Tuple[GroupState, jax.Array, jax.Array]:
    """Advance the logical clock one tick for every instance where the
    scalar `tick` flag is set (masked arithmetic, no lax.cond branch — the
    cond's per-field copies showed up in the TPU profile). Returns
    (state, hb_fire_term, vote_fire_term): (G, P) int32 arrays holding the
    term at which a heartbeat broadcast / vote broadcast was staged this
    round (0 = none) — the term lets send assembly cancel the broadcast if a
    same-round message bumped us off that term."""
    G, P = st.term.shape
    is_ldr = st.state == LEADER
    elapsed = st.elapsed + tick.astype(jnp.int32)

    # Leaders: heartbeat timeout (reference tickHeartbeat raft.go:376-382).
    hb_timeout = tick & active & is_ldr & (elapsed >= cfg.heartbeat_tick)
    hb_fire_term = _where(hb_timeout, st.term, 0)

    # Followers/candidates: randomized election timeout (reference
    # tickElection + isElectionTimeout raft.go:362-373,765-771).
    d = elapsed - cfg.election_tick
    draw = tick & active & ~is_ldr & (d >= 0)
    prng = _where(draw, xorshift32(st.prng), st.prng)
    timeout = draw & (d > (prng % jnp.uint32(cfg.election_tick)).astype(jnp.int32))

    st = st._replace(
        prng=prng,
        elapsed=_where(hb_timeout | timeout, 0, elapsed),
    )

    # Campaign (reference campaign() raft.go:429-443): term+1, vote self,
    # tally own vote; single-voter groups win instantly.
    camp = timeout
    self_id = jnp.arange(1, P + 1, dtype=jnp.int32)[None, :]
    votes = _where(camp[..., None], 0, st.votes)
    votes = _where(
        camp[..., None] & (jnp.arange(P)[None, None, :]
                           == jnp.arange(P)[None, :, None]),
        1, votes)
    st = st._replace(
        term=_where(camp, st.term + 1, st.term),
        vote=_where(camp, self_id, st.vote),
        lead=_where(camp, 0, st.lead),
        state=_where(camp, CANDIDATE, st.state),
        votes=votes,
        # reset() also clears progress; leaders-to-be re-reset on winning.
        paused=_where(camp[..., None], False, st.paused),
    )
    instant_win = camp & (quorum(st)[:, None] == 1)
    st = _append_noop_and_lead(st, cfg, instant_win)
    vote_fire_term = _where(camp & ~instant_win, st.term, 0)

    # Heartbeat broadcast resumes all paused probes (reference
    # bcastHeartbeat raft.go:313-321).
    st = st._replace(paused=_where(hb_timeout[..., None], False, st.paused))
    return st, hb_fire_term, vote_fire_term


# ---------------------------------------------------------------------------
# Phase 2: one sender slot's messages, for all instances at once
# ---------------------------------------------------------------------------

def _step_msgs_from(st: GroupState, cfg: KernelConfig, q: int,
                    msg: jax.Array, active: jax.Array,
                    ) -> Tuple[GroupState, jax.Array]:
    """Process the inbox slot from sender `q` on every instance; returns the
    updated state and the staged response (G, P, F) addressed back to q.

    Mirrors raft.Step (reference raft.go:462-669) as masked dense updates.
    """
    G, P = st.term.shape
    F = cfg.fields
    mtype = msg[..., F_TYPE]
    mterm = msg[..., F_TERM]
    mindex = msg[..., F_INDEX]
    mlogterm = msg[..., F_LOGTERM]
    mcommit = msg[..., F_COMMIT]
    mreject = msg[..., F_REJECT]
    mhint = msg[..., F_HINT]
    mnent = msg[..., F_NENT]
    ent_terms = msg[..., N_FIXED_FIELDS:]

    has = active & (mtype != M_NONE)
    resp = jnp.zeros((G, P, F), jnp.int32)

    # -- term gate (reference raft.go:470-486) -----------------------------
    higher = has & (mterm > st.term)
    lead_on_higher = _where(mtype == M_VOTE, 0, q + 1)
    st = _become_follower(st, higher, mterm, lead_on_higher)
    live = has & (mterm == st.term)  # stale (lower-term) messages ignored

    is_f = st.state == FOLLOWER
    is_c = st.state == CANDIDATE
    is_l = st.state == LEADER

    # -- MsgApp / MsgHeartbeat demote same-term candidates (stepCandidate) --
    demote = live & is_c & ((mtype == M_APP) | (mtype == M_HB))
    st = _become_follower(st, demote, st.term, q + 1)
    is_f, is_c, is_l = (st.state == FOLLOWER, st.state == CANDIDATE,
                        st.state == LEADER)

    # -- MsgVote (uniform grant rule; reference stepFollower raft.go:636-647,
    #    leaders/candidates reject naturally because vote == self) ----------
    v = live & (mtype == M_VOTE)
    last_t = _last_term(st, cfg)
    up_to_date = (mlogterm > last_t) | ((mlogterm == last_t)
                                        & (mindex >= st.last_index))
    grant = v & ((st.vote == 0) | (st.vote == q + 1)) & up_to_date
    st = st._replace(
        vote=_where(grant, q + 1, st.vote),
        elapsed=_where(grant, 0, st.elapsed),
    )
    resp = _stage(resp, v, M_VOTE_RESP, st.term, reject=~grant)

    # -- MsgVoteResp (reference stepCandidate raft.go:603-612) --------------
    vr = live & is_c & (mtype == M_VOTE_RESP)
    first = st.votes[:, :, q] == 0
    # int32 literals: under x64 test configs plain ints promote to int64
    # and the votes scatter would mix dtypes (FutureWarning today, error
    # in future jax).
    vote_val = _where(mreject == 0, jnp.int32(1), jnp.int32(2))
    votes = st.votes.at[:, :, q].set(
        _where(vr & first, vote_val, st.votes[:, :, q]))
    st = st._replace(votes=votes)
    granted = jnp.sum((votes == 1).astype(jnp.int32), axis=2)
    rejected = jnp.sum((votes == 2).astype(jnp.int32), axis=2)
    qr = quorum(st)[:, None]
    win = vr & (granted >= qr)
    lose = vr & ~win & (rejected >= qr)
    st = _append_noop_and_lead(st, cfg, win)
    st = _become_follower(st, lose, st.term, 0)
    is_f, is_c, is_l = (st.state == FOLLOWER, st.state == CANDIDATE,
                        st.state == LEADER)

    # -- MsgApp (reference handleAppendEntries raft.go:651-664) -------------
    a = live & (mtype == M_APP) & ~is_l
    st = st._replace(
        elapsed=_where(a, 0, st.elapsed),
        lead=_where(a, q + 1, st.lead),
    )
    below_commit = a & (mindex < st.commit)
    resp = _stage(resp, below_commit, M_APP_RESP, st.term,
                  index=st.commit)

    chk = a & ~below_commit
    prev_t = term_at(st, cfg, mindex)
    prev_in_win = in_window(st, cfg, mindex)
    # Below the device window (but >= commit): the host resolves it.
    escape = chk & ~prev_in_win & (mindex <= st.last_index)
    st = st._replace(need_host=_flag(st.need_host, escape, NH_SNAP))

    match_ok = chk & ~escape & prev_in_win & (prev_t == mlogterm)
    rej = chk & ~escape & ~match_ok
    resp = _stage(resp, rej, M_APP_RESP, st.term, index=mindex,
                  reject=True, hint=st.last_index)

    # Conflict scan + append over the E entry slots (reference
    # findConflict/truncateAndAppend log.go:98-123).
    E = cfg.max_ents
    idx_j = mindex[..., None] + 1 + jnp.arange(E, dtype=jnp.int32)[None, None]
    valid_j = jnp.arange(E)[None, None] < mnent[..., None]
    my_t = _terms_at_many(st, cfg, idx_j)
    mismatch = valid_j & (my_t != ent_terms)
    any_conf = match_ok & jnp.any(mismatch, axis=-1)
    first_j = jnp.argmax(mismatch, axis=-1)
    ci = _where(any_conf, mindex + 1 + first_j, 0)
    # Safety: conflicting with a committed entry is a protocol violation
    # (reference log.go maybeAppend panic); flag it distinctly so the host
    # dumps state and fails loudly instead of papering over it.
    st = st._replace(need_host=_flag(st.need_host,
                                     any_conf & (ci <= st.commit),
                                     NH_VIOLATION))

    do_append = any_conf
    st = _write_terms(st, cfg, anchor=mindex, terms=ent_terms, lo=ci,
                      count=mnent, mask=do_append)
    lastnewi = mindex + mnent
    old_last = st.last_index
    st = st._replace(
        last_index=_where(do_append, lastnewi, st.last_index))
    # A SHRINKING truncation strands ring slots: the discarded entries'
    # slots now alias indices W lower, which fall back INSIDE the valid
    # window — but those lower entries' true terms were overwritten long
    # ago. Zero the stranded slots so stale terms can never be read as
    # live ones (0 = unresolvable sentinel). The device itself only reads
    # terms at indices >= commit (all strands are strictly below commit:
    # the admission throttle keeps last-commit < W), but the host engine
    # diffs the whole ring into its WAL and must not record junk.
    shrink = do_append & (old_last > lastnewi)
    w_idx = jnp.arange(cfg.window, dtype=jnp.int32)[None, None, :]
    i_w = old_last[..., None] - jnp.mod(old_last[..., None] - w_idx,
                                        cfg.window)
    strand = shrink[..., None] & (i_w > lastnewi[..., None])
    st = st._replace(log_term=jnp.where(strand, 0, st.log_term))
    new_commit = jnp.maximum(st.commit,
                             jnp.minimum(mcommit, lastnewi))
    st = st._replace(commit=_where(match_ok, new_commit, st.commit))
    resp = _stage(resp, match_ok, M_APP_RESP, st.term, index=lastnewi)

    # -- MsgAppResp (reference stepLeader raft.go:514-546) ------------------
    ar = live & is_l & (mtype == M_APP_RESP)
    match_q = st.match[:, :, q]
    next_q = st.next[:, :, q]
    pr_q = st.pr_state[:, :, q]
    paused_q = st.paused[:, :, q]

    rej_resp = ar & (mreject != 0)
    # replicate: fall back to match+1 and probe (maybeDecrTo fast path)
    repl_rej = rej_resp & (pr_q == PR_REPLICATE) & (mindex > match_q)
    # probe: only the outstanding probe at next-1 counts
    probe_rej = rej_resp & (pr_q == PR_PROBE) & (next_q - 1 == mindex)
    next_q = _where(repl_rej, match_q + 1, next_q)
    next_q = _where(probe_rej,
                    jnp.maximum(jnp.minimum(mindex, mhint + 1), 1), next_q)
    pr_q = _where(repl_rej, PR_PROBE, pr_q)
    paused_q = _where(probe_rej, False, paused_q)

    ok_resp = ar & (mreject == 0)
    upd = ok_resp & (match_q < mindex)
    match_q = _where(upd, mindex, match_q)
    paused_q = _where(upd, False, paused_q)
    pr_q = _where(upd & (pr_q == PR_PROBE), PR_REPLICATE, pr_q)
    next_q = jnp.maximum(next_q, _where(ok_resp, mindex + 1, 0))

    st = st._replace(
        match=st.match.at[:, :, q].set(match_q),
        next=st.next.at[:, :, q].set(next_q),
        pr_state=st.pr_state.at[:, :, q].set(pr_q),
        paused=st.paused.at[:, :, q].set(paused_q),
        # Any append response (accept or reject) is replication-liveness
        # evidence from this target.
        ack_age=st.ack_age.at[:, :, q].set(
            _where(ar, 0, st.ack_age[:, :, q])),
    )

    # -- MsgHeartbeat (reference handleHeartbeat raft.go:666-669) -----------
    h = live & (mtype == M_HB) & ~is_l
    st = st._replace(
        elapsed=_where(h, 0, st.elapsed),
        lead=_where(h, q + 1, st.lead),
        commit=_where(h, jnp.maximum(st.commit,
                                     jnp.minimum(mcommit, st.last_index)),
                      st.commit),
    )
    resp = _stage(resp, h, M_HB_RESP, st.term)

    # -- MsgHeartbeatResp: staleness-driven retransmission (reference
    #    stepLeader MsgHeartbeatResp -> sendAppend, raft.go:547-551).
    #    Gap-driven sends make the ordinary case a no-op, but appends can
    #    be lost (network drops, outbox slot collisions) with next already
    #    optimistically bumped — then nothing ever resends: match freezes
    #    whether unacked pinned at the flow window or the group just went
    #    idle. A heartbeat response while the target's append responses
    #    have been silent for > 2 heartbeat intervals pulls next back to
    #    match+1 so the gap-driven sender retransmits the window. The age
    #    gate keeps steady-state traffic (acks merely in flight) free of
    #    duplicate sends. --
    hrs = live & is_l & (mtype == M_HB_RESP)
    match_h = st.match[:, :, q]
    next_h = st.next[:, :, q]
    stale = (hrs & (st.pr_state[:, :, q] == PR_REPLICATE)
             & (match_h < st.last_index)
             & (st.ack_age[:, :, q] > 2 * cfg.heartbeat_tick + 2))
    st = st._replace(
        next=st.next.at[:, :, q].set(
            _where(stale, match_h + 1, next_h)))
    return st, resp


def _stage(resp: jax.Array, mask: jax.Array, mtype: int, term: jax.Array,
           index=None, reject=None, hint=None) -> jax.Array:
    """Write a response message into `resp` (G, P, F) where mask holds.
    Later stages win slot collisions, matching sequential Step semantics
    (each message produces at most one response in the scalar core)."""
    upd = resp
    upd = upd.at[..., F_TYPE].set(jnp.where(mask, mtype, upd[..., F_TYPE]))
    upd = upd.at[..., F_TERM].set(jnp.where(mask, term, upd[..., F_TERM]))
    if index is not None:
        upd = upd.at[..., F_INDEX].set(
            jnp.where(mask, index, upd[..., F_INDEX]))
    if reject is not None:
        rej = jnp.asarray(reject)
        upd = upd.at[..., F_REJECT].set(
            jnp.where(mask, rej.astype(jnp.int32), upd[..., F_REJECT]))
    if hint is not None:
        upd = upd.at[..., F_HINT].set(jnp.where(mask, hint, upd[..., F_HINT]))
    return upd


def _terms_at_many(st: GroupState, cfg: KernelConfig,
                   idx: jax.Array) -> jax.Array:
    """term_at for an extra trailing axis of indices: idx (G, P, E) ->
    terms (G, P, E); 0 outside the window / beyond last. The one-hot
    select-sum below IS the measured-fastest TPU formulation (it replaced
    the take_along_axis gathers that originally dominated the round). A
    Pallas variant (ops/pallas_kernels.ring_resolve) was measured on real
    TPU in r4: 2.3x faster in isolation but 9.3x SLOWER wired in here
    (scripts/pallas_roundbench.py — the pallas_call boundary defeats the
    fusion this formulation exists for), so the jnp path stays."""
    slot = jnp.mod(idx, cfg.window)
    t = ring_lookup(st.log_term, slot)
    last = st.last_index[..., None]
    valid = (idx > last - cfg.window) & (idx <= last) & (idx >= 1)
    return jnp.where(valid, t, 0)


def _write_terms(st: GroupState, cfg: KernelConfig, anchor: jax.Array,
                 terms: jax.Array, lo: jax.Array, count: jax.Array,
                 mask: jax.Array) -> GroupState:
    """Write entry terms for the contiguous index range
    (max(lo, anchor+1) .. anchor+count] into the log ring, where entry
    anchor+1+j takes terms[..., j].

    Formulated ring-slot-wise (one gather + elementwise select over the W
    axis) instead of as a scatter: TPU scatters with computed indices
    serialize, and this runs on every message-phase pass. Each ring slot w
    maps to at most ONE index in the range (count <= E < W), namely
    j_w = (w - (anchor+1)) mod W.

    anchor/lo/count: (G, P); terms: (G, P, E); mask: (G, P).
    """
    W = cfg.window
    E = terms.shape[-1]
    w_idx = jnp.arange(W, dtype=jnp.int32)[None, None, :]
    j_w = jnp.mod(w_idx - (anchor[..., None] + 1), W)
    idx_w = anchor[..., None] + 1 + j_w
    write = (mask[..., None] & (j_w < count[..., None])
             & (idx_w >= lo[..., None]))
    val = ring_lookup(terms, jnp.minimum(j_w, E - 1))
    return st._replace(
        log_term=jnp.where(write, val, st.log_term))


# ---------------------------------------------------------------------------
# Phase 3: proposals
# ---------------------------------------------------------------------------

def _apply_proposals_slots(st: GroupState, cfg: KernelConfig,
                           cnt_gp: jax.Array,
                           active: jax.Array) -> GroupState:
    """Per-SLOT proposal admission for the multi-host engine: cnt_gp is
    (G, P), SHARDED like the state over the peers mesh axis — each host
    stages proposals only at its own local leader slots, so no replicated
    (and therefore cross-host-agreed) input is needed. Semantics match
    _apply_proposals with prop_slot = the slot whose count is nonzero;
    non-leader slots admit nothing."""
    is_ldr = active & (st.state == LEADER)
    tail = st.last_index - st.commit
    room = jnp.maximum(0, cfg.window // 2 - tail)
    cnt = jnp.minimum(jnp.minimum(cnt_gp, cfg.max_ents), room)
    cnt = cnt * is_ldr.astype(jnp.int32)
    E = cfg.max_ents
    terms = jnp.broadcast_to(st.term[..., None], (*st.term.shape, E))
    st = _write_terms(st, cfg, anchor=st.last_index, terms=terms,
                      lo=st.last_index + 1, count=cnt, mask=cnt > 0)
    st = st._replace(last_index=st.last_index + cnt)
    return _set_self_progress(st)


def _apply_proposals(st: GroupState, cfg: KernelConfig, prop_count: jax.Array,
                     prop_slot: jax.Array, active: jax.Array) -> GroupState:
    """The addressed leader appends `prop_count[g]` new entries of its term
    (reference appendEntry raft.go:351-360; payloads live in the host log
    store). `prop_slot[g]` names the slot the host routed the proposals to —
    during a transient two-leader window only that instance appends, so the
    host's (group, index)->payload map stays unambiguous."""
    P = st.term.shape[1]
    is_target = jnp.arange(P, dtype=jnp.int32)[None, :] == prop_slot[:, None]
    is_ldr = active & is_target & (st.state == LEADER)
    # Admission control: never let the uncommitted tail outrun half the
    # device log window, or followers' needed entries fall off the ring and
    # every group degrades to the host snapshot path. This is the batched
    # analogue of the reference's proposal backpressure (its raft channel
    # blocks; here the device itself throttles and the host engine retries
    # unaccepted proposals next round).
    tail = st.last_index - st.commit
    room = jnp.maximum(0, cfg.window // 2 - tail)
    cnt = jnp.minimum(jnp.minimum(prop_count[:, None], cfg.max_ents), room)
    cnt = cnt * is_ldr.astype(jnp.int32)
    E = cfg.max_ents
    terms = jnp.broadcast_to(st.term[..., None],
                             (*st.term.shape, E))
    st = _write_terms(st, cfg, anchor=st.last_index, terms=terms,
                      lo=st.last_index + 1, count=cnt,
                      mask=cnt > 0)
    st = st._replace(last_index=st.last_index + cnt)
    return _set_self_progress(st)


# ---------------------------------------------------------------------------
# Phase 4: quorum commit (THE reduction — reference maybeCommit
# raft.go:323-332 becomes one top_k over the peers axis)
# ---------------------------------------------------------------------------

def _quorum_commit(st: GroupState, cfg: KernelConfig, active: jax.Array,
                   lead_term0: jax.Array) -> GroupState:
    G, P = st.term.shape
    eye = jnp.eye(P, dtype=bool)[None, :, :]
    target_active = active[:, None, :]
    mrow = _where(eye, st.last_index[..., None], st.match)
    mrow = _where(target_active, mrow, -1)
    topk, _ = jax.lax.top_k(mrow, P)  # sorted descending
    qidx = jnp.broadcast_to((quorum(st) - 1)[:, None, None], (G, P, 1))
    mci = ring_lookup(topk, qidx)[..., 0]
    # Only entries from the leader's own term commit by counting
    # (raftLog.maybeCommit; Raft paper §5.4.2). The reference runs
    # maybeCommit inside each MsgAppResp (raft.go:514-545), BEFORE a
    # later message might demote the leader; this deferred phase must not
    # lose that advance, so an instance demoted DURING the message phase
    # still commits on behalf of the term it led at round start
    # (lead_term0): its match row was only updatable by same-term acks,
    # making this exactly the reference's per-response maybeCommit.
    eff_term = _where(st.state == LEADER, st.term, lead_term0)
    mci_term = term_at(st, cfg, jnp.maximum(mci, 0))
    ok = (eff_term > 0) & (mci > st.commit) & (mci_term == eff_term)
    return st._replace(commit=_where(ok, mci, st.commit))


# ---------------------------------------------------------------------------
# Phase 5: send assembly (gap-driven)
# ---------------------------------------------------------------------------

def _assemble_sends(st: GroupState, cfg: KernelConfig, resp: jax.Array,
                    hb_fire_term: jax.Array, vote_fire_term: jax.Array,
                    active: jax.Array) -> Tuple[GroupState, jax.Array]:
    """Build the outbox (G, P_from, P_to, F) and apply optimistic progress
    updates for sent appends."""
    G, P = st.term.shape
    F = cfg.fields
    E = cfg.max_ents
    eye = jnp.eye(P, dtype=bool)[None, :, :]
    tgt_ok = active[:, None, :] & active[:, :, None] & ~eye

    # ---- appends --------------------------------------------------------
    is_ldr = (st.state == LEADER)[..., None]
    last = st.last_index[..., None]
    unacked = st.next - 1 - st.match
    paused_eff = _where(st.pr_state == PR_PROBE, st.paused,
                        unacked >= cfg.effective_flow_window)
    has_gap = st.next <= last
    prev = st.next - 1
    prev_in_win = in_window(st, cfg, prev)
    # Entries next..next+n-1 must ALSO be resolvable from the sender's ring
    # (next > last - W). prev == 0 passes in_window via the empty-log
    # special case, but once last > W the ring no longer holds entry 1 —
    # without this guard the term gather below would alias modulo W and
    # ship garbage terms to an empty/new follower.
    ents_ok = st.next > last - cfg.window
    sendable = prev_in_win & ents_ok
    # Target lags below the device window -> host must ship a snapshot.
    need_snap = is_ldr & tgt_ok & has_gap & ~sendable
    st = st._replace(need_host=_flag(st.need_host,
                                     jnp.any(need_snap, axis=2), NH_SNAP))

    send_app = is_ldr & tgt_ok & has_gap & ~paused_eff & sendable
    n = jnp.minimum(last - st.next + 1, E)
    n = _where(send_app, n, 0)

    # Entry terms for slots next .. next+n-1, from the SENDER's ring; the
    # one-hot select-sum broadcasts the (G,P,1,W) ring across targets
    # without materializing a (G,P,P,W) copy.
    idx_e = st.next[..., None] + jnp.arange(E, dtype=jnp.int32)[None, None, None]
    slot_e = jnp.mod(idx_e, cfg.window)
    terms_e = ring_lookup(st.log_term[:, :, None, :], slot_e)
    valid_e = jnp.arange(E)[None, None, None] < n[..., None]
    terms_e = jnp.where(valid_e, terms_e, 0)

    prev_term = _terms_at_many(st, cfg, prev)  # (G, P, P): per-sender ring

    out = jnp.zeros((G, P, P, F), jnp.int32)
    term_b = jnp.broadcast_to(st.term[..., None], (G, P, P))
    commit_b = jnp.broadcast_to(st.commit[..., None], (G, P, P))

    def put(out, mask, field, val):
        return out.at[..., field].set(jnp.where(mask, val, out[..., field]))

    out = put(out, send_app, F_TYPE, M_APP)
    out = put(out, send_app, F_TERM, term_b)
    out = put(out, send_app, F_INDEX, prev)
    out = put(out, send_app, F_LOGTERM, prev_term)
    out = put(out, send_app, F_COMMIT, commit_b)
    out = put(out, send_app, F_NENT, n)
    ents_cur = out[..., N_FIXED_FIELDS:]
    out = out.at[..., N_FIXED_FIELDS:].set(
        jnp.where(send_app[..., None], terms_e, ents_cur))

    # Optimistic update / probe pause (reference sendAppend raft.go:267-279).
    sent_n = _where(send_app, n, 0)
    st = st._replace(
        next=_where(send_app & (st.pr_state == PR_REPLICATE),
                    st.next + sent_n, st.next),
        paused=_where(send_app & (st.pr_state == PR_PROBE), True, st.paused),
    )

    # ---- heartbeats (lower priority than appends) -----------------------
    hb_ok = (hb_fire_term[..., None] == term_b) & (hb_fire_term[..., None] > 0)
    send_hb = is_ldr & tgt_ok & hb_ok & ~send_app
    hb_commit = jnp.minimum(st.match, commit_b)  # reference raft.go:285-298
    out = put(out, send_hb, F_TYPE, M_HB)
    out = put(out, send_hb, F_TERM, term_b)
    out = put(out, send_hb, F_COMMIT, hb_commit)

    # ---- vote requests --------------------------------------------------
    is_cand = (st.state == CANDIDATE)[..., None]
    vf = (vote_fire_term[..., None] == term_b) & (vote_fire_term[..., None] > 0)
    send_vote = is_cand & tgt_ok & vf & (out[..., F_TYPE] == M_NONE)
    last_t = _last_term(st, cfg)
    out = put(out, send_vote, F_TYPE, M_VOTE)
    out = put(out, send_vote, F_TERM, term_b)
    out = put(out, send_vote, F_INDEX,
              jnp.broadcast_to(last[..., 0][..., None], (G, P, P)))
    out = put(out, send_vote, F_LOGTERM,
              jnp.broadcast_to(last_t[..., None], (G, P, P)))

    # ---- responses override everything (drop-on-collision is safe) ------
    has_resp = resp[..., F_TYPE] != M_NONE
    out = jnp.where(has_resp[..., None], resp, out)
    return st, out


# ---------------------------------------------------------------------------
# The step
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=0, donate_argnums=_donate_at_import((1,)))
def step(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
         prop_count: jax.Array, prop_slot: jax.Array, tick: jax.Array
         ) -> Tuple[GroupState, jax.Array]:
    """One batched consensus round for all G×P instances.

    inbox:      (G, P, P_from, F) int32 — inbox[g, p, q] is the message
                delivered to instance (g, p) from sender slot q this round
                (M_NONE-typed slots are empty).
    prop_count: (G,) int32 — entries proposed to each group's leader this
                round (payloads stay in the host log store).
    prop_slot:  (G,) int32 — which peer slot the host routed proposals to.
    tick:       () bool — whether this round advances the logical clock.

    Returns (new_state, outbox) with outbox (G, P_to_assignment...) shaped
    (G, P_from, P_to, F). Routing outbox->inbox is a transpose of the two
    peer axes (single host) or an all_to_all over the "peers" mesh axis.

    Phase order (the scalar equivalence harness mirrors it exactly):
    tick -> messages by sender slot 0..P-1 -> proposals -> quorum commit ->
    send assembly -> defensive invariant check (the reference's
    log.maybeAppend/commitTo panics: commit past the log end means
    corrupted state and raises NH_VIOLATION).
    """
    return _step_body(cfg, st, inbox, prop_count, prop_slot, tick,
                      quiet=False)


# ---------------------------------------------------------------------------
# Quiescent fast path
#
# In steady state (every group led, no elections or term changes in flight)
# the P sequential message passes above are overkill: leaders receive ONLY
# append/heartbeat responses — whose progress updates live in per-sender
# columns and therefore commute across senders — and each follower receives
# AT MOST one append-or-heartbeat, from its leader (one leader per term;
# send assembly emits one message per (leader, target) per round). Both
# facts collapse the message phase into ONE vectorized pass. step_auto
# checks the quiescence predicate on device and lax.cond-selects the fast
# or the full path — election rounds automatically take the full path, so
# the two are behaviorally identical (tests/test_quiet_path.py drives
# bit-exactness round by round).
# ---------------------------------------------------------------------------

def _quiet_pred(st: GroupState, cfg: KernelConfig, inbox: jax.Array,
                active: jax.Array, tick: jax.Array) -> jax.Array:
    """() bool: NOTHING this round can need the sequential message phases.
    Conservative — false positives are impossible, false negatives only
    cost a slow round."""
    mtype = inbox[..., F_TYPE]
    present = mtype != M_NONE
    vote_ish = present & ((mtype == M_VOTE) | (mtype == M_VOTE_RESP))
    # Any cross-term message (stale or new-term) needs the term gate.
    term_mism = present & (inbox[..., F_TERM] != st.term[:, :, None])
    is_c = active & (st.state == CANDIDATE)
    # A follower whose clock would reach its election timeout this round
    # might campaign (and must draw from the PRNG stream either way).
    could_campaign = (tick & active & (st.state != LEADER)
                      & (st.elapsed + 1 >= cfg.election_tick))
    n_lead = jnp.sum((active & (st.state == LEADER)).astype(jnp.int32),
                     axis=1)
    pending_host = st.need_host != 0
    return ~(jnp.any(vote_ish) | jnp.any(term_mism) | jnp.any(is_c)
             | jnp.any(could_campaign) | jnp.any(n_lead > 1)
             | jnp.any(pending_host))


def _quiet_msgs(st: GroupState, cfg: KernelConfig, inbox: jax.Array,
                active: jax.Array) -> Tuple[GroupState, jax.Array]:
    """One-pass message processing for quiescent rounds; returns (state,
    resp) with resp shaped (G, P, P, F) like the full path's."""
    G, P = st.term.shape
    F = cfg.fields
    mtype_all = inbox[..., F_TYPE]
    is_l = st.state == LEADER
    recv = active[..., None]

    # -- responses to leaders: per-sender columns are independent, so all
    # P columns update in one shot (the q-loop of the full path exists
    # only for cross-column state transitions, which quiescence excludes).
    mindex_all = inbox[..., F_INDEX]
    mreject_all = inbox[..., F_REJECT]
    mhint_all = inbox[..., F_HINT]
    ar = recv & is_l[..., None] & (mtype_all == M_APP_RESP)
    match, nxt = st.match, st.next
    prs, paused = st.pr_state, st.paused

    rej = ar & (mreject_all != 0)
    repl_rej = rej & (prs == PR_REPLICATE) & (mindex_all > match)
    probe_rej = rej & (prs == PR_PROBE) & (nxt - 1 == mindex_all)
    nxt = _where(repl_rej, match + 1, nxt)
    nxt = _where(probe_rej,
                 jnp.maximum(jnp.minimum(mindex_all, mhint_all + 1), 1), nxt)
    prs = _where(repl_rej, PR_PROBE, prs)
    paused = _where(probe_rej, False, paused)

    ok = ar & (mreject_all == 0)
    upd = ok & (match < mindex_all)
    match = _where(upd, mindex_all, match)
    paused = _where(upd, False, paused)
    prs = _where(upd & (prs == PR_PROBE), PR_REPLICATE, prs)
    nxt = jnp.maximum(nxt, _where(ok, mindex_all + 1, 0))
    ack_age = _where(ar, 0, st.ack_age)

    hrs = recv & is_l[..., None] & (mtype_all == M_HB_RESP)
    stale = (hrs & (prs == PR_REPLICATE)
             & (match < st.last_index[..., None])
             & (ack_age > 2 * cfg.heartbeat_tick + 2))
    nxt = _where(stale, match + 1, nxt)
    st = st._replace(match=match, next=nxt, pr_state=prs, paused=paused,
                     ack_age=ack_age)

    # -- the one append-or-heartbeat each follower may hold: reduce over
    # the sender axis (at most one slot is populated — one leader per
    # term), then process it exactly like the full path's single-message
    # case.
    fm = recv & ~is_l[..., None] & ((mtype_all == M_APP)
                                    | (mtype_all == M_HB))
    has_fm = jnp.any(fm, axis=2)
    s_idx = jnp.argmax(fm, axis=2).astype(jnp.int32)      # (G, P)
    onehot_s = (jnp.arange(P, dtype=jnp.int32)[None, None, :]
                == s_idx[..., None])
    # dtype pinned: under x64 test configs jnp.sum promotes int32 -> int64.
    msg = jnp.sum(inbox * (fm & onehot_s)[..., None].astype(jnp.int32),
                  axis=2, dtype=jnp.int32)                 # (G, P, F)
    mtype = jnp.where(has_fm, msg[..., F_TYPE], M_NONE)
    mindex = msg[..., F_INDEX]
    mlogterm = msg[..., F_LOGTERM]
    mcommit = msg[..., F_COMMIT]
    mnent = msg[..., F_NENT]
    ent_terms = msg[..., N_FIXED_FIELDS:]

    resp_f = jnp.zeros((G, P, F), jnp.int32)
    a = has_fm & (mtype == M_APP)
    h = has_fm & (mtype == M_HB)
    st = st._replace(
        elapsed=_where(a | h, 0, st.elapsed),
        lead=_where(a | h, s_idx + 1, st.lead),
    )

    below_commit = a & (mindex < st.commit)
    resp_f = _stage(resp_f, below_commit, M_APP_RESP, st.term,
                    index=st.commit)
    chk = a & ~below_commit
    prev_t = term_at(st, cfg, mindex)
    prev_in_win = in_window(st, cfg, mindex)
    escape = chk & ~prev_in_win & (mindex <= st.last_index)
    st = st._replace(need_host=_flag(st.need_host, escape, NH_SNAP))

    match_ok = chk & ~escape & prev_in_win & (prev_t == mlogterm)
    rej_m = chk & ~escape & ~match_ok
    resp_f = _stage(resp_f, rej_m, M_APP_RESP, st.term, index=mindex,
                    reject=True, hint=st.last_index)

    E = cfg.max_ents
    idx_j = mindex[..., None] + 1 + jnp.arange(E, dtype=jnp.int32)[None, None]
    valid_j = jnp.arange(E)[None, None] < mnent[..., None]
    my_t = _terms_at_many(st, cfg, idx_j)
    mismatch = valid_j & (my_t != ent_terms)
    any_conf = match_ok & jnp.any(mismatch, axis=-1)
    first_j = jnp.argmax(mismatch, axis=-1)
    ci = _where(any_conf, mindex + 1 + first_j, 0)
    st = st._replace(need_host=_flag(st.need_host,
                                     any_conf & (ci <= st.commit),
                                     NH_VIOLATION))
    st = _write_terms(st, cfg, anchor=mindex, terms=ent_terms, lo=ci,
                      count=mnent, mask=any_conf)
    lastnewi = mindex + mnent
    old_last = st.last_index
    st = st._replace(
        last_index=_where(any_conf, lastnewi, st.last_index))
    shrink = any_conf & (old_last > lastnewi)
    w_idx = jnp.arange(cfg.window, dtype=jnp.int32)[None, None, :]
    i_w = old_last[..., None] - jnp.mod(old_last[..., None] - w_idx,
                                        cfg.window)
    strand = shrink[..., None] & (i_w > lastnewi[..., None])
    st = st._replace(log_term=jnp.where(strand, 0, st.log_term))
    new_commit = jnp.maximum(st.commit, jnp.minimum(mcommit, lastnewi))
    st = st._replace(commit=_where(match_ok, new_commit, st.commit))
    resp_f = _stage(resp_f, match_ok, M_APP_RESP, st.term, index=lastnewi)

    st = st._replace(
        commit=_where(h, jnp.maximum(st.commit,
                                     jnp.minimum(mcommit, st.last_index)),
                      st.commit))
    resp_f = _stage(resp_f, h, M_HB_RESP, st.term)

    # Route each follower's response back to its sender slot.
    resp = (resp_f[:, :, None, :]
            * onehot_s[..., None].astype(jnp.int32))        # (G, P, P, F)
    return st, resp


def _step_body(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
               prop_count: jax.Array, prop_slot: Optional[jax.Array],
               tick: jax.Array, quiet: bool,
               force_hb: bool = False) -> Tuple[GroupState, jax.Array]:
    """Shared round skeleton; `quiet` (Python bool, traced twice under the
    cond) selects the message-phase implementation. prop_slot=None selects
    per-SLOT proposal admission (prop_count is then (G, P) — the
    multi-host engine's sharded input). `force_hb` (Python bool) makes
    every active leader broadcast a heartbeat this pass regardless of its
    heartbeat clock — the ReadIndex step uses it to solicit the quorum
    acks that confirm leadership (reference bcastHeartbeat on a pending
    read, raft.go:313-321 via step MsgReadIndex)."""
    active = active_mask(st)
    P = st.term.shape[1]
    st = st._replace(ack_age=jnp.minimum(st.ack_age + 1, 1 << 20))
    st, hb_fire, vote_fire = _tick(st, cfg, active, tick)
    if force_hb:
        ldr = active & (st.state == LEADER)
        hb_fire = _where(ldr, st.term, hb_fire)
        # The broadcast resumes paused probes, exactly like a timed one.
        st = st._replace(paused=_where(ldr[..., None], False, st.paused))
    lead_term0 = _where(st.state == LEADER, st.term, 0)
    if quiet:
        st, resp = _quiet_msgs(st, cfg, inbox, active)
    else:
        resp = jnp.zeros((st.term.shape[0], P, P, cfg.fields), jnp.int32)
        for q in range(P):
            st, r = _step_msgs_from(st, cfg, q, inbox[:, :, q, :], active)
            resp = resp.at[:, :, q, :].set(r)
    if prop_slot is None:
        st = _apply_proposals_slots(st, cfg, prop_count, active)
    else:
        st = _apply_proposals(st, cfg, prop_count, prop_slot, active)
    st = _quorum_commit(st, cfg, active, lead_term0)
    st, outbox = _assemble_sends(st, cfg, resp, hb_fire, vote_fire, active)
    bad = active & (st.commit > st.last_index)
    st = st._replace(need_host=_flag(st.need_host, bad, NH_VIOLATION))
    return st, outbox


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=_donate_at_import((1, 2)))
def step_routed_auto(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
                     prop_count: jax.Array, prop_slot: jax.Array,
                     tick: jax.Array, drop_mask=None,
                     hops: int = 1) -> Tuple[GroupState, jax.Array]:
    """step + route_local with on-device fast-path selection: quiescent
    rounds (the steady-state common case) skip the P sequential message
    passes. ONE compiled program; lax.cond executes exactly one branch at
    runtime.

    `hops` chains that many message-phase+routing passes INSIDE the one
    compiled program: proposals and the tick fire only on the first hop,
    so `hops=H` is bit-identical to H successive 1-hop calls whose last
    H-1 carry no proposals and no tick (tests/test_kernel.py pins this).
    With hops=3 a proposal admitted on hop 0 is replicated (hop 0 send ->
    hop 1 append+ack -> hop 2 commit) within ONE invocation — the
    propose->commit pipeline collapses from 3 round-trips through the
    host to one device program, which is what makes sub-round ack
    latencies possible on the serving path. `drop_mask` (G, P_to, P_from,
    1) int32, applied to the routed inbox after EVERY hop, keeps
    fault-injection (partitions, message drops) hop-accurate."""
    for h in range(hops):
        pc = prop_count if h == 0 else jnp.zeros_like(prop_count)
        tk = tick if h == 0 else jnp.asarray(False)
        active = active_mask(st)
        quiet = _quiet_pred(st, cfg, inbox, active, tk)

        def fast(ops):
            st, inbox, pc, ps, tick = ops
            s, out = _step_body(cfg, st, inbox, pc, ps, tick, quiet=True)
            return s, route_local(out)

        def full(ops):
            st, inbox, pc, ps, tick = ops
            s, out = _step_body(cfg, st, inbox, pc, ps, tick, quiet=False)
            return s, route_local(out)

        st, inbox = jax.lax.cond(quiet, fast, full,
                                 (st, inbox, pc, prop_slot, tk))
        if drop_mask is not None:
            inbox = inbox * drop_mask
    return st, inbox


def route_local(outbox: jax.Array) -> jax.Array:
    """Single-host message routing: outbox[g, from, to] -> inbox[g, to, from]
    is just a transpose of the peer axes — the entire rafthttp layer
    (reference rafthttp/, 4187 lines) collapses to this when peers are
    co-located as array rows."""
    return jnp.swapaxes(outbox, 1, 2)


# ---------------------------------------------------------------------------
# Batched ReadIndex (the zero-append linearizable read plane)
# ---------------------------------------------------------------------------

def _at_slot(x: jax.Array, slot: jax.Array) -> jax.Array:
    """x[g, slot[g]] for x (G, P), slot (G,) — one-hot select-sum instead
    of a computed-index gather (same TPU reasoning as ring_lookup)."""
    P = x.shape[1]
    oh = jnp.arange(P, dtype=jnp.int32)[None, :] == slot[:, None]
    return jnp.sum(jnp.where(oh, x, 0), axis=1, dtype=x.dtype)


def _read_register(st: GroupState, cfg: KernelConfig
                   ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Register a batched ReadIndex for every group at once: capture
    (read_slot, read_term, read_commit, has_ldr), all (G,).

    read_commit is the leader's commit index AT REGISTRATION — the index
    the reference's ReadIndex protocol hands back (raft.go step
    MsgReadIndex: r.readOnly.addRequest captures r.raftLog.committed).
    has_ldr additionally requires the leader to have committed an entry
    of its OWN term (the no-op): until then its commit index may lag
    entries a prior leader already committed (Raft §8; the reference
    rejects ReadIndex before the no-op commits, raft.go:872-880). The
    term of the entry at `commit` is resolved from the leader's ring —
    unresolvable (outside the device window) reads as not-confirmed,
    which is conservative: the engine just retries next round."""
    lead_term = jnp.where(active_mask(st) & (st.state == LEADER),
                          st.term, 0)
    read_slot = jnp.argmax(lead_term, axis=1).astype(jnp.int32)
    read_term = jnp.max(lead_term, axis=1)
    read_commit = _at_slot(st.commit, read_slot)
    commit_term = _at_slot(term_at(st, cfg, st.commit), read_slot)
    has_ldr = (read_term > 0) & (commit_term == read_term)
    return read_slot, read_term, read_commit, has_ldr


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=_donate_at_import((1, 2)))
def step_routed_read_auto(cfg: KernelConfig, st: GroupState,
                          inbox: jax.Array, prop_count: jax.Array,
                          prop_slot: jax.Array, tick: jax.Array,
                          drop_mask=None, hops: int = 1
                          ) -> Tuple[GroupState, jax.Array, jax.Array,
                                     jax.Array]:
    """step_routed_auto plus a batched ReadIndex pass: returns
    (st, inbox, confirmed (G,) bool, read_commit (G,) int32).

    Protocol (reference raft.go step MsgReadIndex + ReadOnlySafe recvAck,
    data-parallel over (groups, peers)): each group's leader registers
    the read at invocation start — capturing its commit index — then
    hop 0 forces a heartbeat broadcast (`force_hb`) and every subsequent
    hop counts the M_HB_RESP / M_APP_RESP messages routed back to the
    leader slot AT the registered term. A group is `confirmed` when the
    leader (still leader, same term, own-term entry committed) holds
    acks from a quorum including itself. Nothing is appended: the whole
    pass piggybacks on the existing heartbeat/append-response machinery,
    so a confirmed read costs zero log entries and zero WAL bytes.

    Freshness: only messages produced INSIDE this invocation are counted
    (the ack scan runs after each hop's routing, never on the caller's
    initial inbox). A response carrying term T generated here proves the
    sender's term was still T after registration — so no term>T leader
    can have committed anything the registered read_commit misses. Stale
    mailbox contents predate registration and prove nothing; they are
    consumed by hop 0 but never counted.

    With hops >= 2 a quiescent group confirms within ONE invocation
    (hop 0 emits the forced heartbeat, hop 1 delivers + responds, the
    ack scan after hop 1 sees it). At hops == 1 confirmation still
    arrives opportunistically (responses to the previous round's
    traffic) or on the NEXT invocation — callers just retry unconfirmed
    groups. Proposals/tick fire on hop 0 exactly like step_routed_auto:
    a read round is also a full write round."""
    G, P = st.term.shape
    read_slot, read_term, read_commit, has_ldr = _read_register(st, cfg)
    oh_lead = (jnp.arange(P, dtype=jnp.int32)[None, :]
               == read_slot[:, None])                        # (G, P)
    acks = jnp.zeros((G, P), bool)
    for h in range(hops):
        pc = prop_count if h == 0 else jnp.zeros_like(prop_count)
        tk = tick if h == 0 else jnp.asarray(False)
        active = active_mask(st)
        quiet = _quiet_pred(st, cfg, inbox, active, tk)

        def fast(ops, _h=h):
            st, inbox, pc, ps, tick = ops
            s, out = _step_body(cfg, st, inbox, pc, ps, tick, quiet=True,
                                force_hb=(_h == 0))
            return s, route_local(out)

        def full(ops, _h=h):
            st, inbox, pc, ps, tick = ops
            s, out = _step_body(cfg, st, inbox, pc, ps, tick, quiet=False,
                                force_hb=(_h == 0))
            return s, route_local(out)

        st, inbox = jax.lax.cond(quiet, fast, full,
                                 (st, inbox, pc, prop_slot, tk))
        if drop_mask is not None:
            inbox = inbox * drop_mask
        # Messages routed to the registered leader slot this hop.
        to_lead = jnp.sum(
            inbox * oh_lead[:, :, None, None].astype(jnp.int32),
            axis=1, dtype=jnp.int32)                         # (G, P_from, F)
        mt = to_lead[..., F_TYPE]
        fresh = (((mt == M_HB_RESP) | (mt == M_APP_RESP))
                 & (to_lead[..., F_TERM] == read_term[:, None]))
        acks = acks | fresh
    n_acks = jnp.sum((acks & ~oh_lead).astype(jnp.int32), axis=1)
    still = ((_at_slot(st.state, read_slot) == LEADER)
             & (_at_slot(st.term, read_slot) == read_term))
    confirmed = has_ldr & still & (n_acks + 1 >= quorum(st))
    return st, inbox, confirmed, read_commit


# Per-(g, p) change flags emitted by step_routed_compact.
CHG_HS = 1       # term | vote | commit changed (the WAL HardState diff)
CHG_LAST = 2     # last_index changed
CHG_RING = 4     # any ring (log-term window) slot changed
CHG_STATE = 8    # role changed (host mirror only; never journaled)


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=_donate_at_import((1, 2)))
def step_routed_compact(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
                        prop_count: jax.Array, prop_slot: jax.Array,
                        tick: jax.Array, drop_mask=None, hops: int = 1
                        ) -> Tuple[GroupState, jax.Array, jax.Array,
                                   jax.Array]:
    """step_routed_auto plus an ON-DEVICE state diff: returns (st, inbox,
    flags, any_need_host) where flags is a (G, P) uint8 CHG_* bitmask of
    what changed this round vs the pre-step state.

    Why: the serving engine's per-round full-state readback is O(G*P*W)
    bytes (the ring alone is 32 MB at G=100k) even when a round changed
    almost nothing — the common case at sub-saturated load, and the term
    that dominates ack latency when the device is remote (the TPU tunnel
    bills every byte). With the diff computed where the state lives, a
    quiet round reads back G*P bytes of flags + one bool, and the host
    fetches values only for rows that actually changed (gather_rows). A
    round that changed more rows than the engine's cap falls back to the
    full readback — at saturation the full transfer is amortized by the
    huge batch it carries, so the fallback costs throughput nothing.

    The flag set covers exactly the fields the engine mirrors on the
    host (term/vote/commit -> WAL HardState diff, last_index, ring,
    state): a round leaving all four bits clear for a row is a round the
    full path would have read back byte-identical mirror values for.
    any_need_host folds the (G, P) need_host bitmask to one scalar; a
    true value sends the whole round down the full-readback path (need-
    host rounds do snapshot/violation surgery that reads bulk state
    anyway)."""
    st0 = st
    st, inbox = step_routed_auto.__wrapped__(
        cfg, st, inbox, prop_count, prop_slot, tick, drop_mask, hops)
    hs = ((st.term != st0.term) | (st.vote != st0.vote)
          | (st.commit != st0.commit))
    flags = (hs.astype(jnp.uint8) * CHG_HS
             | (st.last_index != st0.last_index).astype(jnp.uint8)
             * CHG_LAST
             | jnp.any(st.log_term != st0.log_term, axis=2)
             .astype(jnp.uint8) * CHG_RING
             | (st.state != st0.state).astype(jnp.uint8) * CHG_STATE)
    any_nh = jnp.any(st.need_host != 0)
    return st, inbox, flags, any_nh


@jax.jit
def gather_rows(st: GroupState, gi: jax.Array, pi: jax.Array):
    """Fetch the engine-mirrored fields for K specific (g, p) rows:
    (term, vote, commit, state, last_index) each (K,) plus the (K, W)
    ring rows. K is a trace-time constant — callers pad the index
    vectors to size buckets to bound retraces. Padding rows (0, 0) are
    harmless: callers slice results back to the true K."""
    return (st.term[gi, pi], st.vote[gi, pi], st.commit[gi, pi],
            st.state[gi, pi], st.last_index[gi, pi],
            st.log_term[gi, pi])


@functools.partial(jax.jit, static_argnums=0, donate_argnums=_donate_at_import((1, 2)))
def step_routed_slots(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
                      cnt_gp: jax.Array, tick: jax.Array
                      ) -> Tuple[GroupState, jax.Array]:
    """Multi-host serving step: per-SLOT proposal counts (G, P) sharded
    like the state (see _apply_proposals_slots), full sequential message
    path, fused routing — an all_to_all over the peers mesh axis when the
    state is sharded across hosts (the ICI/DCN consensus transport of
    SURVEY §2.4)."""
    st, outbox = _step_body(cfg, st, inbox, cnt_gp, None, tick,
                            quiet=False)
    return st, route_local(outbox)


@functools.partial(jax.jit, static_argnums=(0, 6), donate_argnums=_donate_at_import((1, 2)))
def step_routed_slots_auto(cfg: KernelConfig, st: GroupState,
                           inbox: jax.Array, cnt_gp: jax.Array,
                           tick: jax.Array, drop_mask=None,
                           hops: int = 1) -> Tuple[GroupState, jax.Array]:
    """step_routed_slots with the quiescent fast path (and the same
    multi-hop/drop-mask machinery as step_routed_auto — this IS that
    function with per-slot admission selected via prop_slot=None).

    DURABILITY CONSTRAINT (multi-host callers): hops MUST stay 1 when
    peers are sharded across independently-failing hosts. With hops>1
    the leader consumes follower acks produced ON DEVICE, before those
    followers' hosts have journaled the appended entries — quorum commit
    would then cover unpersisted replicas, and a follower-host crash
    after the collective but before its WAL append could elect a new
    quorum WITHOUT an acked entry (the exact loss the persist-before-
    send contract exists to prevent). Multi-hop is safe only where all
    peers share one failure domain (the single-host MultiEngine)."""
    return step_routed_auto.__wrapped__(cfg, st, inbox, cnt_gp, None,
                                        tick, drop_mask, hops)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=_donate_at_import((1, 2)))
def step_routed(cfg: KernelConfig, st: GroupState, inbox: jax.Array,
                prop_count: jax.Array, prop_slot: jax.Array,
                tick: jax.Array) -> Tuple[GroupState, jax.Array]:
    """step + route_local fused into ONE device program: returns
    (new_state, next_inbox). Saves a dispatch + transpose copy per round
    for single-host callers that always route locally (bench, engine)."""
    st, outbox = step.__wrapped__(cfg, st, inbox, prop_count, prop_slot,
                                  tick)
    return st, route_local(outbox)


# ---------------------------------------------------------------------------
# CPU donation hazard
# ---------------------------------------------------------------------------
# XLA:CPU's thunk executor has a buffer-aliasing race under the donated
# multi-hop step: a donated input that an output merely passes through
# (peer_mask — the kernel never writes it, so XLA aliases input buffer
# to output) occasionally comes back holding a DIFFERENT intermediate of
# the same program (the step's is-leader mask). Bisected at G=4/P=5:
# 21/40 boots corrupted with donation, 0/40 without, with bit-identical
# trajectories both ways — a runtime race, not a miscompile. The same
# race scribbles freed heap: long engine workloads segfault or hang at
# shutdown ~1/3 of runs with donation and never without (12/12 clean).
# Two gates keep cpu runs off donation: the module-level jits import
# undonated whenever JAX_PLATFORMS pins a non-TPU platform
# (_donate_at_import — covers the test suite and every kernel-direct
# caller), and serving engines re-decide per LIVE backend below (covers
# the JAX_PLATFORMS-unset cpu fallback). TPU keeps donation — the state
# arrays ARE the HBM budget there, and the race has only ever been
# observed on cpu. The engine's
# peer_mask watchdog (EngineConfig.mask_check_rounds) stays on as
# defense-in-depth for donating backends. ETCD_TPU_DONATE=on|off
# overrides the auto choice (e.g. `on` to A/B the race, `off` to run a
# TPU box conservatively).

_STEP_STATICS = {
    "step_routed_auto": (0, 7),
    "step_routed_compact": (0, 7),
    "step_routed_read_auto": (0, 7),
    "step_routed_slots_auto": (0, 6),
}


def donate_safe(argnums):
    """`argnums` if donation is safe on the LIVE backend, else ().

    Calls jax.default_backend(), which initializes the backend — only
    call this from engine/serving init (platform flags final), never at
    import time (multihost scripts set JAX_PLATFORMS/distributed state
    after importing this module)."""
    mode = os.environ.get("ETCD_TPU_DONATE", "auto")
    if mode in ("on", "1"):
        return tuple(argnums)
    if mode in ("off", "0"):
        return ()
    return () if jax.default_backend() == "cpu" else tuple(argnums)


@functools.lru_cache(maxsize=None)
def _undonated(name):
    return jax.jit(globals()[name].__wrapped__,
                   static_argnums=_STEP_STATICS[name])


def step_variant(name):
    """The module-level jitted step `name`, or its undonated twin when
    donation is unsafe on the live backend (cached — one compile per
    shape either way). When the module jits already imported undonated
    (_donate_at_import, e.g. the JAX_PLATFORMS=cpu test suite) the
    module jit IS the undonated twin — reuse it so kernel-direct tests
    and engine tests share one compile cache."""
    if donate_safe((1,)) or not _donate_at_import((1,)):
        return globals()[name]
    return _undonated(name)
