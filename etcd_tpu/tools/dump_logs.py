"""Offline data-dir inspector.

Behavioral equivalent of reference tools/etcd-dump-logs: load the newest
snapshot (print its term/index/conf state), then replay the WAL from the
snapshot marker and print every entry — decoded Requests for normal
entries, decoded ConfChanges for configuration entries — plus the WAL
metadata (node/cluster IDs) and final HardState.

Usage: python -m etcd_tpu.tools.dump_logs <data-dir>
"""
from __future__ import annotations

import json
import sys
from typing import Optional, Sequence

from etcd_tpu import raftpb
from etcd_tpu.raftpb import ConfChangeType, EntryType
from etcd_tpu.server.request import Request
from etcd_tpu.snap import Snapshotter
from etcd_tpu.wal import WAL, WalSnapshot


def _describe_entry(e) -> str:
    if e.type == EntryType.CONF_CHANGE:
        cc = raftpb.decode_conf_change(e.data)
        kind = ConfChangeType(cc.type).name
        ctx = ""
        if cc.context:
            try:
                ctx = " " + json.dumps(json.loads(cc.context.decode()))
            except (ValueError, UnicodeDecodeError):
                ctx = f" <{len(cc.context)}B context>"
        return (f"{e.term}\t{e.index}\tconf\t{kind} "
                f"{cc.node_id:x}{ctx}")
    if not e.data:
        return f"{e.term}\t{e.index}\tnorm\t<empty>"
    try:
        r = Request.decode(e.data)
        detail = f"{r.method} {r.path}"
        if r.val:
            v = r.val if len(r.val) <= 32 else r.val[:29] + "..."
            detail += f" val={v!r}"
        if r.prev_exist is not None:
            detail += f" prevExist={r.prev_exist}"
        return f"{e.term}\t{e.index}\tnorm\t{detail}"
    except Exception:
        return f"{e.term}\t{e.index}\tnorm\t<{len(e.data)}B undecodable>"


def dump(data_dir: str, out=sys.stdout) -> int:
    import os
    snapdir = os.path.join(data_dir, "member", "snap")
    waldir = os.path.join(data_dir, "member", "wal")
    if not os.path.isdir(waldir):
        print(f"no member/wal under {data_dir}", file=sys.stderr)
        return 1

    walsnap = WalSnapshot()
    if os.path.isdir(snapdir):
        snap = Snapshotter(snapdir).load_or_none()
        if snap is not None:
            md = snap.metadata
            walsnap = WalSnapshot(index=md.index, term=md.term)
            print(f"Snapshot:\nterm={md.term} index={md.index} nodes="
                  f"{[f'{n:x}' for n in md.conf_state.nodes]}", file=out)
        else:
            print("Snapshot:\nempty", file=out)

    print("Start dumping log entries from snapshot.", file=out)
    w = WAL.open(waldir, walsnap, write=False)
    try:
        metadata, state, ents = w.read_all()
    finally:
        w.close()
    try:
        md = json.loads(metadata.decode())
        print(f"WAL metadata:\nnodeID={md['id']} clusterID="
              f"{md['clusterId']}", file=out)
    except (ValueError, KeyError):
        print(f"WAL metadata: <{len(metadata)}B>", file=out)
    print(f"WAL entries: {len(ents)}", file=out)
    if ents:
        print(f"lastIndex={ents[-1].index}", file=out)
    print("term\tindex\ttype\tdata", file=out)
    for e in ents:
        print(_describe_entry(e), file=out)
    print(f"HardState: term={state.term} vote={state.vote:x} "
          f"commit={state.commit}", file=out)
    return 0


def dump_engine(data_dir: str, out=sys.stdout) -> int:
    """Inspect a MultiEngine data dir: newest checkpoint summary + every
    WAL round record (HardState/ring delta counts, admitted entries with
    decoded payloads, membership flips)."""
    from etcd_tpu.server.engine import P_CONF, P_REQ
    from etcd_tpu.server.enginewal import CONF_ADD, EngineWAL

    w = EngineWAL(data_dir, fsync=False)
    ckpt_round, ckpt = w.load_checkpoint()
    if ckpt is not None:
        print(f"Checkpoint: round={ckpt_round} stores="
              f"{len(ckpt.get('stores', {}))} "
              f"pending_payloads={len(ckpt.get('payloads', []))}", file=out)
    else:
        print("Checkpoint: none", file=out)
    print("round\ths\tlast\tring\tentries/confs", file=out)
    n = 0
    for rec in w.replay(after_round=ckpt_round):
        n += 1
        detail = []
        for g, i, t, payload in rec.entries:
            kind = "?"
            body = ""
            if payload[:1] == bytes([P_REQ]):
                try:
                    r = Request.decode(payload[1:])
                    kind, body = "req", f"{r.method} {r.path}"
                except ValueError:
                    kind = "req<bad>"
            elif payload[:1] == bytes([P_CONF]):
                kind, body = "conf", payload[1:].decode(errors="replace")
            detail.append(f"g{g}@{i}.t{t} {kind} {body}".rstrip())
        for g, slot, op in rec.confs:
            detail.append(f"g{g} slot{slot} "
                          f"{'ADD' if op == CONF_ADD else 'REMOVE'}")
        print(f"{rec.round_no}\t{len(rec.hs_g)}\t{len(rec.last_g)}\t"
              f"{len(rec.ring_g)}\t{'; '.join(detail)}", file=out)
    print(f"{n} round records after checkpoint", file=out)
    return 0


def dump_v3(data_dir: str, out=sys.stdout) -> int:
    """Inspect a member's v3 backend: consistent index, revision span,
    live keys, leases (server/v3.py layout)."""
    import os
    import struct

    from etcd_tpu.server.v3 import (CONSISTENT_INDEX_KEY, LEASE_BUCKET,
                                    V3Applier, b64d)

    path = os.path.join(data_dir, "member", "v3", "kv.db")
    if not os.path.isfile(path):
        print(f"no member/v3/kv.db under {data_dir}", file=sys.stderr)
        return 1
    a = V3Applier(path)
    try:
        kv = a.kv
        print(f"consistentIndex={a.consistent_index}", file=out)
        print(f"currentRev={kv.current_rev.main} "
              f"compactedRev={kv.compact_main_rev}", file=out)
        kvs, rev = kv.range(b"", b"\x00")   # whole keyspace
        print(f"live keys at rev {rev}: {len(kvs)}", file=out)
        print("key\tcreate\tmod\tver\tbytes", file=out)
        for item in kvs:
            print(f"{item.key.decode(errors='replace')}\t"
                  f"{item.create_rev}\t{item.mod_rev}\t{item.version}\t"
                  f"{len(item.value)}", file=out)
        print(f"leases: {len(a.leases)}", file=out)
        for lid, rec in sorted(a.leases.items()):
            keys = [b64d(k).decode(errors="replace") for k in rec["keys"]]
            print(f"lease {lid:x}: ttl={rec['ttl']} seq={rec['seq']} "
                  f"keys={keys}", file=out)
    finally:
        a.close()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("--engine", "--v3"):
        if len(argv) != 2:
            print(f"usage: python -m etcd_tpu.tools.dump_logs {argv[0]} "
                  "<dir>", file=sys.stderr)
            return 2
        return (dump_engine if argv[0] == "--engine" else dump_v3)(argv[1])
    if len(argv) != 1:
        print("usage: python -m etcd_tpu.tools.dump_logs [--engine|--v3] "
              "<data-dir>", file=sys.stderr)
        return 2
    return dump(argv[0])


if __name__ == "__main__":
    sys.exit(main())
