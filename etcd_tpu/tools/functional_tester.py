"""Distributed chaos harness.

Behavioral equivalent of reference tools/functional-tester: `Agent`
daemons manage real member processes (etcd-agent/rpc.go start/stop/
restart/terminate/cleanup), a `Tester` controller loops rounds of failure
cases over a live cluster under continuous write load (`Stresser`,
etcd-tester/stresser.go), waiting for full health between cases
(etcd-tester/tester.go:31-75) and archiving+rebootstrapping on a stuck
round (tester.go cleanup). Failure classes match etcd-tester/failure.go:
kill-all, kill-majority, kill-one, kill-leader-for-long,
kill-one-for-long (snapshot catch-up), isolate-one, isolate-all.

Process control here is in-process (subprocess + signals) instead of a
net/rpc daemon: "kill" is SIGKILL, and "isolate" is SIGSTOP — a frozen
process drops off the network for peers exactly like the reference's
iptables DropPort (pkg/netutil/isolate_linux.go) while keeping its state
intact for SIGCONT recovery.
"""
from __future__ import annotations

import json
import logging
import os
import random
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable, List, NamedTuple, Optional, Sequence

log = logging.getLogger("functional-tester")


def _free_ports(n: int) -> List[int]:
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _get_json(url: str, timeout: float = 2.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


class Agent:
    """Manages one etcd-tpu member process (reference etcd-agent)."""

    def __init__(self, name: str, data_dir: str, peer_url: str,
                 client_url: str, initial_cluster: str,
                 heartbeat_ms: int = 20, election_ms: int = 200,
                 snapshot_count: int = 1000,
                 log_dir: Optional[str] = None) -> None:
        self.name = name
        self.data_dir = data_dir
        self.peer_url = peer_url
        self.client_url = client_url
        self.initial_cluster = initial_cluster
        self.heartbeat_ms = heartbeat_ms
        self.election_ms = election_ms
        self.snapshot_count = snapshot_count
        self.log_path = os.path.join(log_dir or data_dir + "-logs",
                                     f"{name}.log")
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        self.proc: Optional[subprocess.Popen] = None
        self._isolated = False

    def _args(self) -> List[str]:
        return ["--name", self.name, "--data-dir", self.data_dir,
                "--listen-peer-urls", self.peer_url,
                "--initial-advertise-peer-urls", self.peer_url,
                "--listen-client-urls", self.client_url,
                "--advertise-client-urls", self.client_url,
                "--initial-cluster", self.initial_cluster,
                "--heartbeat-interval", str(self.heartbeat_ms),
                "--election-timeout", str(self.election_ms),
                "--snapshot-count", str(self.snapshot_count)]

    def start(self) -> None:
        env = dict(os.environ, PYTHONPATH=os.pathsep.join(
            [p for p in (os.environ.get("PYTHONPATH"),
                         os.path.dirname(os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__)))))
             if p]), JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "etcd_tpu"] + self._args(),
            stdout=open(self.log_path, "ab"), stderr=subprocess.STDOUT,
            env=env)
        self._isolated = False

    def stop(self) -> None:
        """Hard-kill the member ("kill" failure class)."""
        if self.proc is not None:
            if self._isolated:
                self.unisolate()
            self.proc.kill()
            self.proc.wait()
            self.proc = None

    def restart(self) -> None:
        if self.proc is None:
            self.start()

    def terminate(self) -> None:
        """Stop + wipe data (reference agent Terminate)."""
        self.stop()
        shutil.rmtree(self.data_dir, ignore_errors=True)

    def cleanup(self) -> None:
        """Stop + archive the data dir for postmortem, leaving a fresh slate
        (reference agent Cleanup archives to a failure_archive)."""
        self.stop()
        if os.path.isdir(self.data_dir):
            archive = f"{self.data_dir}.failure_archive.{int(time.time())}"
            shutil.move(self.data_dir, archive)

    def isolate(self) -> None:
        """Freeze the process — it vanishes from the network while keeping
        state (the SIGSTOP analogue of iptables DropPort)."""
        if self.proc is not None and not self._isolated:
            os.kill(self.proc.pid, signal.SIGSTOP)
            self._isolated = True

    def unisolate(self) -> None:
        if self.proc is not None and self._isolated:
            os.kill(self.proc.pid, signal.SIGCONT)
            self._isolated = False

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def healthy(self) -> bool:
        try:
            return _get_json(self.client_url + "/health").get(
                "health") == "true"
        except Exception:
            return False


class Cluster:
    """N agents + bootstrap/health plumbing (reference etcd-tester
    cluster.go)."""

    def __init__(self, size: int, base_dir: str, heartbeat_ms: int = 20,
                 election_ms: int = 200, snapshot_count: int = 1000,
                 health_timeout: float = 60.0) -> None:
        self.size = size
        self.base_dir = base_dir
        # Member subprocesses pay a multi-second JAX import on every
        # (re)start and share CPUs with whatever else runs (a full pytest
        # session, the reference CI's parallel jobs) — callers under heavy
        # contention raise this (reference tester budgets minutes/round).
        self.health_timeout = health_timeout
        ports = _free_ports(2 * size)
        peer_urls = [f"http://127.0.0.1:{ports[i]}" for i in range(size)]
        client_urls = [f"http://127.0.0.1:{ports[size + i]}"
                       for i in range(size)]
        ic = ",".join(f"m{i}={peer_urls[i]}" for i in range(size))
        self.agents = [
            Agent(f"m{i}", os.path.join(base_dir, f"m{i}"), peer_urls[i],
                  client_urls[i], ic, heartbeat_ms, election_ms,
                  snapshot_count, log_dir=os.path.join(base_dir, "logs"))
            for i in range(size)]

    def bootstrap(self) -> None:
        for a in self.agents:
            a.start()
        self.wait_health()

    def wait_health(self, timeout: Optional[float] = None) -> None:
        """All running members healthy (reference cluster.WaitHealth)."""
        deadline = time.time() + (timeout if timeout is not None
                                  else self.health_timeout)
        while time.time() < deadline:
            if all(a.healthy() for a in self.agents if a.running):
                if any(a.running for a in self.agents):
                    return
            time.sleep(0.25)
        raise TimeoutError("cluster did not become healthy")

    def leader_index(self) -> Optional[int]:
        for i, a in enumerate(self.agents):
            if not a.running:
                continue
            try:
                st = _get_json(a.client_url + "/v2/stats/self")
                if st.get("state") == "StateLeader":
                    return i
            except Exception:
                continue
        return None

    def client_endpoints(self) -> List[str]:
        return [a.client_url for a in self.agents if a.running]

    def cleanup_and_rebootstrap(self) -> None:
        for a in self.agents:
            a.cleanup()
        self.bootstrap()

    def stop(self) -> None:
        for a in self.agents:
            a.stop()


class Stresser:
    """Continuous write load during failures (reference stresser.go):
    N threads PUT random suffixed keys with `key_size` values."""

    def __init__(self, endpoints: Sequence[str], n: int = 4,
                 key_size: int = 64, key_suffix_range: int = 100) -> None:
        self.endpoints = list(endpoints)
        self.n = n
        self.key_size = key_size
        self.key_suffix_range = key_suffix_range
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.success = 0
        self.failure = 0
        self._threads: List[threading.Thread] = []

    def _loop(self, seed: int) -> None:
        rng = random.Random(seed)
        body = ("value=" + "x" * self.key_size).encode()
        while not self._stop.is_set():
            ep = rng.choice(self.endpoints)
            key = f"/v2/keys/stress-{rng.randrange(self.key_suffix_range)}"
            req = urllib.request.Request(
                ep + key, data=body, method="PUT",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            try:
                with urllib.request.urlopen(req, timeout=1.0) as r:
                    ok = r.status < 400
            except Exception:
                ok = False
            with self._lock:
                if ok:
                    self.success += 1
                else:
                    self.failure += 1

    def stress(self) -> None:
        self._stop.clear()
        self._threads = [threading.Thread(target=self._loop, args=(i,),
                                          daemon=True)
                         for i in range(self.n)]
        for t in self._threads:
            t.start()

    def cancel(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=3)

    def report(self):
        with self._lock:
            return self.success, self.failure


# -- failure cases (reference etcd-tester/failure.go:25-228) -----------------

class Failure(NamedTuple):
    desc: str
    inject: Callable[[Cluster, int], None]
    recover: Callable[[Cluster, int], None]


def _kill_all(c: Cluster, r: int) -> None:
    for a in c.agents:
        a.stop()


def _recover_all(c: Cluster, r: int) -> None:
    for a in c.agents:
        a.restart()
    c.wait_health()


def _to_kill(size: int, seed: int) -> List[int]:
    rng = random.Random(seed)
    majority = size // 2 + 1
    picked: set = set()
    while len(picked) < majority:
        picked.add(rng.randrange(size))
    return sorted(picked)


def _kill_majority(c: Cluster, r: int) -> None:
    for i in _to_kill(c.size, r):
        c.agents[i].stop()


def _recover_majority(c: Cluster, r: int) -> None:
    for i in _to_kill(c.size, r):
        c.agents[i].restart()
    c.wait_health()


def _kill_one(c: Cluster, r: int) -> None:
    c.agents[r % c.size].stop()


def _recover_one(c: Cluster, r: int) -> None:
    c.agents[r % c.size].restart()
    c.wait_health()


def _kill_leader_long(c: Cluster, r: int) -> None:
    i = c.leader_index()
    c._last_leader = i if i is not None else r % c.size
    c.agents[c._last_leader].stop()
    time.sleep(2.0)  # long outage: the rest must re-elect and make progress


def _recover_leader_long(c: Cluster, r: int) -> None:
    c.agents[c._last_leader].restart()
    c.wait_health()


def _kill_one_long(c: Cluster, r: int) -> None:
    """Down long enough that catch-up needs a snapshot (snapshot_count is
    set low; the stresser keeps writing meanwhile)."""
    c.agents[r % c.size].stop()
    time.sleep(3.0)


def _isolate_one(c: Cluster, r: int) -> None:
    c.agents[r % c.size].isolate()
    time.sleep(1.0)


def _unisolate_one(c: Cluster, r: int) -> None:
    c.agents[r % c.size].unisolate()
    c.wait_health()


def _isolate_all(c: Cluster, r: int) -> None:
    for a in c.agents:
        a.isolate()
    time.sleep(1.0)


def _unisolate_all(c: Cluster, r: int) -> None:
    for a in c.agents:
        a.unisolate()
    c.wait_health()


FAILURES: List[Failure] = [
    Failure("kill all members", _kill_all, _recover_all),
    Failure("kill majority of the cluster", _kill_majority,
            _recover_majority),
    Failure("kill one random member", _kill_one, _recover_one),
    Failure("kill leader for long time", _kill_leader_long,
            _recover_leader_long),
    Failure("kill one member for long time (snapshot catch-up)",
            _kill_one_long, _recover_one),
    Failure("isolate one member", _isolate_one, _unisolate_one),
    Failure("isolate all members", _isolate_all, _unisolate_all),
]


class Tester:
    """Round loop (reference tester.go runLoop): per round, run every
    failure case against a healthy cluster under stress; on any error,
    archive data dirs and re-bootstrap."""

    def __init__(self, cluster: Cluster,
                 failures: Optional[List[Failure]] = None,
                 rounds: int = 1, progress_timeout: float = 90.0) -> None:
        self.cluster = cluster
        self.failures = failures if failures is not None else FAILURES
        self.rounds = rounds
        self.progress_timeout = progress_timeout
        self.round = 0
        self.case = 0
        self.succeeded = 0
        self.failed = 0

    def run_loop(self) -> None:
        stresser = Stresser(self.cluster.client_endpoints())
        stresser.stress()
        try:
            for i in range(self.rounds):
                self.round = i
                for j, f in enumerate(self.failures):
                    self.case = j
                    tag = f"[round#{i} case#{j}]"
                    try:
                        self.cluster.wait_health()
                        log.info("%s injecting: %s", tag, f.desc)
                        f.inject(self.cluster, i)
                        log.info("%s recovering: %s", tag, f.desc)
                        f.recover(self.cluster, i)
                        self._verify_progress()
                        log.info("%s succeed!", tag)
                        self.succeeded += 1
                    except Exception as e:
                        log.warning("%s FAILED (%s); cleaning up", tag, e)
                        self.failed += 1
                        self.cluster.cleanup_and_rebootstrap()
        finally:
            stresser.cancel()
        s, fcount = stresser.report()
        log.info("stresser: %d success, %d failure writes", s, fcount)

    def _verify_progress(self) -> None:
        """After recovery the cluster must commit NEW writes on every
        member's endpoint (the reference's health+progress bar)."""
        import urllib.parse
        for a in self.cluster.agents:
            if not a.running:
                continue
            body = urllib.parse.urlencode(
                {"value": f"progress-{time.time()}"}).encode()
            req = urllib.request.Request(
                a.client_url + "/v2/keys/tester-progress", data=body,
                method="PUT",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            # Generous: member subprocesses share CPUs with the test
            # runner; the reference tester budgets minutes per round
            # (etcd-tester/tester.go round deadlines).
            deadline = time.time() + self.progress_timeout
            while True:
                try:
                    with urllib.request.urlopen(req, timeout=2.0) as r:
                        if r.status < 400:
                            break
                except Exception:
                    pass
                if time.time() > deadline:
                    raise TimeoutError(
                        f"member {a.name} makes no progress")
                time.sleep(0.25)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import tempfile
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s: %(message)s")
    ap = argparse.ArgumentParser(prog="etcd-tpu-functional-tester")
    ap.add_argument("--size", type=int, default=3)
    ap.add_argument("--rounds", type=int, default=1)
    ap.add_argument("--base-dir", default="")
    ns = ap.parse_args(argv)
    base = ns.base_dir or tempfile.mkdtemp(prefix="etcd-tpu-tester-")
    c = Cluster(ns.size, base)
    c.bootstrap()
    t = Tester(c, rounds=ns.rounds)
    try:
        t.run_loop()
    finally:
        c.stop()
    print(json.dumps({"rounds": ns.rounds, "cases": len(t.failures),
                      "succeeded": t.succeeded, "failed": t.failed}))
    return 0 if t.failed == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
