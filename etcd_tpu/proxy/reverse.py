"""The request-forwarding half of proxy mode.

Behavioral equivalent of reference proxy/reverse.go + proxy.go: buffer the
client request body once, strip single-hop headers (reverse.go:24-44), try
each available endpoint in director order — marking an endpoint failed and
moving on when the dial/send errors (reverse.go:113-127) — and relay the
first successful response. 503 when zero endpoints are available
(reverse.go:84-91), 502 when every endpoint fails (reverse.go:131-137).

Like the reference (whose proxy transport has no response deadline and
cancels the upstream request when the client goes away,
reverse.go:93-108), a dial gets a short timeout but the response read is
unbounded — v2 watch long-polls park here until the member answers — and a
watchdog cancels the upstream socket once the downstream client
disconnects. Chunked upstream responses (stream watches) are re-chunked
through instead of buffered.

``readonly`` wraps a handler to reject non-GETs with 501 (proxy.go:48-63).
"""
from __future__ import annotations

import http.client
import json
import socket
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple
from urllib.parse import urlsplit

from etcd_tpu.etcdhttp.web import Ctx
from etcd_tpu.proxy.director import Director

# RFC 2616 hop-by-hop headers the reference strips (reverse.go:24-35).
SINGLE_HOP_HEADERS = {"connection", "keep-alive", "proxy-authenticate",
                      "proxy-authorization", "te", "trailers",
                      "transfer-encoding", "upgrade"}


def _clean_headers(src) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for k, v in src.items():
        if k.lower() not in SINGLE_HOP_HEADERS and k.lower() != "host":
            out[k] = v
    return out


class ReverseProxy:
    """Install as a catch-all route: ``router.add("/", proxy.handle)``."""

    def __init__(self, director: Director, dial_timeout: float = 5.0,
                 tls_context=None) -> None:
        self.director = director
        self.dial_timeout = dial_timeout
        # ssl context for https:// upstream endpoints (reference startProxy
        # wires the client TLSInfo into the outbound transport).
        self.tls_context = tls_context

    def handle(self, ctx: Ctx, suffix: str) -> None:
        endpoints = self.director.endpoints()
        if not endpoints:
            ctx.send_json(503, {"message":
                                "proxy: zero endpoints currently available"})
            return

        headers = _clean_headers(ctx.headers)
        # X-Forwarded-For chain (reverse.go maybeSetForwardedFor).
        client_ip = ctx.remote_addr().rsplit(":", 1)[0]
        prior = headers.get("X-Forwarded-For")
        headers["X-Forwarded-For"] = (f"{prior}, {client_ip}" if prior
                                      else client_ip)

        # Original request target including the query string.
        target = ctx._h.path

        for ep in endpoints:
            conn = self._dial_and_send(ep.url, ctx.method, target, ctx.body,
                                       headers)
            if conn is None:
                # Dial/send failure: this member is down — quarantine and
                # fail over (reverse.go:119-126).
                ep.failed()
                continue
            self._relay(ctx, conn)
            return

        ctx.send_json(502, {"message":
                            f"proxy: unable to get response from "
                            f"{len(endpoints)} endpoint(s)"})

    def _dial_and_send(self, base: str, method: str, target: str,
                       body: bytes, headers: Dict[str, str]
                       ) -> Optional[http.client.HTTPConnection]:
        from etcd_tpu.utils.tlsutil import open_conn
        conn = open_conn(base, self.dial_timeout, self.tls_context)
        try:
            conn.connect()
            # Dial succeeded — lift the deadline so long-polls can park.
            conn.sock.settimeout(None)
            conn.request(method, target, body=body or None, headers=headers)
            return conn
        except OSError:
            conn.close()
            return None

    def _relay(self, ctx: Ctx, conn: http.client.HTTPConnection) -> None:
        """Wait for the upstream response (unbounded — watch long-polls),
        then relay it; chunked responses stream through. A watchdog severs
        the upstream socket when the downstream client disconnects (the
        CloseNotify/CancelRequest pair of reverse.go:93-108)."""
        done = threading.Event()

        def watchdog() -> None:
            while not done.wait(2.0):
                if ctx.client_gone():
                    try:
                        conn.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    conn.close()
                    return

        t = threading.Thread(target=watchdog, daemon=True,
                             name="proxy-watchdog")
        t.start()
        try:
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException):
            # Watchdog cancel or upstream died mid-response: nothing useful
            # to relay; the endpoint already answered the dial, so no
            # quarantine.
            done.set()
            conn.close()
            return

        rheaders = dict(resp.getheaders())
        passthrough = {k: v for k, v in rheaders.items()
                       if k.lower() not in SINGLE_HOP_HEADERS and
                       k.lower() not in ("content-type", "content-length")}
        ctype = rheaders.get("Content-Type", "text/plain")
        try:
            if resp.chunked:
                ctx.begin_stream(resp.status, ctype, passthrough)
                while True:
                    chunk = resp.read(4096)
                    if not chunk:
                        ctx.end_stream()
                        return
                    if not ctx.write_chunk(chunk):
                        return
            else:
                ctx.send(resp.status, resp.read(), ctype, passthrough)
        except (OSError, http.client.HTTPException):
            pass
        finally:
            done.set()
            conn.close()


def readonly(handler: Callable[[Ctx, str], None]) -> Callable[[Ctx, str], None]:
    """Reject mutating methods with 501 (reference proxy.go:54-63)."""
    def wrapped(ctx: Ctx, suffix: str) -> None:
        if ctx.method != "GET":
            ctx.send(501)
            return
        handler(ctx, suffix)
    return wrapped


def fetch_cluster_urls(peer_urls: Iterable[str], timeout: float = 2.0,
                       tls_context=None) -> Tuple[List[str], List[str]]:
    """GET /members from each peer until one answers; return
    (client_urls, peer_urls) of the cluster — the proxy's view-refresh
    primitive (reference cluster_util.go:54-98 GetClusterFromRemotePeers,
    used by etcdmain/etcd.go:288-323 startProxy's urls func)."""
    from etcd_tpu.utils.tlsutil import open_conn
    for base in peer_urls:
        try:
            conn = open_conn(base, timeout, tls_context)
            try:
                conn.request("GET", "/members")
                resp = conn.getresponse()
                if resp.status != 200:
                    continue
                data = json.loads(resp.read().decode())
            finally:
                conn.close()
        except (OSError, ValueError):
            continue
        members = data.get("members", [])
        curls = [c for m in members for c in m.get("clientURLs", [])]
        purls = [p for m in members for p in m.get("peerURLs", [])]
        if purls:
            return curls, purls
    return [], []
