from etcd_tpu.proxy.director import Director, Endpoint, write_cluster_file
from etcd_tpu.proxy.reverse import (ReverseProxy, fetch_cluster_urls,
                                    readonly)

__all__ = ["Director", "Endpoint", "ReverseProxy", "fetch_cluster_urls",
           "readonly", "write_cluster_file"]
