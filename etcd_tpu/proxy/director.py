"""Endpoint set management for the stateless proxy mode.

Behavioral equivalent of reference proxy/director.go: a background refresh
loop re-queries the cluster for client URLs every ``refresh_interval``
(30s there, director.go:31), a failed endpoint is quarantined for
``failure_wait`` (5s, director.go:28) before being reconsidered, and the
endpoint list is shuffled on refresh so connections don't pile onto one
member (director.go:69-73).
"""
from __future__ import annotations

import random
import threading
from typing import Callable, List, Sequence


class Endpoint:
    def __init__(self, url: str, failure_wait: float) -> None:
        self.url = url.rstrip("/")
        self._failure_wait = failure_wait
        self._lock = threading.Lock()
        self._available = True

    @property
    def available(self) -> bool:
        with self._lock:
            return self._available

    def failed(self) -> None:
        """Quarantine this endpoint; a timer restores it (director.go:107-135)."""
        with self._lock:
            if not self._available:
                return
            self._available = False
        t = threading.Timer(self._failure_wait, self._restore)
        t.daemon = True
        t.start()

    def _restore(self) -> None:
        with self._lock:
            self._available = True


class Director:
    """Maintains the live endpoint list from a ``urls_func`` snapshot."""

    def __init__(self, urls_func: Callable[[], Sequence[str]],
                 refresh_interval: float = 30.0,
                 failure_wait: float = 5.0) -> None:
        self._uf = urls_func
        self._failure_wait = failure_wait
        self._refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._eps: List[Endpoint] = []
        self._stop = threading.Event()
        self.refresh()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="proxy-director")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._refresh_interval):
            try:
                self.refresh()
            except Exception:
                pass

    def refresh(self) -> None:
        urls = list(self._uf() or ())
        eps = [Endpoint(u, self._failure_wait) for u in urls]
        random.shuffle(eps)
        with self._lock:
            self._eps = eps

    def endpoints(self) -> List[Endpoint]:
        with self._lock:
            return [ep for ep in self._eps if ep.available]

    def stop(self) -> None:
        self._stop.set()


# The proxy's subdirectory inside a data dir; etcdmain's DIR_PROXY and
# every cluster-file path derive from this single definition.
PROXY_DIR_NAME = "proxy"


def write_cluster_file(data_dir: str, peer_urls) -> str:
    """Atomically persist the proxy's endpoint view at
    <data_dir>/proxy/cluster — THE schema ProxyServer boots from and
    refreshes (single owner of the file format; the standby migration
    writes through here too). Returns the file path."""
    import json
    import os
    proxy_dir = os.path.join(data_dir, PROXY_DIR_NAME)
    os.makedirs(proxy_dir, exist_ok=True)
    path = os.path.join(proxy_dir, "cluster")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"PeerURLs": list(peer_urls)}, f)
    os.replace(tmp, path)
    return path
