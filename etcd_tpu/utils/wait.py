"""The propose→apply rendezvous registry (reference pkg/wait/wait.go:21-58).

A proposer registers a request id and blocks on the returned queue; the apply
loop triggers the id with the result once the entry commits and applies.
Thread-safe: proposers are HTTP handler threads, the trigger side is the
single run-loop thread.
"""
from __future__ import annotations

import queue
from typing import Any, Dict, Optional


class Wait:
    """Lock-free on the hot path: CPython dict setdefault/pop are
    GIL-atomic, and trigger() sits on the apply loop's per-request path
    (profiled), so the registry rides the GIL instead of a Lock."""

    def __init__(self) -> None:
        self._waiters: Dict[int, "queue.Queue[Any]"] = {}

    def register(self, wid: int) -> "queue.Queue[Any]":
        q: "queue.Queue[Any]" = queue.Queue(maxsize=1)
        if self._waiters.setdefault(wid, q) is not q:
            raise ValueError(f"duplicate wait id {wid:x}")
        return q

    def trigger(self, wid: int, value: Any) -> bool:
        q = self._waiters.pop(wid, None)
        if q is None:
            return False
        q.put(value)
        return True

    def is_registered(self, wid: int) -> bool:
        return wid in self._waiters

    def cancel(self, wid: int) -> None:
        self._waiters.pop(wid, None)
