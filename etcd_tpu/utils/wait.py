"""The propose→apply rendezvous registry (reference pkg/wait/wait.go:21-58).

A proposer registers a request id and blocks on the returned queue; the apply
loop triggers the id with the result once the entry commits and applies.
Thread-safe: proposers are HTTP handler threads, the trigger side is the
single run-loop thread.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional


class Wait:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._waiters: Dict[int, "queue.Queue[Any]"] = {}

    def register(self, wid: int) -> "queue.Queue[Any]":
        with self._lock:
            if wid in self._waiters:
                raise ValueError(f"duplicate wait id {wid:x}")
            q: "queue.Queue[Any]" = queue.Queue(maxsize=1)
            self._waiters[wid] = q
            return q

    def trigger(self, wid: int, value: Any) -> bool:
        with self._lock:
            q = self._waiters.pop(wid, None)
        if q is None:
            return False
        q.put(value)
        return True

    def is_registered(self, wid: int) -> bool:
        with self._lock:
            return wid in self._waiters

    def cancel(self, wid: int) -> None:
        with self._lock:
            self._waiters.pop(wid, None)
