"""TLS plumbing for listeners, the peer transport, clients and the proxy.

Behavioral equivalent of reference pkg/transport (listener.go:28-,
transport.go): a TLSInfo {cert, key, trusted CA, client-cert-auth} that can
mint a server-side or client-side context. Python's ssl module replaces Go's
crypto/tls; the same files and the same verification semantics apply:

- server: presents cert/key; with `client_cert_auth` (or a CA given for the
  peer listener) it REQUIRES and verifies client certificates against the CA
  (reference ClientConfig/ServerConfig split, listener.go:200-233).
- client: verifies the server against the CA; presents cert/key when given
  (mutual TLS between peers, reference transport.go NewTransport).
"""
from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class TLSInfo:
    cert_file: str = ""
    key_file: str = ""
    ca_file: str = ""          # trusted CA for verifying the other side
    client_cert_auth: bool = False

    def empty(self) -> bool:
        return not (self.cert_file or self.key_file or self.ca_file)

    def server_context(self) -> ssl.SSLContext:
        """Context for a listening socket (reference ServerConfig
        listener.go:213-233)."""
        if not (self.cert_file and self.key_file):
            raise ValueError(
                "TLS listener requires both cert_file and key_file "
                f"(got cert={self.cert_file!r} key={self.key_file!r})")
        if self.client_cert_auth and not self.ca_file:
            raise ValueError("client_cert_auth requires ca_file")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.ca_file:
            # A trusted CA on a listener ALWAYS requires and verifies
            # client certificates (reference listener.go:222-228: CAFile
            # implies tls.RequireAndVerifyClientCert) — CERT_OPTIONAL would
            # silently admit unauthenticated peers.
            ctx.load_verify_locations(self.ca_file)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Context for dialing out (reference ClientConfig
        listener.go:200-211)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
            ctx.check_hostname = False  # peers dial IPs; CA pinning is the gate
        else:
            # No CA: encrypted but unauthenticated (reference
            # InsecureSkipVerify when trusted CA absent).
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.cert_file and self.key_file:
            ctx.load_cert_chain(self.cert_file, self.key_file)
        return ctx


def client_context_or_none(info: Optional["TLSInfo"]) -> Optional[ssl.SSLContext]:
    if info is None or info.empty():
        return None
    return info.client_context()


def open_conn(url: str, timeout: float, tls_context=None):
    """http.client connection for `url`, TLS-aware: HTTPSConnection with
    the given context for https://, plain HTTPConnection otherwise. The
    single construction point for every outbound TLS-capable dialer
    (peer /members fetches, proxy upstream relay)."""
    import http.client
    from urllib.parse import urlsplit

    u = urlsplit(url)
    if u.scheme == "https":
        return http.client.HTTPSConnection(u.hostname, u.port,
                                           timeout=timeout,
                                           context=tls_context)
    return http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
