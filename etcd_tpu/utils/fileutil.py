"""Filesystem helpers shared by the durability layer.

Behavioral equivalent of reference pkg/fileutil (dir perms, exclusive file
locks pkg/fileutil/lock_unix.go, retention loop pkg/fileutil/purge.go),
re-designed for a synchronous Python host loop: PurgeKeeper is drained
explicitly by the server's housekeeping tick instead of running a goroutine.
"""
from __future__ import annotations

import errno
import fcntl
import os
from typing import List, Optional

PRIVATE_DIR_MODE = 0o700
PRIVATE_FILE_MODE = 0o600


class LockError(OSError):
    """Another process holds the lock (reference fileutil.ErrLocked)."""


class LockedFile:
    """A file opened with an exclusive (non-blocking) flock, as the reference
    takes on every live WAL segment (pkg/fileutil/lock_unix.go)."""

    def __init__(self, path: str, flags: int = os.O_RDWR,
                 mode: int = PRIVATE_FILE_MODE) -> None:
        self.path = path
        self.fd = os.open(path, flags, mode)
        try:
            fcntl.flock(self.fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(self.fd)
            if e.errno in (errno.EAGAIN, errno.EACCES, errno.EWOULDBLOCK):
                raise LockError(e.errno, f"file already locked: {path}")
            raise

    def close(self) -> None:
        if self.fd >= 0:
            try:
                fcntl.flock(self.fd, fcntl.LOCK_UN)
            finally:
                os.close(self.fd)
                self.fd = -1

    def __enter__(self) -> "LockedFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def is_dir_writable(d: str) -> bool:
    probe = os.path.join(d, ".touch")
    try:
        with open(probe, "w"):
            pass
        os.remove(probe)
        return True
    except OSError:
        return False


def create_dir_all(d: str) -> None:
    """mkdir -p, then insist it is empty (reference fileutil.CreateDirAll)."""
    touch_dir_all(d)
    if os.listdir(d):
        raise OSError(f"expected {d!r} to be empty, got {os.listdir(d)!r}")


def touch_dir_all(d: str) -> None:
    os.makedirs(d, mode=PRIVATE_DIR_MODE, exist_ok=True)
    if not is_dir_writable(d):
        raise OSError(f"directory {d!r} is not writable")


def read_dir(d: str) -> List[str]:
    """Sorted directory listing (reference fileutil.ReadDir)."""
    return sorted(os.listdir(d))


def fsync(fd: int) -> None:
    os.fsync(fd)


def fsync_dir(d: str) -> None:
    """Durably record directory entries (new/renamed files)."""
    dfd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def purge_files(dirname: str, suffix: str, keep: int) -> List[str]:
    """Remove the oldest `suffix` files beyond the newest `keep`, skipping any
    that are still flock-held (reference pkg/fileutil/purge.go semantics,
    invoked from the server's housekeeping tick rather than a goroutine).
    Returns the paths removed."""
    names = [n for n in read_dir(dirname) if n.endswith(suffix)]
    removed: List[str] = []
    while len(names) > keep:
        victim = os.path.join(dirname, names.pop(0))
        try:
            lock = LockedFile(victim)
        except LockError:
            break  # oldest is in use; newer ones are too
        except FileNotFoundError:
            continue
        try:
            os.remove(victim)
            removed.append(victim)
        finally:
            lock.close()
    return removed
