"""Minimal Prometheus-style metrics registry.

Behavioral equivalent of the reference's vendored prometheus client as used
by etcdserver/metrics.go, wal/metrics.go, snap/metrics.go and
rafthttp/metrics.go: counters, gauges, and summaries (count/sum + live
quantiles over a sliding window) rendered in the Prometheus text exposition
format at /metrics. Pure stdlib; thread-safe.
"""
from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 registry: Optional["Registry"] = None) -> None:
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        (registry or REGISTRY).register(self)

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        raise NotImplementedError


class _NullRegistry:
    """Sentinel registry for child metrics a labeled parent exposes itself."""

    def register(self, m: "_Metric") -> None:
        pass


UNREGISTERED = _NullRegistry()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, registry=None) -> None:
        self._v = 0.0
        super().__init__(name, help_, registry)

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def samples(self):
        return [(self.name, {}, self.value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, registry=None) -> None:
        self._v = 0.0
        super().__init__(name, help_, registry)

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta

    def dec(self, delta: float = 1.0) -> None:
        self.inc(-delta)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def samples(self):
        return [(self.name, {}, self.value)]


class Histogram(_Metric):
    """A bucketed Prometheus histogram (`*_bucket{le=...}` + sum/count).

    Lock-light by design: observe() is two integer adds and a float add
    on thread-confined-or-GIL-serialized cells — no mutex on the hot
    path (the engine's round loop and writer/applier workers observe
    from their own threads at pipeline rate; the standard client's
    per-observation mutex is exactly the overhead the instrumentation
    A/B gate exists to forbid). Under CPython's GIL a concurrent
    increment can at worst lose single counts (never tear, never go
    backwards), which is inside monitoring noise; exposition derives
    `_count` from the bucket cells themselves so a scrape is always
    internally consistent (cumulative buckets monotone, +Inf == count).
    """

    kind = "histogram"

    # The prometheus client's DefBuckets, in seconds — fits both the
    # sub-ms engine phases and multi-ms fsyncs.
    DEFAULT = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
               0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT,
                 registry=None) -> None:
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)   # +Inf tail cell
        self._sum = 0.0
        super().__init__(name, help_, registry)

    def observe(self, v: float) -> None:
        # bisect over a small tuple beats a Python loop; no lock (see
        # class docstring).
        i = bisect.bisect_left(self.buckets, v)
        self._counts[i] += 1
        self._sum += v

    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    def samples(self):
        counts = list(self._counts)      # one snapshot, used throughout
        out = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append((self.name + "_bucket", {"le": repr(float(b))}, cum))
        cum += counts[-1]
        out.append((self.name + "_bucket", {"le": "+Inf"}, cum))
        out.append((self.name + "_sum", {}, self._sum))
        out.append((self.name + "_count", {}, cum))
        return out


class LabeledHistogram(_Metric):
    """A histogram vector keyed by one or more labels (e.g. the engine's
    per-compartment shard index, reference wal/snap metrics.go shape)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 buckets: Sequence[float] = Histogram.DEFAULT,
                 registry=None) -> None:
        self.label_names = tuple(label_names)
        self._buckets = buckets
        self._children: Dict[Tuple[str, ...], Histogram] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values) -> Histogram:
        key = tuple(str(v) for v in values)
        h = self._children.get(key)
        if h is None:
            with self._lock:
                h = self._children.get(key)
                if h is None:
                    h = Histogram(self.name, self.help, self._buckets,
                                  registry=UNREGISTERED)
                    self._children[key] = h
        return h

    def samples(self):
        out = []
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            lbls = dict(zip(self.label_names, key))
            for name, extra, v in child.samples():
                out.append((name, {**lbls, **extra}, v))
        return out


class LabeledGauge(_Metric):
    """A gauge vector keyed by one or more labels (per-shard queue depths
    and watermarks)."""

    kind = "gauge"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 registry=None) -> None:
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], Gauge] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values) -> Gauge:
        key = tuple(str(v) for v in values)
        g = self._children.get(key)
        if g is None:
            with self._lock:
                g = self._children.get(key)
                if g is None:
                    g = Gauge(self.name, self.help, registry=UNREGISTERED)
                    self._children[key] = g
        return g

    def samples(self):
        out = []
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            lbls = dict(zip(self.label_names, key))
            for name, extra, v in child.samples():
                out.append((name, {**lbls, **extra}, v))
        return out


class Summary(_Metric):
    """count/sum plus 0.5/0.9/0.99 quantiles over the last `window`
    observations (the prometheus client's default objectives)."""

    kind = "summary"
    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, name: str, help_: str, window: int = 1024,
                 registry=None) -> None:
        self._count = 0
        self._sum = 0.0
        self._window: deque = deque(maxlen=window)
        super().__init__(name, help_, registry)

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            self._window.append(v)

    def samples(self):
        with self._lock:
            vals = sorted(self._window)
            out = []
            for q in self.QUANTILES:
                if vals:
                    idx = min(len(vals) - 1, int(math.ceil(q * len(vals))) - 1)
                    out.append((self.name, {"quantile": str(q)},
                                vals[max(idx, 0)]))
                else:
                    out.append((self.name, {"quantile": str(q)},
                                float("nan")))
            out.append((self.name + "_sum", {}, self._sum))
            out.append((self.name + "_count", {}, self._count))
            return out


class LabeledSummary(_Metric):
    """A summary vector keyed by one label (e.g. sendingType or
    remoteID/sendingType, reference rafthttp/metrics.go)."""

    kind = "summary"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 window: int = 1024, registry=None) -> None:
        self.label_names = tuple(label_names)
        self._window = window
        self._children: Dict[Tuple[str, ...], Summary] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values: str) -> Summary:
        key = tuple(values)
        with self._lock:
            s = self._children.get(key)
            if s is None:
                s = Summary(self.name, self.help, self._window,
                            registry=UNREGISTERED)
                self._children[key] = s
            return s

    def samples(self):
        out = []
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            lbls = dict(zip(self.label_names, key))
            for name, extra, v in child.samples():
                out.append((name, {**lbls, **extra}, v))
        return out


class LabeledCounter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 registry=None) -> None:
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], float] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values: str) -> "_LabeledCounterChild":
        return _LabeledCounterChild(self, tuple(values))

    def _inc(self, key: Tuple[str, ...], delta: float) -> None:
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + delta

    def samples(self):
        with self._lock:
            return [(self.name, dict(zip(self.label_names, key)), v)
                    for key, v in self._children.items()]


class _LabeledCounterChild:
    def __init__(self, parent: LabeledCounter, key: Tuple[str, ...]) -> None:
        self._p = parent
        self._k = key

    def inc(self, delta: float = 1.0) -> None:
        self._p._inc(self._k, delta)


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, m: _Metric) -> None:
        with self._lock:
            # Idempotent by name so module reimports/multiple members in one
            # process share the series (the reference's MustRegister panics;
            # a shared-process test harness needs tolerance instead).
            self._metrics.setdefault(m.name, m)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    @staticmethod
    def _escape_label(val: str) -> str:
        """Text exposition format: label values escape backslash,
        double-quote, and line feed (in that order — backslash first so
        the escapes themselves survive)."""
        return (str(val).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _series_name(name: str, labels: Dict[str, str]) -> str:
        if not labels:
            return name
        lbl = ",".join(f'{k}="{Registry._escape_label(val)}"'
                       for k, val in sorted(labels.items()))
        return f"{name}{{{lbl}}}"

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            # HELP text escapes backslash and line feed (no quote escape).
            help_ = m.help.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {m.name} {help_}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, v in m.samples():
                series = self._series_name(name, labels)
                if isinstance(v, float) and math.isnan(v):
                    lines.append(f"{series} NaN")
                else:
                    lines.append(f"{series} {v}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat {series-with-labels: value} map of every finite sample.

        The bench uses before/after snapshots of this to cross-check its
        own BENCH columns against what /metrics would have reported.
        """
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for name, labels, v in m.samples():
                if isinstance(v, float) and math.isnan(v):
                    continue
                out[self._series_name(name, labels)] = float(v)
        return out


REGISTRY = Registry()

# -- the reference's metric set ----------------------------------------------

# etcdserver/metrics.go
propose_durations = Summary(
    "etcd_server_proposal_durations_milliseconds",
    "The latency distributions of committing proposal.")
propose_pending = Gauge(
    "etcd_server_pending_proposal_total",
    "The total number of pending proposals.")
propose_failed = Counter(
    "etcd_server_proposal_failed_total",
    "The total number of failed proposals.")
file_descriptors_used = Gauge(
    "etcd_server_file_descriptors_used_total",
    "The total number of file descriptors used.")

# wal/metrics.go
wal_fsync_durations = Summary(
    "etcd_wal_fsync_durations_microseconds",
    "The latency distributions of fsync called by wal.")
wal_last_index_saved = Gauge(
    "etcd_wal_last_index_saved",
    "The index of the last entry saved by wal.")

# snap/metrics.go
snap_save_durations = Summary(
    "etcd_snapshot_save_total_durations_microseconds",
    "The total latency distributions of save called by snapshot.")

# rafthttp/metrics.go
msg_sent_latency = LabeledSummary(
    "etcd_rafthttp_message_sent_latency_microseconds",
    "message sent latency distributions.",
    ("sendingType", "remoteID", "msgType"))
msg_sent_failed = LabeledCounter(
    "etcd_rafthttp_message_sent_failed_total",
    "The total number of failed messages sent.",
    ("sendingType", "remoteID", "msgType"))


def fd_usage() -> Tuple[int, int]:
    """(used, limit) file descriptors (reference pkg/runtime/fds_linux.go)."""
    import os
    import resource
    try:
        used = len(os.listdir("/proc/self/fd"))
    except OSError:
        used = -1
    limit = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    return used, limit
