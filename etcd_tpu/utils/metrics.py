"""Minimal Prometheus-style metrics registry.

Behavioral equivalent of the reference's vendored prometheus client as used
by etcdserver/metrics.go, wal/metrics.go, snap/metrics.go and
rafthttp/metrics.go: counters, gauges, and summaries (count/sum + live
quantiles over a sliding window) rendered in the Prometheus text exposition
format at /metrics. Pure stdlib; thread-safe.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 registry: Optional["Registry"] = None) -> None:
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        (registry or REGISTRY).register(self)

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        raise NotImplementedError


class _NullRegistry:
    """Sentinel registry for child metrics a labeled parent exposes itself."""

    def register(self, m: "_Metric") -> None:
        pass


UNREGISTERED = _NullRegistry()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, registry=None) -> None:
        self._v = 0.0
        super().__init__(name, help_, registry)

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def samples(self):
        return [(self.name, {}, self.value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, registry=None) -> None:
        self._v = 0.0
        super().__init__(name, help_, registry)

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta

    def dec(self, delta: float = 1.0) -> None:
        self.inc(-delta)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def samples(self):
        return [(self.name, {}, self.value)]


class Summary(_Metric):
    """count/sum plus 0.5/0.9/0.99 quantiles over the last `window`
    observations (the prometheus client's default objectives)."""

    kind = "summary"
    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, name: str, help_: str, window: int = 1024,
                 registry=None) -> None:
        self._count = 0
        self._sum = 0.0
        self._window: deque = deque(maxlen=window)
        super().__init__(name, help_, registry)

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            self._window.append(v)

    def samples(self):
        with self._lock:
            vals = sorted(self._window)
            out = []
            for q in self.QUANTILES:
                if vals:
                    idx = min(len(vals) - 1, int(math.ceil(q * len(vals))) - 1)
                    out.append((self.name, {"quantile": str(q)},
                                vals[max(idx, 0)]))
                else:
                    out.append((self.name, {"quantile": str(q)},
                                float("nan")))
            out.append((self.name + "_sum", {}, self._sum))
            out.append((self.name + "_count", {}, self._count))
            return out


class LabeledSummary(_Metric):
    """A summary vector keyed by one label (e.g. sendingType or
    remoteID/sendingType, reference rafthttp/metrics.go)."""

    kind = "summary"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 window: int = 1024, registry=None) -> None:
        self.label_names = tuple(label_names)
        self._window = window
        self._children: Dict[Tuple[str, ...], Summary] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values: str) -> Summary:
        key = tuple(values)
        with self._lock:
            s = self._children.get(key)
            if s is None:
                s = Summary(self.name, self.help, self._window,
                            registry=UNREGISTERED)
                self._children[key] = s
            return s

    def samples(self):
        out = []
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            lbls = dict(zip(self.label_names, key))
            for name, extra, v in child.samples():
                out.append((name, {**lbls, **extra}, v))
        return out


class LabeledCounter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 registry=None) -> None:
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], float] = {}
        super().__init__(name, help_, registry)

    def labels(self, *values: str) -> "_LabeledCounterChild":
        return _LabeledCounterChild(self, tuple(values))

    def _inc(self, key: Tuple[str, ...], delta: float) -> None:
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + delta

    def samples(self):
        with self._lock:
            return [(self.name, dict(zip(self.label_names, key)), v)
                    for key, v in self._children.items()]


class _LabeledCounterChild:
    def __init__(self, parent: LabeledCounter, key: Tuple[str, ...]) -> None:
        self._p = parent
        self._k = key

    def inc(self, delta: float = 1.0) -> None:
        self._p._inc(self._k, delta)


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, m: _Metric) -> None:
        with self._lock:
            # Idempotent by name so module reimports/multiple members in one
            # process share the series (the reference's MustRegister panics;
            # a shared-process test harness needs tolerance instead).
            self._metrics.setdefault(m.name, m)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, v in m.samples():
                if labels:
                    lbl = ",".join(f'{k}="{val}"'
                                   for k, val in sorted(labels.items()))
                    series = f"{name}{{{lbl}}}"
                else:
                    series = name
                if isinstance(v, float) and math.isnan(v):
                    lines.append(f"{series} NaN")
                else:
                    lines.append(f"{series} {v}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- the reference's metric set ----------------------------------------------

# etcdserver/metrics.go
propose_durations = Summary(
    "etcd_server_proposal_durations_milliseconds",
    "The latency distributions of committing proposal.")
propose_pending = Gauge(
    "etcd_server_pending_proposal_total",
    "The total number of pending proposals.")
propose_failed = Counter(
    "etcd_server_proposal_failed_total",
    "The total number of failed proposals.")
file_descriptors_used = Gauge(
    "etcd_server_file_descriptors_used_total",
    "The total number of file descriptors used.")

# wal/metrics.go
wal_fsync_durations = Summary(
    "etcd_wal_fsync_durations_microseconds",
    "The latency distributions of fsync called by wal.")
wal_last_index_saved = Gauge(
    "etcd_wal_last_index_saved",
    "The index of the last entry saved by wal.")

# snap/metrics.go
snap_save_durations = Summary(
    "etcd_snapshot_save_total_durations_microseconds",
    "The total latency distributions of save called by snapshot.")

# rafthttp/metrics.go
msg_sent_latency = LabeledSummary(
    "etcd_rafthttp_message_sent_latency_microseconds",
    "message sent latency distributions.",
    ("sendingType", "remoteID", "msgType"))
msg_sent_failed = LabeledCounter(
    "etcd_rafthttp_message_sent_failed_total",
    "The total number of failed messages sent.",
    ("sendingType", "remoteID", "msgType"))


def fd_usage() -> Tuple[int, int]:
    """(used, limit) file descriptors (reference pkg/runtime/fds_linux.go)."""
    import os
    import resource
    try:
        used = len(os.listdir("/proc/self/fd"))
    except OSError:
        used = -1
    limit = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    return used, limit
