"""Force JAX onto virtual CPU devices, robustly against this image's quirks.

The runtime image preloads jax at interpreter start (axon site hook), so by
the time user code runs, setting JAX_PLATFORMS in os.environ is too late for
the platform choice — the preloaded jax captured the ambient config whose
'axon' TPU backend dials a tunnel that can hang forever when unreachable.
The platform must be forced through jax.config.update; XLA_FLAGS is still
read lazily at CPU-client creation, so the device count rides the env var
(replacing any stale value already present).

Single source of truth for bench.py, __graft_entry__.py and
tests/conftest.py (they previously carried divergent copies).
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# Persistent XLA compile cache shared across processes AND driver rounds:
# the batched kernel's TPU compile measured ~235s at G=100k — without the
# cache a fresh bench process burns its whole budget compiling.
CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_compile_cache() -> None:
    """Turn on JAX's persistent compilation cache under the repo root.
    Safe to call multiple times / before or after backend init."""
    import jax

    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax or read-only fs: cache is an optimization only


def set_host_device_count(n: int) -> None:
    """Set (or raise to n) the virtual CPU device count in XLA_FLAGS.
    Only effective before the CPU backend is instantiated."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = flags[:m.start(1)] + str(n) + flags[m.end(1):]
    os.environ["XLA_FLAGS"] = flags


def force_cpu(n_devices: int = 1):
    """Force JAX onto >= n_devices virtual CPU devices regardless of the
    ambient platform config; returns the device list. If a backend was
    already instantiated on the wrong platform/count, clears and re-inits
    (best effort — goes through a private jax API)."""
    set_host_device_count(n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n_devices:
        try:
            from jax._src import api as _api
            _api.clear_backends()
        except Exception:
            pass
        else:
            devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n_devices:
        raise RuntimeError(
            f"cannot get {n_devices} cpu devices: have "
            f"{len(devs)} x {devs[0].platform}")
    return devs
