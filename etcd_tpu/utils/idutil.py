"""Cluster-unique request id generation (reference pkg/idutil/id.go:44-76).

Layout: [2 bytes member id suffix][5 bytes timestamp ms][1 byte counter
low bits] — ids from different members never collide, and one member's ids
are strictly increasing.
"""
from __future__ import annotations

import threading
import time


class Generator:
    def __init__(self, member_id: int, now_ms: int = None) -> None:
        self._lock = threading.Lock()
        prefix = (member_id & 0xFFFF) << 48
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        suffix = (now_ms & ((1 << 40) - 1)) << 8
        self._id = prefix | suffix

    def next(self) -> int:
        with self._lock:
            self._id += 1
            return self._id
