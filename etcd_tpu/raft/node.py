"""Single-group run protocol: the Ready/Advance seam between the pure FSM and
the application's I/O.

Behavioral equivalent of reference raft/node.go:52-463, redesigned without
goroutines/channels: the Node is a synchronous driver — the host event loop
calls tick()/step()/propose(), then drains ready() and acknowledges with
advance(). The prescribed ordering contract (reference raft/doc.go:28-55)
is unchanged: persist HardState+Entries BEFORE sending Messages; apply
CommittedEntries; then advance().

This synchronous shape is exactly what the batched MultiNode engine
(etcd_tpu/server/engine.py) needs: one host thread owns all group state, and
"channels" become dense per-tick batches.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import (ConfChange, ConfChangeType, ConfState, Entry,
                             EntryType, HardState, EMPTY_HARD_STATE, Message,
                             MessageType, Snapshot, SoftState, StateType)
from etcd_tpu.raft.core import Config, Raft
from etcd_tpu.raft.progress import ProgressState


@dataclass
class Ready:
    """Everything the application must act on after stepping the FSM
    (reference node.go:52-80). Field order mirrors the required handling
    order."""

    soft_state: Optional[SoftState] = None
    hard_state: HardState = EMPTY_HARD_STATE
    entries: List[Entry] = field(default_factory=list)          # persist FIRST
    snapshot: Snapshot = Snapshot()                              # persist
    committed_entries: List[Entry] = field(default_factory=list)  # then apply
    messages: List[Message] = field(default_factory=list)        # send AFTER persist

    def contains_updates(self) -> bool:
        return (self.soft_state is not None
                or not self.hard_state.is_empty()
                or not self.snapshot.is_empty()
                or bool(self.entries)
                or bool(self.committed_entries)
                or bool(self.messages))


@dataclass(frozen=True)
class Peer:
    id: int
    context: bytes = b""


class SnapshotStatus:
    FINISH = True
    FAILURE = False


@dataclass
class Status:
    """Point-in-time introspection copy (reference raft/status.go:23-49)."""

    id: int
    hard_state: HardState
    soft_state: SoftState
    applied: int
    progress: Dict[int, Tuple[int, int, str]]  # id -> (match, next, state)

    def to_json(self) -> dict:
        d = {
            "id": f"{self.id:x}",
            "term": self.hard_state.term,
            "vote": f"{self.hard_state.vote:x}",
            "commit": self.hard_state.commit,
            "lead": f"{self.soft_state.lead:x}",
            "raftState": self.soft_state.raft_state.name,
            "progress": {},
        }
        if self.soft_state.raft_state == StateType.LEADER:
            d["progress"] = {
                f"{pid:x}": {"match": m, "next": n, "state": s}
                for pid, (m, n, s) in self.progress.items()
            }
        return d


class Node:
    """Synchronous wrapper turning the pure Raft core into a drivable unit."""

    def __init__(self, r: Raft) -> None:
        self._raft = r
        self._prev_soft = r.soft_state()
        self._prev_hard = EMPTY_HARD_STATE
        self._prev_last_unstable: Optional[Tuple[int, int]] = None  # (i, t)
        self._prev_snap_index = 0
        self._awaiting_advance = False
        self._prop_blocked = False  # local node removed from cluster

    # -- bootstrap -----------------------------------------------------------

    @staticmethod
    def start(c: Config, peers: Sequence[Peer]) -> "Node":
        """Fresh cluster bootstrap: synthesize committed ConfChangeAddNode
        entries at term 1 for the initial membership (reference
        node.go:145-180)."""
        r = Raft(c)
        r.become_follower(1, raftpb.NO_LEADER)
        for peer in peers:
            cc = ConfChange(type=ConfChangeType.ADD_NODE, node_id=peer.id,
                            context=peer.context)
            e = Entry(type=EntryType.CONF_CHANGE, term=1,
                      index=r.raft_log.last_index() + 1,
                      data=raftpb.encode_conf_change(cc))
            r.raft_log.append([e])
        r.raft_log.committed = r.raft_log.last_index()
        for peer in peers:
            r.add_node(peer.id)
        return Node(r)

    @staticmethod
    def restart(c: Config) -> "Node":
        """Restart from Storage (state recovered from WAL+snapshot); no peers
        argument — membership comes from the log (reference node.go:186-192)."""
        return Node(Raft(c))

    # -- inputs --------------------------------------------------------------

    def tick(self) -> None:
        self._raft.tick()

    def campaign(self) -> None:
        self._raft.step(Message(type=MessageType.HUP, frm=self._raft.id))

    def propose(self, data: bytes) -> None:
        if self._prop_blocked:
            from etcd_tpu.raft.core import ProposalDroppedError
            raise ProposalDroppedError("local node removed from cluster")
        self.step(Message(type=MessageType.PROP, frm=self._raft.id,
                          entries=(Entry(data=data),)))

    def propose_conf_change(self, cc: ConfChange) -> None:
        if self._prop_blocked:
            from etcd_tpu.raft.core import ProposalDroppedError
            raise ProposalDroppedError("local node removed from cluster")
        self.step(Message(type=MessageType.PROP, frm=self._raft.id,
                          entries=(Entry(type=EntryType.CONF_CHANGE,
                                         data=raftpb.encode_conf_change(cc)),)))

    def step(self, m: Message) -> None:
        # Ignore unexpected local messages arriving over the network; use
        # tick()/campaign()/report_*() for those (reference node.go:365-372).
        if raftpb.is_local_msg(m.type) and m.frm != self._raft.id:
            return
        if m.type in (MessageType.HUP, MessageType.BEAT):
            self._raft.step(m)
            return
        # Drop response messages from peers we don't know (reference
        # node.go:281-283).
        if raftpb.is_response_msg(m.type) and m.frm not in self._raft.prs:
            return
        self._raft.step(m)

    def report_unreachable(self, id: int) -> None:
        self._raft.step(Message(type=MessageType.UNREACHABLE, frm=id))

    def report_snapshot(self, id: int, ok: bool) -> None:
        self._raft.step(Message(type=MessageType.SNAP_STATUS, frm=id,
                                reject=not ok))

    def apply_conf_change(self, cc: ConfChange) -> ConfState:
        if cc.node_id == raftpb.NO_LEADER:
            self._raft.reset_pending_conf()
        elif cc.type == ConfChangeType.ADD_NODE:
            self._raft.add_node(cc.node_id)
        elif cc.type == ConfChangeType.REMOVE_NODE:
            if cc.node_id == self._raft.id:
                self._prop_blocked = True
            self._raft.remove_node(cc.node_id)
        elif cc.type == ConfChangeType.UPDATE_NODE:
            self._raft.reset_pending_conf()
        else:
            raise ValueError(f"unexpected conf change type {cc.type}")
        return ConfState(nodes=tuple(self._raft.nodes()))

    # -- Ready/Advance -------------------------------------------------------

    def has_ready(self) -> bool:
        if self._awaiting_advance:
            return False
        r = self._raft
        return (bool(r.msgs)
                or bool(r.raft_log.unstable.entries)
                or r.raft_log.unstable.snapshot is not None
                or r.raft_log.has_next_ents()
                or r.soft_state() != self._prev_soft
                or r.hard_state() != self._prev_hard)

    def ready(self) -> Optional[Ready]:
        """Drain the pending work batch; the caller must advance() before the
        next ready()."""
        if self._awaiting_advance:
            return None
        rd = self._new_ready()
        if not rd.contains_updates():
            return None
        if rd.soft_state is not None:
            self._prev_soft = rd.soft_state
        if rd.entries:
            last = rd.entries[-1]
            self._prev_last_unstable = (last.index, last.term)
        if not rd.hard_state.is_empty():
            self._prev_hard = rd.hard_state
        if not rd.snapshot.is_empty():
            self._prev_snap_index = rd.snapshot.metadata.index
        self._raft.msgs = []
        self._awaiting_advance = True
        return rd

    def advance(self) -> None:
        """Application finished persisting/applying the last Ready (reference
        node.go:330-337)."""
        if not self._awaiting_advance:
            return
        r = self._raft
        if self._prev_hard.commit != 0:
            r.raft_log.applied_to(self._prev_hard.commit)
        if self._prev_last_unstable is not None:
            r.raft_log.stable_to(*self._prev_last_unstable)
            self._prev_last_unstable = None
        r.raft_log.stable_snap_to(self._prev_snap_index)
        self._awaiting_advance = False

    def _new_ready(self) -> Ready:
        r = self._raft
        rd = Ready(
            entries=r.raft_log.unstable_entries(),
            committed_entries=r.raft_log.next_ents(),
            messages=list(r.msgs),
        )
        soft = r.soft_state()
        if soft != self._prev_soft:
            rd.soft_state = soft
        hard = r.hard_state()
        if hard != self._prev_hard:
            rd.hard_state = hard
        if r.raft_log.unstable.snapshot is not None:
            rd.snapshot = r.raft_log.unstable.snapshot
        return rd

    # -- introspection -------------------------------------------------------

    @property
    def raft(self) -> Raft:
        return self._raft

    def status(self) -> Status:
        r = self._raft
        return Status(
            id=r.id,
            hard_state=r.hard_state(),
            soft_state=r.soft_state(),
            applied=r.raft_log.applied,
            progress={pid: (pr.match, pr.next, pr.state.name)
                      for pid, pr in r.prs.items()},
        )
