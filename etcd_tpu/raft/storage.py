"""Stable-log storage interface used by the consensus core.

Behavioral equivalent of reference raft/storage.go:40-249: a read-only view of
the persisted log (InitialState/Entries/Term/LastIndex/FirstIndex/Snapshot)
plus the in-memory implementation with Append/Compact/CreateSnapshot/
ApplySnapshot and the Compacted/SnapOutOfDate/Unavailable sentinels.

In the TPU framework the host keeps one MemoryStorage-equivalent *window* per
group (entries beyond the on-device term window spill here), so this module is
deliberately free of any device concern.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import ConfState, Entry, HardState, Snapshot, SnapshotMetadata


class CompactedError(Exception):
    """Requested index predates the last snapshot/compaction."""


class SnapOutOfDateError(Exception):
    """Requested snapshot index is older than the existing snapshot."""


class UnavailableError(Exception):
    """Requested entries are not yet available in storage."""


class Storage:
    """Read interface the core uses for the stable portion of the log."""

    def initial_state(self) -> Tuple[HardState, ConfState]:
        raise NotImplementedError

    def entries(self, lo: int, hi: int, max_size: int = raftpb.NO_LIMIT) -> Tuple[Entry, ...]:
        raise NotImplementedError

    def term(self, i: int) -> int:
        raise NotImplementedError

    def last_index(self) -> int:
        raise NotImplementedError

    def first_index(self) -> int:
        raise NotImplementedError

    def snapshot(self) -> Snapshot:
        raise NotImplementedError


class MemoryStorage(Storage):
    """In-RAM log window backed by a list, with a dummy entry at offset 0
    holding the term of the last compacted index (so ents[0].index is the
    compaction watermark, mirroring the reference's layout invariant)."""

    def __init__(self, entries: Sequence[Entry] = (),
                 hard_state: HardState = HardState(),
                 snapshot: Snapshot = Snapshot()) -> None:
        self._mu = threading.Lock()
        self._hard_state = hard_state
        self._snapshot = snapshot
        self._ents: List[Entry] = [Entry(term=snapshot.metadata.term,
                                         index=snapshot.metadata.index)]
        self._ents.extend(entries)

    # -- Storage interface ---------------------------------------------------

    def initial_state(self) -> Tuple[HardState, ConfState]:
        with self._mu:
            return self._hard_state, self._snapshot.metadata.conf_state

    def set_hard_state(self, hs: HardState) -> None:
        with self._mu:
            self._hard_state = hs

    def entries(self, lo: int, hi: int, max_size: int = raftpb.NO_LIMIT) -> Tuple[Entry, ...]:
        with self._mu:
            offset = self._ents[0].index
            if lo <= offset:
                raise CompactedError(lo)
            if hi > self._last_index() + 1:
                raise ValueError(f"entries hi {hi} out of bound {self._last_index()}")
            if len(self._ents) == 1:  # only the dummy entry
                raise UnavailableError(lo)
            ents = self._ents[lo - offset:hi - offset]
            return raftpb.limit_size(ents, max_size)

    def term(self, i: int) -> int:
        with self._mu:
            offset = self._ents[0].index
            if i < offset:
                raise CompactedError(i)
            if i - offset >= len(self._ents):
                raise UnavailableError(i)
            return self._ents[i - offset].term

    def last_index(self) -> int:
        with self._mu:
            return self._last_index()

    def _last_index(self) -> int:
        return self._ents[0].index + len(self._ents) - 1

    def first_index(self) -> int:
        with self._mu:
            return self._ents[0].index + 1

    def snapshot(self) -> Snapshot:
        with self._mu:
            return self._snapshot

    # -- Write side ----------------------------------------------------------

    def apply_snapshot(self, snap: Snapshot) -> None:
        with self._mu:
            if self._snapshot.metadata.index >= snap.metadata.index:
                raise SnapOutOfDateError(snap.metadata.index)
            self._snapshot = snap
            self._ents = [Entry(term=snap.metadata.term, index=snap.metadata.index)]

    def create_snapshot(self, i: int, cs: Optional[ConfState], data: bytes) -> Snapshot:
        with self._mu:
            if i <= self._snapshot.metadata.index:
                raise SnapOutOfDateError(i)
            offset = self._ents[0].index
            if i > self._last_index():
                raise ValueError(f"snapshot {i} past last index {self._last_index()}")
            md = SnapshotMetadata(
                index=i,
                term=self._ents[i - offset].term,
                conf_state=cs if cs is not None else self._snapshot.metadata.conf_state,
            )
            self._snapshot = Snapshot(data=data, metadata=md)
            return self._snapshot

    def compact(self, compact_index: int) -> None:
        """Discard entries <= compact_index; the app must ensure it does not
        compact past applied."""
        with self._mu:
            offset = self._ents[0].index
            if compact_index <= offset:
                raise CompactedError(compact_index)
            if compact_index > self._last_index():
                raise ValueError(
                    f"compact {compact_index} out of bound {self._last_index()}")
            # New dummy entry carries the term at the compaction watermark.
            i = compact_index - offset
            self._ents = ([Entry(index=self._ents[i].index, term=self._ents[i].term)]
                          + self._ents[i + 1:])

    def append(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        with self._mu:
            first = self._ents[0].index + 1
            last = entries[0].index + len(entries) - 1
            if last < first:
                return  # entirely compacted away
            if first > entries[0].index:
                entries = entries[first - entries[0].index:]
            offset = entries[0].index - self._ents[0].index
            if offset > len(self._ents):
                raise ValueError(f"missing log entry [last: {self._last_index()}, "
                                 f"append at: {entries[0].index}]")
            # Truncate any conflicting suffix, then append.
            self._ents = self._ents[:offset]
            self._ents.extend(entries)
