"""Consensus core: pure scalar Raft FSM (the oracle), log, progress,
storage, and the synchronous Node/Ready driver."""

from etcd_tpu.raft.core import Config, Raft, ProposalDroppedError
from etcd_tpu.raft.log import RaftLog, Unstable
from etcd_tpu.raft.node import Node, Peer, Ready, Status
from etcd_tpu.raft.progress import Inflights, Progress, ProgressState
from etcd_tpu.raft.storage import (CompactedError, MemoryStorage,
                                   SnapOutOfDateError, Storage,
                                   UnavailableError)

__all__ = [
    "Config", "Raft", "ProposalDroppedError", "RaftLog", "Unstable", "Node",
    "Peer", "Ready", "Status", "Inflights", "Progress", "ProgressState",
    "CompactedError", "MemoryStorage", "SnapOutOfDateError", "Storage",
    "UnavailableError",
]
