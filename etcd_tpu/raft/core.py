"""The pure, deterministic, I/O-free Raft state machine (scalar reference).

Behavioral equivalent of reference raft/raft.go:125-771: leader election,
log replication, quorum commit, membership change, snapshot transfer
decisions. This scalar implementation is the *oracle* for the batched TPU
kernel (etcd_tpu/ops/kernel.py): both share integer state encodings and the
xorshift32 election-timeout PRNG, so a batched step over G groups must equal
G scalar steps bit-for-bit.

Design departures from the reference (deliberate, TPU-first):
- No goroutines/channels — the FSM is stepped synchronously; the run loop
  lives in etcd_tpu/raft/node.py.
- Randomized election timeout draws from a seedable xorshift32 stream
  (reference raft.go:765-771 uses math/rand seeded by node id) so that the
  dense (G,)-array PRNG in the kernel reproduces it exactly.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from etcd_tpu import raftpb
from etcd_tpu.raftpb import (Entry, EntryType, HardState, Message, MessageType,
                             NO_LEADER, Snapshot, SoftState, StateType)
from etcd_tpu.raft.log import RaftLog
from etcd_tpu.raft.progress import Progress, ProgressState
from etcd_tpu.raft.storage import Storage


class ProposalDroppedError(Exception):
    """Proposal dropped (no leader, or removed from cluster)."""


def xorshift32(x: int) -> int:
    """One step of the 32-bit xorshift PRNG (Marsaglia 2003). Mirrored
    verbatim by the batched kernel on uint32 lanes."""
    x &= 0xFFFFFFFF
    x ^= (x << 13) & 0xFFFFFFFF
    x ^= x >> 17
    x ^= (x << 5) & 0xFFFFFFFF
    return x & 0xFFFFFFFF


def prng_seed(group: int, node: int) -> int:
    """Non-zero deterministic seed per (group, node)."""
    s = (group * 0x9E3779B9 + node * 0x85EBCA6B + 1) & 0xFFFFFFFF
    return s if s else 1


class Config:
    def __init__(self, id: int, election_tick: int, heartbeat_tick: int,
                 storage: Storage, peers: Sequence[int] = (),
                 applied: int = 0,
                 max_size_per_msg: int = raftpb.NO_LIMIT,
                 max_inflight_msgs: int = 256,
                 group: int = 0) -> None:
        self.id = id
        self.peers = tuple(peers)
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.storage = storage
        self.applied = applied
        self.max_size_per_msg = max_size_per_msg
        self.max_inflight_msgs = max_inflight_msgs
        self.group = group

    def validate(self) -> None:
        if self.id == 0:
            raise ValueError("cannot use 0 as raft id")
        if self.heartbeat_tick <= 0:
            raise ValueError("heartbeat tick must be greater than 0")
        if self.election_tick <= self.heartbeat_tick:
            raise ValueError("election tick must be greater than heartbeat tick")
        if self.storage is None:
            raise ValueError("storage cannot be nil")
        if self.max_inflight_msgs <= 0:
            raise ValueError("max inflight messages must be greater than 0")


class Raft:
    def __init__(self, c: Config) -> None:
        c.validate()
        self.id = c.id
        self.group = c.group
        raft_log = RaftLog(c.storage)
        hs, cs = c.storage.initial_state()
        peers = c.peers
        if cs.nodes:
            if peers:
                raise ValueError(
                    "cannot specify both Config.peers and ConfState.nodes")
            peers = cs.nodes

        self.raft_log = raft_log
        self.max_msg_size = c.max_size_per_msg
        self.max_inflight = c.max_inflight_msgs
        self.prs: Dict[int, Progress] = {}
        self.election_timeout = c.election_tick
        self.heartbeat_timeout = c.heartbeat_tick

        # Durable (HardState) fields.
        self.term = 0
        self.vote = NO_LEADER

        # Volatile.
        self.lead = NO_LEADER
        self.state = StateType.FOLLOWER
        self.votes: Dict[int, bool] = {}
        self.msgs: List[Message] = []
        self.pending_conf = False
        self.elapsed = 0
        self._prng = prng_seed(c.group, c.id)

        self._step_fn: Callable[[Message], None] = self._step_follower
        self._tick_fn: Callable[[], None] = self.tick_election

        for p in peers:
            self.prs[p] = Progress(next=1, inflight_size=self.max_inflight)
        if not hs.is_empty():
            self.load_state(hs)
        if c.applied > 0:
            raft_log.applied_to(c.applied)
        self.become_follower(self.term, NO_LEADER)

    # -- introspection -------------------------------------------------------

    def has_leader(self) -> bool:
        return self.lead != NO_LEADER

    def soft_state(self) -> SoftState:
        return SoftState(lead=self.lead, raft_state=self.state)

    def hard_state(self) -> HardState:
        return HardState(term=self.term, vote=self.vote,
                         commit=self.raft_log.committed)

    def quorum(self) -> int:
        return len(self.prs) // 2 + 1

    def nodes(self) -> List[int]:
        return sorted(self.prs)

    # -- outbound messages ---------------------------------------------------

    def _send(self, m: Message) -> None:
        # MsgProp carries no term: proposals forward to the leader and are
        # treated as local (reference raft.go:227-236).
        term = m.term if m.type == MessageType.PROP else self.term
        self.msgs.append(raftpb.replace(m, frm=self.id, term=term))

    def send_append(self, to: int) -> None:
        pr = self.prs[to]
        if pr.is_paused():
            return
        next_idx = pr.next
        if next_idx < self.raft_log.first_index():
            # Follower is behind our compaction point: ship a snapshot.
            snapshot = self.raft_log.snapshot()
            if snapshot.is_empty():
                raise RuntimeError("need non-empty snapshot")
            self._send(Message(type=MessageType.SNAP, to=to, snapshot=snapshot))
            pr.become_snapshot(snapshot.metadata.index)
            return
        entries = tuple(self.raft_log.entries(next_idx, self.max_msg_size))
        m = Message(
            type=MessageType.APP, to=to, index=next_idx - 1,
            log_term=self.raft_log.term(next_idx - 1), entries=entries,
            commit=self.raft_log.committed)
        if entries:
            if pr.state == ProgressState.REPLICATE:
                last = entries[-1].index
                pr.optimistic_update(last)
                pr.ins.add(last)
            elif pr.state == ProgressState.PROBE:
                pr.pause()
            else:
                raise RuntimeError(f"sending append in state {pr.state}")
        self._send(m)

    def send_heartbeat(self, to: int) -> None:
        # Never forward the follower's commit past its match
        # (reference raft.go:285-299).
        commit = min(self.prs[to].match, self.raft_log.committed)
        self._send(Message(type=MessageType.HEARTBEAT, to=to, commit=commit))

    def bcast_append(self) -> None:
        for peer in self.prs:
            if peer != self.id:
                self.send_append(peer)

    def bcast_heartbeat(self) -> None:
        for peer in self.prs:
            if peer != self.id:
                self.send_heartbeat(peer)
                self.prs[peer].resume()

    # -- commit --------------------------------------------------------------

    def maybe_commit(self) -> bool:
        """Quorum commit: the q-th largest match index (reference
        raft.go:323-332). This sort-median is THE reduction the batched kernel
        turns into lax.top_k over the peers axis."""
        matches = sorted((pr.match for pr in self.prs.values()), reverse=True)
        mci = matches[self.quorum() - 1]
        return self.raft_log.maybe_commit(mci, self.term)

    # -- state transitions ---------------------------------------------------

    def reset(self, term: int) -> None:
        if self.term != term:
            self.term = term
            self.vote = NO_LEADER
        self.lead = NO_LEADER
        self.elapsed = 0
        self.votes = {}
        last = self.raft_log.last_index()
        for peer in self.prs:
            self.prs[peer] = Progress(next=last + 1,
                                      inflight_size=self.max_inflight)
            if peer == self.id:
                self.prs[peer].match = last
        self.pending_conf = False

    def append_entry(self, *es: Entry) -> None:
        li = self.raft_log.last_index()
        stamped = [raftpb.replace(e, term=self.term, index=li + 1 + i)
                   for i, e in enumerate(es)]
        self.raft_log.append(stamped)
        self.prs[self.id].maybe_update(self.raft_log.last_index())
        self.maybe_commit()

    def tick_election(self) -> None:
        if not self.promotable():
            self.elapsed = 0
            return
        self.elapsed += 1
        if self.is_election_timeout():
            self.elapsed = 0
            self.step(Message(type=MessageType.HUP, frm=self.id))

    def tick_heartbeat(self) -> None:
        self.elapsed += 1
        if self.elapsed >= self.heartbeat_timeout:
            self.elapsed = 0
            self.step(Message(type=MessageType.BEAT, frm=self.id))

    def tick(self) -> None:
        self._tick_fn()

    def become_follower(self, term: int, lead: int) -> None:
        self._step_fn = self._step_follower
        self.reset(term)
        self._tick_fn = self.tick_election
        self.lead = lead
        self.state = StateType.FOLLOWER

    def become_candidate(self) -> None:
        if self.state == StateType.LEADER:
            raise RuntimeError("invalid transition [leader -> candidate]")
        self._step_fn = self._step_candidate
        self.reset(self.term + 1)
        self._tick_fn = self.tick_election
        self.vote = self.id
        self.state = StateType.CANDIDATE

    def become_leader(self) -> None:
        if self.state == StateType.FOLLOWER:
            raise RuntimeError("invalid transition [follower -> leader]")
        self._step_fn = self._step_leader
        self.reset(self.term)
        self._tick_fn = self.tick_heartbeat
        self.lead = self.id
        self.state = StateType.LEADER
        for e in self.raft_log.entries(self.raft_log.committed + 1):
            if e.type != EntryType.CONF_CHANGE:
                continue
            if self.pending_conf:
                raise RuntimeError("unexpected double uncommitted config entry")
            self.pending_conf = True
        # Leader commits a no-op entry from its own term (paper §5.4.2).
        self.append_entry(Entry())

    def campaign(self) -> None:
        if not self.promotable():
            return  # removed from the cluster; a HUP must not crash us
        self.become_candidate()
        if self.quorum() == self.poll(self.id, True):
            self.become_leader()
            return
        for peer in self.prs:
            if peer == self.id:
                continue
            self._send(Message(type=MessageType.VOTE, to=peer,
                               index=self.raft_log.last_index(),
                               log_term=self.raft_log.last_term()))

    def poll(self, id: int, granted: bool) -> int:
        if id not in self.votes:
            self.votes[id] = granted
        return sum(1 for v in self.votes.values() if v)

    # -- the step function ---------------------------------------------------

    def step(self, m: Message) -> None:
        if m.type == MessageType.HUP:
            # A leader ignores HUP (its tick path never produces one; a no-op
            # here keeps the batched kernel branch-free on this edge).
            if self.state != StateType.LEADER:
                self.campaign()
            return

        if m.term == 0:
            pass  # local message
        elif m.term > self.term:
            # A vote request doesn't establish its sender as leader.
            lead = NO_LEADER if m.type == MessageType.VOTE else m.frm
            self.become_follower(m.term, lead)
        elif m.term < self.term:
            return  # stale — ignore

        self._step_fn(m)

    def _step_leader(self, m: Message) -> None:
        t = m.type
        if t == MessageType.BEAT:
            self.bcast_heartbeat()
            return
        if t == MessageType.PROP:
            if not m.entries:
                raise RuntimeError("stepped empty MsgProp")
            entries = list(m.entries)
            for i, e in enumerate(entries):
                if e.type == EntryType.CONF_CHANGE:
                    # Only one in-flight config change at a time: demote
                    # extras to empty normal entries (reference raft.go:504-511).
                    if self.pending_conf:
                        entries[i] = Entry(type=EntryType.NORMAL)
                    self.pending_conf = True
            self.append_entry(*entries)
            self.bcast_append()
            return
        if t == MessageType.VOTE:
            self._send(Message(type=MessageType.VOTE_RESP, to=m.frm, reject=True))
            return

        pr = self.prs.get(m.frm)
        if pr is None:
            return
        if t == MessageType.APP_RESP:
            if m.reject:
                if pr.maybe_decr_to(m.index, m.reject_hint):
                    if pr.state == ProgressState.REPLICATE:
                        pr.become_probe()
                    self.send_append(m.frm)
            else:
                old_paused = pr.is_paused()
                if pr.maybe_update(m.index):
                    if pr.state == ProgressState.PROBE:
                        pr.become_replicate()
                    elif (pr.state == ProgressState.SNAPSHOT
                          and pr.need_snapshot_abort()):
                        pr.become_probe()
                    elif pr.state == ProgressState.REPLICATE:
                        pr.ins.free_to(m.index)
                    if self.maybe_commit():
                        self.bcast_append()
                    elif old_paused:
                        # The ack unpaused this follower; send the delayed
                        # append now.
                        self.send_append(m.frm)
        elif t == MessageType.HEARTBEAT_RESP:
            if pr.state == ProgressState.REPLICATE and pr.ins.full():
                pr.ins.free_first_one()
            if pr.match < self.raft_log.last_index():
                self.send_append(m.frm)
        elif t == MessageType.SNAP_STATUS:
            if pr.state != ProgressState.SNAPSHOT:
                return
            if m.reject:
                pr.snapshot_failure()
            pr.become_probe()
            # Wait for the next MsgAppResp (success) or a heartbeat interval
            # (failure) before the next append (reference raft.go:559-574).
            pr.pause()
        elif t == MessageType.UNREACHABLE:
            # An optimistic in-flight MsgApp was probably lost.
            if pr.state == ProgressState.REPLICATE:
                pr.become_probe()

    def _step_candidate(self, m: Message) -> None:
        t = m.type
        if t == MessageType.PROP:
            raise ProposalDroppedError(f"no leader at term {self.term}")
        if t == MessageType.APP:
            self.become_follower(self.term, m.frm)
            self.handle_append_entries(m)
        elif t == MessageType.HEARTBEAT:
            self.become_follower(self.term, m.frm)
            self.handle_heartbeat(m)
        elif t == MessageType.SNAP:
            self.become_follower(m.term, m.frm)
            self.handle_snapshot(m)
        elif t == MessageType.VOTE:
            self._send(Message(type=MessageType.VOTE_RESP, to=m.frm, reject=True))
        elif t == MessageType.VOTE_RESP:
            granted = self.poll(m.frm, not m.reject)
            if granted == self.quorum():
                self.become_leader()
                self.bcast_append()
            elif len(self.votes) - granted == self.quorum():
                self.become_follower(self.term, NO_LEADER)

    def _step_follower(self, m: Message) -> None:
        t = m.type
        if t == MessageType.PROP:
            if self.lead == NO_LEADER:
                raise ProposalDroppedError(f"no leader at term {self.term}")
            self._send(raftpb.replace(m, to=self.lead))
        elif t == MessageType.APP:
            self.elapsed = 0
            self.lead = m.frm
            self.handle_append_entries(m)
        elif t == MessageType.HEARTBEAT:
            self.elapsed = 0
            self.lead = m.frm
            self.handle_heartbeat(m)
        elif t == MessageType.SNAP:
            self.elapsed = 0
            self.handle_snapshot(m)
        elif t == MessageType.VOTE:
            if ((self.vote in (NO_LEADER, m.frm))
                    and self.raft_log.is_up_to_date(m.index, m.log_term)):
                self.elapsed = 0
                self.vote = m.frm
                self._send(Message(type=MessageType.VOTE_RESP, to=m.frm))
            else:
                self._send(Message(type=MessageType.VOTE_RESP, to=m.frm,
                                   reject=True))

    # -- message handlers ----------------------------------------------------

    def handle_append_entries(self, m: Message) -> None:
        if m.index < self.raft_log.committed:
            self._send(Message(type=MessageType.APP_RESP, to=m.frm,
                               index=self.raft_log.committed))
            return
        lastnewi = self.raft_log.maybe_append(m.index, m.log_term, m.commit,
                                              m.entries)
        if lastnewi is not None:
            self._send(Message(type=MessageType.APP_RESP, to=m.frm,
                               index=lastnewi))
        else:
            self._send(Message(type=MessageType.APP_RESP, to=m.frm,
                               index=m.index, reject=True,
                               reject_hint=self.raft_log.last_index()))

    def handle_heartbeat(self, m: Message) -> None:
        self.raft_log.commit_to(m.commit)
        self._send(Message(type=MessageType.HEARTBEAT_RESP, to=m.frm))

    def handle_snapshot(self, m: Message) -> None:
        if self.restore(m.snapshot):
            self._send(Message(type=MessageType.APP_RESP, to=m.frm,
                               index=self.raft_log.last_index()))
        else:
            self._send(Message(type=MessageType.APP_RESP, to=m.frm,
                               index=self.raft_log.committed))

    def restore(self, s: Snapshot) -> bool:
        """Recover log + membership from a snapshot (reference
        raft.go:686-713)."""
        if s.metadata.index <= self.raft_log.committed:
            return False
        if self.raft_log.match_term(s.metadata.index, s.metadata.term):
            # Already have these entries; just fast-forward commit.
            self.raft_log.commit_to(s.metadata.index)
            return False
        self.raft_log.restore(s)
        self.prs = {}
        for n in s.metadata.conf_state.nodes:
            next_idx = self.raft_log.last_index() + 1
            match = next_idx - 1 if n == self.id else 0
            self.set_progress(n, match, next_idx)
        return True

    # -- membership ----------------------------------------------------------

    def promotable(self) -> bool:
        return self.id in self.prs

    def add_node(self, id: int) -> None:
        if id in self.prs:
            return  # bootstrap entries can be applied twice
        self.set_progress(id, 0, self.raft_log.last_index() + 1)
        self.pending_conf = False

    def remove_node(self, id: int) -> None:
        self.prs.pop(id, None)
        self.pending_conf = False
        if not self.prs:
            return
        # Quorum shrank: pending entries may now be committed (adopted from
        # the upstream fix after the reference snapshot; without it a removal
        # can stall commits until the next proposal).
        if self.state == StateType.LEADER and self.maybe_commit():
            self.bcast_append()

    def reset_pending_conf(self) -> None:
        self.pending_conf = False

    def set_progress(self, id: int, match: int, next: int) -> None:
        pr = Progress(next=next, match=match, inflight_size=self.max_inflight)
        self.prs[id] = pr

    def load_state(self, state: HardState) -> None:
        if (state.commit < self.raft_log.committed
                or state.commit > self.raft_log.last_index()):
            raise RuntimeError(
                f"hardstate commit {state.commit} out of range "
                f"[{self.raft_log.committed}, {self.raft_log.last_index()}]")
        self.raft_log.committed = state.commit
        self.term = state.term
        self.vote = state.vote

    # -- timers --------------------------------------------------------------

    def is_election_timeout(self) -> bool:
        """True when elapsed exceeds a randomized point in
        (election_timeout, 2*election_timeout - 1) — reference raft.go:765-771,
        with math/rand replaced by the kernel-mirrorable xorshift32 stream."""
        d = self.elapsed - self.election_timeout
        if d < 0:
            return False
        self._prng = xorshift32(self._prng)
        return d > self._prng % self.election_timeout
