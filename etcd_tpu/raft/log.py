"""The raft log: committed/applied cursors over a merged view of the unstable
in-memory tail and the stable Storage.

Behavioral equivalent of reference raft/log.go:24-301 and
raft/log_unstable.go:23-137: maybe_append with conflict detection and
truncation, next_ents (committed-but-unapplied window), stable_to cursors,
bounded slice reads. The batched TPU kernel mirrors a fixed-width window of
this structure on device (term ring per group); this host copy is the source
of truth and the oracle for kernel equivalence tests.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import Entry, Snapshot
from etcd_tpu.raft.storage import (CompactedError, Storage, UnavailableError)


class Unstable:
    """The not-yet-persisted tail: maybe a snapshot being installed, plus
    entries starting at `offset` (all with index >= offset)."""

    def __init__(self, offset: int) -> None:
        self.snapshot: Optional[Snapshot] = None
        self.entries: List[Entry] = []
        self.offset = offset

    def maybe_first_index(self) -> Optional[int]:
        if self.snapshot is not None:
            return self.snapshot.metadata.index + 1
        return None

    def maybe_last_index(self) -> Optional[int]:
        if self.entries:
            return self.offset + len(self.entries) - 1
        if self.snapshot is not None:
            return self.snapshot.metadata.index
        return None

    def maybe_term(self, i: int) -> Optional[int]:
        if i < self.offset:
            if (self.snapshot is not None
                    and self.snapshot.metadata.index == i):
                return self.snapshot.metadata.term
            return None
        last = self.maybe_last_index()
        if last is None or i > last:
            return None
        return self.entries[i - self.offset].term

    def stable_to(self, i: int, t: int) -> None:
        gt = self.maybe_term(i)
        if gt is None:
            return
        # Only shrink if the persisted (i, term) still matches our unstable
        # tail — a conflicting truncate may have replaced it.
        if gt == t and i >= self.offset:
            self.entries = self.entries[i + 1 - self.offset:]
            self.offset = i + 1

    def stable_snap_to(self, i: int) -> None:
        if self.snapshot is not None and self.snapshot.metadata.index == i:
            self.snapshot = None

    def restore(self, s: Snapshot) -> None:
        self.offset = s.metadata.index + 1
        self.entries = []
        self.snapshot = s

    def truncate_and_append(self, ents: Sequence[Entry]) -> None:
        after = ents[0].index
        if after == self.offset + len(self.entries):
            self.entries.extend(ents)
        elif after <= self.offset:
            # Replace the whole unstable tail.
            self.offset = after
            self.entries = list(ents)
        else:
            # Truncate to after-1, then append.
            self.entries = self.entries[:after - self.offset]
            self.entries.extend(ents)

    def slice(self, lo: int, hi: int) -> List[Entry]:
        self._check_out_of_bounds(lo, hi)
        return self.entries[lo - self.offset:hi - self.offset]

    def _check_out_of_bounds(self, lo: int, hi: int) -> None:
        if lo > hi:
            raise ValueError(f"invalid unstable slice {lo} > {hi}")
        upper = self.offset + len(self.entries)
        if lo < self.offset or hi > upper:
            raise ValueError(
                f"unstable slice[{lo},{hi}) out of bound [{self.offset},{upper}]")


class RaftLog:
    def __init__(self, storage: Storage) -> None:
        self.storage = storage
        first = storage.first_index()
        last = storage.last_index()
        self.unstable = Unstable(offset=last + 1)
        self.committed = first - 1
        self.applied = first - 1

    def __repr__(self) -> str:
        return (f"RaftLog(committed={self.committed}, applied={self.applied}, "
                f"unstable.offset={self.unstable.offset}, "
                f"len(unstable)={len(self.unstable.entries)})")

    # -- append path ---------------------------------------------------------

    def maybe_append(self, index: int, log_term: int, committed: int,
                     ents: Sequence[Entry]) -> Optional[int]:
        """Follower append rule: if (index, log_term) matches our log, resolve
        conflicts, append what's new, and advance commit. Returns the index of
        the last new entry, or None on mismatch (reference log.go:72-96)."""
        if not self.match_term(index, log_term):
            return None
        lastnewi = index + len(ents)
        ci = self.find_conflict(ents)
        if ci == 0:
            pass  # no new entries, all duplicates
        elif ci <= self.committed:
            raise RuntimeError(
                f"entry {ci} conflicts with committed entry [committed="
                f"{self.committed}]")
        else:
            offset = index + 1
            self.append(ents[ci - offset:])
        self.commit_to(min(committed, lastnewi))
        return lastnewi

    def append(self, ents: Sequence[Entry]) -> int:
        if not ents:
            return self.last_index()
        after = ents[0].index - 1
        if after < self.committed:
            raise RuntimeError(
                f"after({after}) is out of range [committed({self.committed})]")
        self.unstable.truncate_and_append(ents)
        return self.last_index()

    def find_conflict(self, ents: Sequence[Entry]) -> int:
        """First index whose term mismatches ours (0 if none conflict and none
        are new); reference log.go:98-123."""
        for e in ents:
            if not self.match_term(e.index, e.term):
                if e.index <= self.last_index():
                    pass  # conflict with existing entry — caller truncates
                return e.index
        return 0

    # -- read path -----------------------------------------------------------

    def unstable_entries(self) -> List[Entry]:
        return list(self.unstable.entries)

    def next_ents(self, max_size: int = raftpb.NO_LIMIT) -> List[Entry]:
        """Committed-but-unapplied entries (what the state machine applies
        next); reference log.go:135-141."""
        off = max(self.applied + 1, self.first_index())
        if self.committed + 1 > off:
            return list(self.slice(off, self.committed + 1, max_size))
        return []

    def has_next_ents(self) -> bool:
        off = max(self.applied + 1, self.first_index())
        return self.committed + 1 > off

    def snapshot(self) -> Snapshot:
        if self.unstable.snapshot is not None:
            return self.unstable.snapshot
        return self.storage.snapshot()

    def first_index(self) -> int:
        i = self.unstable.maybe_first_index()
        if i is not None:
            return i
        return self.storage.first_index()

    def last_index(self) -> int:
        i = self.unstable.maybe_last_index()
        if i is not None:
            return i
        return self.storage.last_index()

    # -- cursors -------------------------------------------------------------

    def commit_to(self, tocommit: int) -> None:
        if self.committed < tocommit:
            if self.last_index() < tocommit:
                raise RuntimeError(
                    f"tocommit({tocommit}) is out of range "
                    f"[lastIndex({self.last_index()})]")
            self.committed = tocommit

    def applied_to(self, i: int) -> None:
        if i == 0:
            return
        if self.committed < i or i < self.applied:
            raise RuntimeError(
                f"applied({i}) is out of range [prevApplied({self.applied}), "
                f"committed({self.committed})]")
        self.applied = i

    def stable_to(self, i: int, t: int) -> None:
        self.unstable.stable_to(i, t)

    def stable_snap_to(self, i: int) -> None:
        self.unstable.stable_snap_to(i)

    # -- terms ---------------------------------------------------------------

    def last_term(self) -> int:
        return self.term(self.last_index())

    def term(self, i: int) -> int:
        """Term of entry i; 0 if outside the valid window [dummy, last]
        (reference log.go term()); raises CompactedError if storage compacted
        it away mid-query."""
        dummy = self.first_index() - 1
        if i < dummy or i > self.last_index():
            return 0
        t = self.unstable.maybe_term(i)
        if t is not None:
            return t
        return self.storage.term(i)

    def term_or_zero(self, i: int) -> int:
        try:
            return self.term(i)
        except (CompactedError, UnavailableError):
            return 0

    def match_term(self, i: int, term: int) -> bool:
        try:
            return self.term(i) == term
        except (CompactedError, UnavailableError):
            return False

    def is_up_to_date(self, lasti: int, term: int) -> bool:
        """Vote rule: candidate's log is at least as up-to-date as ours
        (reference log.go:216-218; Raft paper §5.4.1)."""
        return term > self.last_term() or (
            term == self.last_term() and lasti >= self.last_index())

    def maybe_commit(self, max_index: int, term: int) -> bool:
        if max_index > self.committed and self.term_or_zero(max_index) == term:
            self.commit_to(max_index)
            return True
        return False

    # -- slices --------------------------------------------------------------

    def entries(self, i: int, max_size: int = raftpb.NO_LIMIT) -> List[Entry]:
        if i > self.last_index():
            return []
        return list(self.slice(i, self.last_index() + 1, max_size))

    def all_entries(self) -> List[Entry]:
        try:
            return self.entries(self.first_index())
        except CompactedError:
            return self.all_entries()  # racing compaction; retry

    def slice(self, lo: int, hi: int, max_size: int = raftpb.NO_LIMIT) -> Tuple[Entry, ...]:
        self._must_check_out_of_bounds(lo, hi)
        if lo == hi:
            return ()
        ents: List[Entry] = []
        if lo < self.unstable.offset:
            stored = self.storage.entries(lo, min(hi, self.unstable.offset), max_size)
            # Short read from storage means size limit hit — stop there.
            if len(stored) < min(hi, self.unstable.offset) - lo:
                return tuple(stored)
            ents.extend(stored)
        if hi > self.unstable.offset:
            ents.extend(self.unstable.slice(max(lo, self.unstable.offset), hi))
        return raftpb.limit_size(ents, max_size)

    def _must_check_out_of_bounds(self, lo: int, hi: int) -> None:
        if lo > hi:
            raise ValueError(f"invalid slice {lo} > {hi}")
        fi = self.first_index()
        if lo < fi:
            raise CompactedError(lo)
        if hi > self.last_index() + 1:
            raise ValueError(
                f"slice[{lo},{hi}) out of bound [{fi},{self.last_index()}]")

    def restore(self, s: Snapshot) -> None:
        self.committed = s.metadata.index
        self.unstable.restore(s)
