"""Per-follower replication flow control.

Behavioral equivalent of reference raft/progress.go:19-237: the
Probe/Replicate/Snapshot state machine, optimistic next-index, pause/resume,
and the in-flight append window. In the batched kernel these fields live as
dense (groups, peers) integer/boolean arrays (see etcd_tpu/ops/state.py);
values of ProgressState are shared between both representations.
"""
from __future__ import annotations

import enum
from typing import List


class ProgressState(enum.IntEnum):
    PROBE = 0      # send at most one append, await response (unsure of match)
    REPLICATE = 1  # optimistic pipeline, window-limited
    SNAPSHOT = 2   # follower needs a snapshot; appends paused


class Inflights:
    """Sliding window of in-flight append last-indices (reference
    progress.go:172-237). Bounded ring; `full` pauses replication."""

    def __init__(self, size: int) -> None:
        self.size = size
        self.buffer: List[int] = []

    def add(self, inflight: int) -> None:
        if self.full():
            raise RuntimeError("cannot add into a full inflights")
        self.buffer.append(inflight)

    def free_to(self, to: int) -> None:
        """Frees inflights <= to (acked by the follower)."""
        i = 0
        while i < len(self.buffer) and self.buffer[i] <= to:
            i += 1
        if i:
            del self.buffer[:i]

    def free_first_one(self) -> None:
        if self.buffer:
            del self.buffer[:1]

    def full(self) -> bool:
        return len(self.buffer) >= self.size

    def count(self) -> int:
        return len(self.buffer)

    def reset(self) -> None:
        self.buffer.clear()


class Progress:
    def __init__(self, next: int = 0, match: int = 0,
                 inflight_size: int = 256) -> None:
        self.match = match
        self.next = next
        self.state = ProgressState.PROBE
        self.paused = False                 # probe sent, awaiting response
        self.pending_snapshot = 0           # index of in-flight snapshot
        self.ins = Inflights(inflight_size)

    def __repr__(self) -> str:
        return (f"Progress(next={self.next}, match={self.match}, "
                f"state={self.state.name}, paused={self.paused}, "
                f"pending_snapshot={self.pending_snapshot})")

    def _reset_state(self, state: ProgressState) -> None:
        self.paused = False
        self.pending_snapshot = 0
        self.state = state
        self.ins.reset()

    def become_probe(self) -> None:
        # Leaving snapshot state: the follower has at least the snapshot's
        # entries, so probe from there (reference progress.go:76-87).
        if self.state == ProgressState.SNAPSHOT:
            pending = self.pending_snapshot
            self._reset_state(ProgressState.PROBE)
            self.next = max(self.match + 1, pending + 1)
        else:
            self._reset_state(ProgressState.PROBE)
            self.next = self.match + 1

    def become_replicate(self) -> None:
        self._reset_state(ProgressState.REPLICATE)
        self.next = self.match + 1

    def become_snapshot(self, snapshot_index: int) -> None:
        self._reset_state(ProgressState.SNAPSHOT)
        self.pending_snapshot = snapshot_index

    def maybe_update(self, n: int) -> bool:
        """A successful MsgAppResp at index n (reference progress.go:102-113).
        Returns True if match advanced."""
        updated = False
        if self.match < n:
            self.match = n
            updated = True
            self.resume()
        if self.next < n + 1:
            self.next = n + 1
        return updated

    def optimistic_update(self, n: int) -> None:
        self.next = n + 1

    def maybe_decr_to(self, rejected: int, last: int) -> bool:
        """A rejected MsgAppResp; back off next (reference progress.go:119-141).
        Returns False if the rejection is stale."""
        if self.state == ProgressState.REPLICATE:
            # Directly decrease next to match + 1.
            if rejected <= self.match:
                return False
            self.next = self.match + 1
            return True
        # Probe: the rejection must be for our outstanding probe at next-1.
        if self.next - 1 != rejected:
            return False
        self.next = min(rejected, last + 1)
        if self.next < 1:
            self.next = 1
        self.resume()
        return True

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def is_paused(self) -> bool:
        """Whether the leader should hold off sending appends (reference
        progress.go:147-158)."""
        if self.state == ProgressState.PROBE:
            return self.paused
        if self.state == ProgressState.REPLICATE:
            return self.ins.full()
        return True  # SNAPSHOT

    def snapshot_failure(self) -> None:
        self.pending_snapshot = 0

    def need_snapshot_abort(self) -> bool:
        """Snapshot no longer needed once match covers it (reference
        progress.go:163-167)."""
        return (self.state == ProgressState.SNAPSHOT
                and self.match >= self.pending_snapshot)
