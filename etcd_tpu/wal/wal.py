"""Write-ahead log: segmented, CRC-chained, fsync-on-save.

Behavioral equivalent of the reference wal/ package (wal/wal.go:37-487,
wal/encoder.go, wal/decoder.go, wal/repair.go): record types
{METADATA, ENTRY, STATE, CRC, SNAPSHOT}, a rolling CRC carried across segment
cuts, 64MB segment rotation, exclusive flocks on live segments with
release-up-to retention, and a one-shot torn-tail repair. Re-designed for the
TPU framework's synchronous host loop: no goroutines — Save() is called from
the Ready-drain step BEFORE messages are sent (ordering contract, reference
raft/doc.go:31-39), and batches many groups' records per fsync.

Record framing (little-endian, fixed 16-byte header then payload):
    type:u32  crc:u32  len:u64  data[len]
crc is the rolling zlib.crc32 of every payload byte written to the log so
far INCLUDING this record's (seeded by the previous segment via the CRC
record) — a mid-file flip is detected at the first bad record, like the
reference's Castagnoli chain (wal/wal.go:60, walpb/record.go:23).
"""
from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

from etcd_tpu import raftpb
from etcd_tpu.raftpb import Entry, HardState, EMPTY_HARD_STATE
from etcd_tpu.utils import fileutil, metrics

# Record types (reference wal/wal.go:37-42).
METADATA_TYPE = 1
ENTRY_TYPE = 2
STATE_TYPE = 3
CRC_TYPE = 4
SNAPSHOT_TYPE = 5

SEGMENT_SIZE_BYTES = 64 * 1024 * 1024  # reference wal/wal.go:49

_REC_HDR = struct.Struct("<IIQ")  # type, crc, len
_WAL_SNAP = struct.Struct("<QQ")  # index, term (reference walpb.Snapshot)


class CorruptError(Exception):
    """CRC mismatch or malformed record (reference ErrCRCMismatch)."""

    def __init__(self, path: str, offset: int, why: str) -> None:
        super().__init__(f"wal: corrupt record in {path} at {offset}: {why}")
        self.path = path
        self.offset = offset


class UnexpectedEOF(Exception):
    """Torn tail: the file ends inside a record."""

    def __init__(self, path: str, offset: int) -> None:
        super().__init__(f"wal: unexpected EOF in {path} at {offset}")
        self.path = path
        self.offset = offset


class SnapshotNotFoundError(Exception):
    """ReadAll did not see the snapshot record it was asked to start from
    (reference ErrSnapshotNotFound)."""


@dataclass(frozen=True)
class WalSnapshot:
    """Snapshot *marker* in the WAL (just index+term, not the payload —
    reference walpb.Record SNAPSHOT type)."""

    index: int = 0
    term: int = 0

    def encode(self) -> bytes:
        return _WAL_SNAP.pack(self.index, self.term)

    @staticmethod
    def decode(b: bytes) -> "WalSnapshot":
        i, t = _WAL_SNAP.unpack(b)
        return WalSnapshot(index=i, term=t)


def wal_name(seq: int, index: int) -> str:
    return f"{seq:016x}-{index:016x}.wal"


def parse_wal_name(name: str) -> Tuple[int, int]:
    if not name.endswith(".wal"):
        raise ValueError(f"bad wal name {name!r}")
    seq_s, _, idx_s = name[:-4].partition("-")
    return int(seq_s, 16), int(idx_s, 16)


def wal_exists(dirname: str) -> bool:
    if not os.path.isdir(dirname):
        return False
    return any(n.endswith(".wal") for n in os.listdir(dirname))


def _scan_names(dirname: str) -> List[str]:
    """Valid .wal names in the dir, sorted; skips unparseable strays
    (reference readWALNames) and verifies the seq chain is contiguous
    (reference wal.go searchIndex/isValidSeq)."""
    names = []
    for n in fileutil.read_dir(dirname):
        if not n.endswith(".wal"):
            continue
        try:
            parse_wal_name(n)
        except ValueError:
            continue  # stray file (editor backup etc.) — ignore
        names.append(n)
    last_seq = None
    for n in names:
        seq, _ = parse_wal_name(n)
        if last_seq is not None and seq != last_seq + 1:
            raise CorruptError(os.path.join(dirname, n), 0,
                               f"wal file seq gap ({last_seq} -> {seq})")
        last_seq = seq
    return names


# ---------------------------------------------------------------------------
# Encoder / decoder
# ---------------------------------------------------------------------------

class _Encoder:
    def __init__(self, fobj, prev_crc: int) -> None:
        self.f = fobj
        self.crc = prev_crc

    def encode(self, rtype: int, data: bytes) -> None:
        # One call through the native codec when built (./build); the
        # Python fallback is byte-identical.
        from etcd_tpu import native
        buf, self.crc = native.encode_records([(rtype, data)], self.crc)
        self.f.write(buf)

    def encode_crc_record(self) -> None:
        """Carry the rolling crc into a fresh segment: a CRC record's crc
        field IS the seed (it covers no payload bytes)."""
        self.f.write(_REC_HDR.pack(CRC_TYPE, self.crc, 0))

    def flush(self) -> None:
        self.f.flush()


@dataclass
class _Record:
    type: int
    crc: int
    data: bytes


class _Decoder:
    """Sequential record reader across segment files, verifying the crc
    chain (reference wal/decoder.go:46-74)."""

    def __init__(self, paths: List[str]) -> None:
        self.paths = paths
        self.fi = 0
        self.f = open(paths[0], "rb") if paths else None
        self.crc = 0
        self.nread = 0           # records consumed so far
        self.last_valid_off = 0  # within current file

    def close(self) -> None:
        if self.f:
            self.f.close()
            self.f = None

    @property
    def path(self) -> str:
        return self.paths[self.fi]

    def decode(self) -> Optional[_Record]:
        """Next record, or None at clean end of the last file. Raises
        UnexpectedEOF / CorruptError on torn or corrupt data."""
        if self.f is None:
            return None
        off = self.f.tell()
        hdr = self.f.read(_REC_HDR.size)
        if len(hdr) == 0:
            # Clean end of this file; move to the next.
            if self.fi + 1 < len(self.paths):
                self.f.close()
                self.fi += 1
                self.f = open(self.paths[self.fi], "rb")
                self.last_valid_off = 0
                return self.decode()
            return None
        if len(hdr) < _REC_HDR.size:
            raise UnexpectedEOF(self.path, off)
        rtype, crc, n = _REC_HDR.unpack(hdr)
        if rtype == 0:
            # A zeroed header is what a torn (pre-allocated / partially
            # synced) tail looks like — repairable, unlike real corruption.
            raise UnexpectedEOF(self.path, off)
        if rtype > SNAPSHOT_TYPE or n > (1 << 40):
            raise CorruptError(self.path, off, f"bad record header type={rtype}")
        data = self.f.read(n)
        if len(data) < n:
            raise UnexpectedEOF(self.path, off)
        if rtype == CRC_TYPE:
            # Segment-boundary seed. If we already consumed records, the seed
            # must CONTINUE the running chain (reference decoder.go checks
            # rec.Crc == d.crc) — a mismatch means a prior segment lost bytes.
            if self.nread > 0 and crc != self.crc:
                raise CorruptError(self.path, off, "crc chain broken")
            self.crc = crc
        else:
            self.crc = zlib.crc32(data, self.crc)
            if self.crc != crc:
                raise CorruptError(self.path, off, "crc mismatch")
        self.nread += 1
        self.last_valid_off = self.f.tell()
        return _Record(type=rtype, crc=crc, data=data)


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------

class WAL:
    """A durable, segmented record log. One instance per data dir; the live
    tail segment holds an exclusive flock."""

    def __init__(self, dirname: str, metadata: bytes,
                 segment_size: int = SEGMENT_SIZE_BYTES) -> None:
        self.dir = dirname
        self.metadata = metadata
        self.segment_size = segment_size
        self.start = WalSnapshot()
        self.state: HardState = EMPTY_HARD_STATE
        self.enti = 0                       # index of last entry saved
        self._locks: List[fileutil.LockedFile] = []  # oldest..newest
        self._names: List[str] = []
        self._enc: Optional[_Encoder] = None
        self._tail = None                    # append file object
        self.fsync_count = 0

    # -- lifecycle -----------------------------------------------------------

    @staticmethod
    def create(dirname: str, metadata: bytes = b"",
               segment_size: int = SEGMENT_SIZE_BYTES) -> "WAL":
        """Initialize a fresh WAL dir with segment 0-0 (reference
        wal.go:87-135: tmp dir + rename for atomicity)."""
        if wal_exists(dirname):
            raise FileExistsError(f"wal already exists in {dirname}")
        tmp = dirname.rstrip("/") + ".tmp"
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp)
        fileutil.create_dir_all(tmp)
        name = wal_name(0, 0)
        f = open(os.path.join(tmp, name), "wb")
        os.fchmod(f.fileno(), fileutil.PRIVATE_FILE_MODE)
        w = WAL(dirname, metadata, segment_size)
        w._tail = f
        w._enc = _Encoder(f, 0)
        w._enc.encode_crc_record()
        w._enc.encode(METADATA_TYPE, metadata)
        w._save_snapshot_record(WalSnapshot())
        w._names = [name]
        f.flush()
        fileutil.fsync(f.fileno())
        fileutil.fsync_dir(tmp)  # make the segment's dir entry durable
        os.rename(tmp, dirname)
        fileutil.fsync_dir(os.path.dirname(dirname.rstrip("/")) or ".")
        # Reopen at the final path and take the lock.
        f.close()
        w._tail = open(os.path.join(dirname, name), "r+b")
        w._tail.seek(0, os.SEEK_END)
        w._enc = _Encoder(w._tail, w._enc.crc)
        w._locks = [fileutil.LockedFile(os.path.join(dirname, name))]
        return w

    @staticmethod
    def open(dirname: str, snap: WalSnapshot = WalSnapshot(), *,
             write: bool = True,
             segment_size: int = SEGMENT_SIZE_BYTES) -> "WAL":
        """Open for reading from `snap` onward; with write=True, flock every
        segment from the one containing snap.index (reference
        wal.go:137-217 Open/OpenNotInUse/openAtIndex)."""
        names = _scan_names(dirname)
        if not names:
            raise FileNotFoundError(f"no wal files in {dirname}")
        # Last file whose first index <= snap.index; if even the oldest
        # segment starts past the snapshot, the region was purged (reference
        # wal.go searchIndex "file not found").
        if parse_wal_name(names[0])[1] > snap.index:
            raise FileNotFoundError(
                f"wal: segment covering index {snap.index} not found in "
                f"{dirname} (purged?)")
        namei = 0
        for i, n in enumerate(names):
            _, idx = parse_wal_name(n)
            if idx <= snap.index:
                namei = i
        names = names[namei:]
        w = WAL(dirname, b"", segment_size)
        w.start = snap
        w._names = names
        if write:
            try:
                for n in names:
                    w._locks.append(
                        fileutil.LockedFile(os.path.join(dirname, n)))
            except BaseException:
                for l in w._locks:
                    l.close()
                raise
        return w

    def close(self) -> None:
        if self._tail is not None:
            self._tail.flush()
            fileutil.fsync(self._tail.fileno())
            self._tail.close()
            self._tail = None
        for l in self._locks:
            l.close()
        self._locks = []

    def __enter__(self) -> "WAL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- replay --------------------------------------------------------------

    def read_all(self) -> Tuple[bytes, HardState, List[Entry]]:
        """Replay records from the start snapshot marker: returns (metadata,
        last HardState, entries with index > snap.index). Raises
        UnexpectedEOF/CorruptError on a torn tail (caller may repair() once
        — reference etcdserver/storage.go:75-107) and SnapshotNotFoundError
        if the start marker never appears (reference wal.go:220-290)."""
        paths = [os.path.join(self.dir, n) for n in self._names]
        dec = _Decoder(paths)
        metadata = b""
        state = EMPTY_HARD_STATE
        ents: List[Entry] = []
        match = self.start.index == 0  # index 0 needs no marker
        try:
            while True:
                rec = dec.decode()
                if rec is None:
                    break
                if rec.type == ENTRY_TYPE:
                    e, _ = raftpb.decode_entry(rec.data)
                    if e.index > self.start.index:
                        # Overwrite semantics: a re-written index truncates
                        # the in-memory tail first (reference wal.go:239-243).
                        keep = e.index - self.start.index - 1
                        ents = ents[:keep]
                        ents.append(e)
                    self.enti = e.index
                elif rec.type == STATE_TYPE:
                    state = raftpb.decode_hard_state(rec.data)
                elif rec.type == METADATA_TYPE:
                    if metadata and rec.data != metadata:
                        raise CorruptError(dec.path, 0,
                                           "inconsistent metadata records")
                    metadata = rec.data
                elif rec.type == SNAPSHOT_TYPE:
                    s = WalSnapshot.decode(rec.data)
                    if s.index == self.start.index:
                        if s.term != self.start.term:
                            raise CorruptError(dec.path, 0,
                                               "snapshot term mismatch")
                        match = True
                # CRC records are consumed inside the decoder.
        finally:
            dec.close()
        if not match:
            raise SnapshotNotFoundError(
                f"wal: snapshot marker {self.start} not found")
        self.metadata = metadata
        self.state = state

        # Writable WAL: position the encoder at the end of the last segment.
        if self._locks and self._tail is None:
            last = os.path.join(self.dir, self._names[-1])
            self._tail = open(last, "r+b")
            self._tail.seek(0, os.SEEK_END)
            self._enc = _Encoder(self._tail, dec.crc)
        return metadata, state, ents

    # -- append --------------------------------------------------------------

    def _ensure_writable(self) -> None:
        if self._enc is None:
            raise RuntimeError("wal: not open for writing (call read_all "
                               "first on an opened WAL)")

    def save(self, st: HardState, ents: List[Entry]) -> None:
        """Append entries + state; fsync only when durability demands it —
        entries appended or term/vote changed. A commit-only HardState
        advance is recorded but NOT synced, since commit is recoverable
        (reference wal.go:459-487 Save + raft MustSync rule)."""
        self._ensure_writable()
        state_changed = not st.is_empty() and st != self.state
        if not ents and not state_changed:
            return
        must_sync = bool(ents) or (not st.is_empty() and
                                   (st.term != self.state.term or
                                    st.vote != self.state.vote))
        for e in ents:
            self._enc.encode(ENTRY_TYPE, raftpb.encode_entry(e))
            self.enti = e.index
        if state_changed:
            self._enc.encode(STATE_TYPE, raftpb.encode_hard_state(st))
            self.state = st
        self._enc.flush()
        if must_sync:
            t0 = time.perf_counter()
            fileutil.fsync(self._tail.fileno())
            metrics.wal_fsync_durations.observe(
                (time.perf_counter() - t0) * 1e6)
            self.fsync_count += 1
        if ents:
            metrics.wal_last_index_saved.set(self.enti)
        if self._tail.tell() >= self.segment_size:
            self._cut()

    def save_snapshot(self, snap: WalSnapshot) -> None:
        """Record a snapshot marker so future opens can skip earlier records
        (reference wal.go:443-457)."""
        self._ensure_writable()
        self._save_snapshot_record(snap)
        self._enc.flush()
        fileutil.fsync(self._tail.fileno())
        self.fsync_count += 1
        if self.start.index < snap.index:
            self.start = snap

    def _save_snapshot_record(self, snap: WalSnapshot) -> None:
        self._enc.encode(SNAPSHOT_TYPE, snap.encode())
        if self.enti < snap.index:
            self.enti = snap.index

    def _cut(self) -> None:
        """Close the current segment and open seq+1 starting at enti+1,
        re-seeding the crc chain and re-writing metadata+state so each
        segment is self-describing (reference wal.go:292-361)."""
        self._tail.flush()
        fileutil.fsync(self._tail.fileno())
        seq, _ = parse_wal_name(self._names[-1])
        name = wal_name(seq + 1, self.enti + 1)
        path = os.path.join(self.dir, name)
        f = open(path, "w+b")
        os.fchmod(f.fileno(), fileutil.PRIVATE_FILE_MODE)
        prev_crc = self._enc.crc
        self._tail.close()
        self._tail = f
        self._enc = _Encoder(f, prev_crc)
        self._enc.encode_crc_record()
        self._enc.encode(METADATA_TYPE, self.metadata)
        if not self.state.is_empty():
            self._enc.encode(STATE_TYPE, raftpb.encode_hard_state(self.state))
        self._enc.flush()
        fileutil.fsync(f.fileno())
        fileutil.fsync_dir(self.dir)
        self._names.append(name)
        self._locks.append(fileutil.LockedFile(path))

    # -- retention -----------------------------------------------------------

    def release_lock_to(self, index: int) -> None:
        """Unlock segments entirely below `index`, keeping the one that
        contains it — they become purgeable (reference wal.go:379-415)."""
        if not self._locks:
            return
        smaller = 0
        for i, n in enumerate(self._names):
            _, idx = parse_wal_name(n)
            if idx < index:
                smaller = i
        # Keep the segment containing `index` (the one before the first
        # segment whose start exceeds it).
        for l in self._locks[:smaller]:
            l.close()
        self._locks = self._locks[smaller:]
        self._names = self._names[smaller:]


def repair(dirname: str) -> bool:
    """One-shot torn-tail repair: decode until the error, truncate the bad
    file there (backing up the original as .broken). Repairable = a torn
    record (UnexpectedEOF) in the LAST file only; CRC corruption, and damage
    to any non-last segment, are not (reference wal/repair.go:29-94 repairs
    zero-length/torn tail records only) — truncating mid-chain would leave a
    silent index gap over committed entries."""
    names = _scan_names(dirname)
    if not names:
        return False
    paths = [os.path.join(dirname, n) for n in names]
    dec = _Decoder(paths)
    try:
        while True:
            if dec.decode() is None:
                return True  # nothing to repair
    except UnexpectedEOF as e:
        if e.path != paths[-1]:
            return False
        bad_path, good_off = e.path, dec.last_valid_off
    except CorruptError:
        return False
    finally:
        dec.close()
    import shutil
    shutil.copyfile(bad_path, bad_path + ".broken")
    with open(bad_path, "r+b") as f:
        f.truncate(good_off)
        fileutil.fsync(f.fileno())
    fileutil.fsync_dir(dirname)
    return True
