from etcd_tpu.wal.wal import (WAL, CorruptError, UnexpectedEOF, WalSnapshot,
                              repair, wal_exists, wal_name, parse_wal_name)

__all__ = ["WAL", "CorruptError", "UnexpectedEOF", "WalSnapshot", "repair",
           "wal_exists", "wal_name", "parse_wal_name"]
