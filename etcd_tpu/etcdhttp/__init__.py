"""HTTP API surface (reference etcdserver/etcdhttp/).

`client` serves the public API (/v2/keys, /v2/members, /v2/stats, /version,
/health); `peer` serves other members (/raft message ingest, /members
bootstrap listing); `web` is the shared threaded-HTTP routing core.
"""
from etcd_tpu.etcdhttp.web import HttpServer  # noqa: F401
from etcd_tpu.etcdhttp.client import ClientAPI  # noqa: F401
from etcd_tpu.etcdhttp.peer import PeerAPI  # noqa: F401
