"""The peer-facing HTTP surface.

Behavioral equivalent of reference etcdserver/etcdhttp/peer.go:27-63 +
rafthttp/http.go inbound handlers: `/raft` ingests batched raft messages
from other members (the pipeline POST path; our frames carry MANY messages
per request — the moral upgrade of msgappv2's batching, SURVEY §2.4),
`/raft/stream` is a receiver-initiated long-poll the remote peer writes
framed messages into, and `/members` serves the member list that remote
joiners bootstrap from (reference cluster_util.go:54-98
GetClusterFromRemotePeers).
"""
from __future__ import annotations

import json
from typing import List

from etcd_tpu import raftpb, version as ver
from etcd_tpu.raftpb import Message, MessageType
from etcd_tpu.etcdhttp.web import Ctx, Router

RAFT_PREFIX = "/raft"
PEER_MEMBERS_PREFIX = "/members"


def decode_frames(body: bytes) -> List[Message]:
    """Split a request body of concatenated encoded Messages."""
    msgs: List[Message] = []
    off = 0
    while off < len(body):
        m, off = raftpb.decode_message(body, off)
        msgs.append(m)
    return msgs


def encode_frames(msgs) -> bytes:
    return b"".join(raftpb.encode_message(m) for m in msgs)


class PeerAPI:
    """Routes for one EtcdServer's peer listener."""

    def __init__(self, server) -> None:
        self.server = server

    def install(self, router: Router) -> None:
        router.add(RAFT_PREFIX, self.handle_raft)
        router.add(PEER_MEMBERS_PREFIX, self.handle_members, exact=True)
        router.add("/version", self.handle_version, exact=True)

    def handle_raft(self, ctx: Ctx, suffix: str) -> None:
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "POST"})
            return
        # Cluster-ID check (reference rafthttp/http.go:69-77): traffic from
        # another cluster is rejected with 412.
        want = f"{self.server.cluster.cluster_id:x}"
        got = ctx.headers.get("X-Etcd-Cluster-ID")
        if got and got != want:
            ctx.send(412, b"cluster ID mismatch\n")
            return
        try:
            msgs = decode_frames(ctx.body)
        except Exception:
            ctx.send(400, b"error decoding raft message\n")
            return
        for m in msgs:
            if m.type == MessageType.APP:
                self.server.stats.recv_append_req(
                    m.frm, len(ctx.body) // max(len(msgs), 1))
            self.server.process(m)
        ctx.send(204)

    def handle_members(self, ctx: Ctx, suffix: str) -> None:
        if ctx.method != "GET":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "GET"})
            return
        members = [{"id": f"{m.id:x}", "name": m.name,
                    "peerURLs": list(m.peer_urls),
                    "clientURLs": list(m.client_urls)}
                   for m in self.server.cluster.members()]
        ctx.send_json(200, {"members": members},
                      {"X-Etcd-Cluster-ID":
                       f"{self.server.cluster.cluster_id:x}"})

    def handle_version(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"etcdserver": ver.VERSION,
                            "etcdcluster": self.server.cluster_version()})
