"""HTTP gateway for the v3 KV preview (reference Documentation/rfc/
v3api.md + v3api.proto Range/Put/DeleteRange/Txn/Compact rpcs, served the
etcd JSON-gateway way: POST with a JSON body, bytes fields base64).

    POST /v3/kv/range        RangeRequest   -> RangeResponse
    POST /v3/kv/put          PutRequest     -> PutResponse
    POST /v3/kv/deleterange  DeleteRangeRequest -> DeleteRangeResponse
    POST /v3/kv/txn          TxnRequest     -> TxnResponse
    POST /v3/kv/compact      CompactionRequest -> CompactionResponse
    POST /v3/watch           WatchRange     -> chunked stream of
                             {"result": {header, events}} JSON lines
                             (created confirmation first; start_revision
                             replays history)
    POST /v3/lease/grant     LeaseCreateRequest -> {lease_id, ttl}
    POST /v3/lease/revoke    LeaseRevokeRequest -> header (attached keys
                             deleted at one revision)
    POST /v3/lease/attach    LeaseAttachRequest -> header
    POST /v3/lease/keepalive LeaseKeepAliveRequest -> {lease_id, ttl}
                             (single-shot POST; expiry is enacted by the
                             leader as a replicated revoke)
    POST /v3/lease/txn       LeaseTnxRequest {request, success, failure}
                             -> {header, response, attach_responses}

Every rpc the RFC declares is served.

Mutations (and linearizable ranges) ride the member's consensus log as
METHOD_V3 requests; serializable ranges (`"serializable": true`) read the
local kvstore directly.
"""
from __future__ import annotations

import json

from etcd_tpu import errors
from etcd_tpu.etcdhttp.web import Ctx, Router
from etcd_tpu.server.request import METHOD_V3, Request
from etcd_tpu.server.v3 import V3Error, validate_op

V3_PREFIX = "/v3"


class V3API:
    def __init__(self, server, security=None) -> None:
        self.server = server
        self.security = security

    def install(self, router: Router) -> None:
        router.add(V3_PREFIX + "/", self.handle)

    def handle(self, ctx: Ctx, suffix: str) -> None:
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "POST"})
            return
        if getattr(self.server, "_fatal", False):
            # Serializable reads bypass do(); refuse them too — the
            # in-memory index may have forked from the rolled-back backend.
            self._err(ctx, 500, 13,
                      "member failed (fatal apply error); restart required")
            return
        if getattr(self.server, "v3_gapped", False):
            # A legacy snapshot (no v3 image) outran this member's v3
            # backend: its keyspace has a hole and would serve forked
            # data — refuse everything, including serializable reads.
            self._err(ctx, 503, 14,
                      "v3 keyspace gapped by snapshot install; member "
                      "resync required")
            return
        # v2 auth has no v3 user model, so when security is enabled the
        # whole v3 preview surface requires root credentials — the same
        # listener must not offer an unauthenticated write path (the
        # admin-ops rule, reference client_security.go hasRootAccess).
        if self.security is not None and not self.security.has_root_access(
                ctx):
            ctx.send(401, b'{"error": "Insufficient credentials", '
                          b'"code": 16}\n', "application/json",
                     {"WWW-Authenticate": 'Basic realm="etcd"'})
            return
        try:
            body = json.loads(ctx.body.decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._err(ctx, 400, 3, f"bad request body: {e}")
            return
        if suffix == "watch":
            self._handle_watch(ctx, body)
            return
        route = {
            "kv/range": "range", "kv/put": "put",
            "kv/deleterange": "deleterange", "kv/txn": "txn",
            "kv/compact": "compact",
            "lease/grant": "lease_create", "lease/create": "lease_create",
            "lease/revoke": "lease_revoke",
            "lease/attach": "lease_attach",
            "lease/keepalive": "lease_keepalive",
            "lease/txn": "lease_txn",
        }.get(suffix)
        if route is None:
            self._err(ctx, 404, 3, f"unknown v3 path {suffix!r}")
            return
        op = dict(body)
        op["type"] = route
        # Lease ops carry no clocks at all (expiry is judged purely on the
        # leader's clock against renewal-seq transitions); the gateway
        # only assigns a fresh id when the client didn't pick one, and
        # strips any client-supplied revoke fence (explicit revokes are
        # unconditional; only the leader's expiry monitor fences).
        if route == "lease_create" and not op.get("lease_id"):
            op["lease_id"] = self.server.reqid.next()
        elif route == "lease_revoke":
            op.pop("seq", None)
        try:
            # Reject malformed ops HERE — nothing unvalidated may enter
            # the consensus log (apply re-validates; defense in depth).
            validate_op(op)
            if route == "range" and body.get("serializable"):
                result = self.server.v3.range(op)
            else:
                if route == "range":
                    op["linearizable"] = True
                result = self.server.do(Request(method=METHOD_V3, v3=op))
        except V3Error as e:
            self._v3err(ctx, e)
            return
        except errors.EtcdError as e:
            self._err(ctx, e.status_code, 13, e.message)
            return
        except (KeyError, ValueError, TypeError) as e:
            self._err(ctx, 400, 3, f"bad v3 request: {e}")
            return
        if isinstance(result, V3Error):   # deterministic apply-side error
            self._v3err(ctx, result)
            return
        ctx.send_json(200, result)

    def _handle_watch(self, ctx: Ctx, body: dict) -> None:
        """Streamed WatchRange (RFC v3api.proto WatchRange rpc): a chunked
        response of JSON lines — first a created confirmation, then one
        {"result": {header, events}} line per committed revision touching
        the range. start_revision replays history first (compacted ->
        error), exactly like etcd's watch."""
        import base64
        from etcd_tpu.server.v3 import V3Error as _V3E
        from etcd_tpu.server.v3 import validate_op

        try:
            validate_op({**{k: body.get(k) for k in
                            ("key", "range_end", "limit")},
                         "type": "range",
                         "revision": body.get("start_revision")})
            key = base64.b64decode(body["key"])
            end = (base64.b64decode(body["range_end"])
                   if body.get("range_end") else None)
            start = int(body.get("start_revision") or 0)
            w, replay = self.server.v3.watch(key, end, start)
        except _V3E as e:
            self._v3err(ctx, e)
            return
        try:
            ctx.begin_stream(200, "application/json")
            created = {"result": {
                "header": {"revision": self.server.v3.kv.current_rev.main},
                "created": True}}
            if not ctx.write_chunk(json.dumps(created).encode() + b"\n"):
                return
            # Historical replay streams straight from the backend (lazy,
            # chunked) before the live queue takes over at the fence. A
            # compaction overtaking the replay cancels the watch (etcd's
            # behavior) rather than delivering a gap-ridden history.
            try:
                for rev, events in (replay or ()):
                    line = json.dumps({"result": {
                        "header": {"revision": rev},
                        "events": events}}).encode() + b"\n"
                    if not ctx.write_chunk(line):
                        return
            except _V3E as e:
                ctx.write_chunk(json.dumps(
                    {"result": {"canceled": True,
                                "reason": e.msg}}).encode() + b"\n")
                ctx.end_stream()
                return
            while True:
                batch = w.next_batch(timeout=0.5)
                if batch is not None:
                    rev, events = batch
                    line = json.dumps({"result": {
                        "header": {"revision": rev},
                        "events": events}}).encode() + b"\n"
                    if not ctx.write_chunk(line):
                        return
                elif w.cancelled:
                    # Slow consumer: the hub dropped this watcher rather
                    # than buffer without bound (etcd cancels, clients
                    # re-watch from their last seen revision).
                    ctx.write_chunk(json.dumps(
                        {"result": {"canceled": True,
                                    "reason": "watcher queue overflow"}}
                    ).encode() + b"\n")
                    ctx.end_stream()
                    return
                elif ctx.client_gone() or self.server.stopped or \
                        getattr(self.server, "_fatal", False):
                    ctx.end_stream()
                    return
        finally:
            w.remove()

    def _v3err(self, ctx: Ctx, e: V3Error) -> None:
        # grpc code 11 = OutOfRange (compacted), 3 = InvalidArgument.
        status = {11: 400, 3: 400, 12: 501}.get(e.code, 400)
        self._err(ctx, status, e.code, e.msg)

    def _err(self, ctx: Ctx, status: int, code: int, msg: str) -> None:
        ctx.send_json(status, {"error": msg, "code": code})
