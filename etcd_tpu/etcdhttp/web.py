"""Threaded HTTP routing core shared by the client and peer APIs.

The reference hangs its handlers off Go's net/http ServeMux
(etcdhttp/client.go:85-114); this is the same shape over Python's
ThreadingHTTPServer: one OS thread per connection (long-poll watches hold
theirs), prefix routing, and a Ctx that can either buffer one response or
switch into chunked streaming for watch streams.
"""
from __future__ import annotations

import json
import select
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit


class Ctx:
    """One request: parsed query+form values, response helpers, and a
    client-disconnect probe for long-polls."""

    def __init__(self, handler: BaseHTTPRequestHandler, method: str,
                 path: str, query: Dict[str, List[str]], body: bytes) -> None:
        self._h = handler
        self.method = method
        self.path = path
        self.body = body
        self._values: Dict[str, List[str]] = dict(query)
        ctype = handler.headers.get("Content-Type", "")
        if body and ctype.startswith("application/x-www-form-urlencoded"):
            # Body parameters take precedence over the URL query string
            # (Go net/http Request.Form semantics the reference relies on).
            for k, v in parse_qs(body.decode("utf-8", "replace"),
                                 keep_blank_values=True).items():
                self._values[k] = v + self._values.get(k, [])
        self._streaming = False
        # Extra headers injected into every response (CORS); set by the
        # server before dispatch.
        self.extra_headers: Dict[str, str] = {}

    # -- inputs -------------------------------------------------------------

    @property
    def headers(self):
        return self._h.headers

    def has(self, key: str) -> bool:
        return key in self._values

    def value(self, key: str, default: str = "") -> str:
        v = self._values.get(key)
        return v[0] if v else default

    def remote_addr(self) -> str:
        return f"{self._h.client_address[0]}:{self._h.client_address[1]}"

    # -- buffered responses ---------------------------------------------------

    def send(self, status: int, body: bytes = b"",
             content_type: str = "text/plain",
             headers: Optional[Dict[str, str]] = None) -> None:
        h = self._h
        h.send_response(status)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        for k, v in self.extra_headers.items():
            h.send_header(k, v)
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        if body and self.method != "HEAD":
            h.wfile.write(body)

    def send_json(self, status: int, obj,
                  headers: Optional[Dict[str, str]] = None) -> None:
        self.send(status, json.dumps(obj).encode(), "application/json",
                  headers)

    # -- chunked streaming (watch streams) ------------------------------------

    def begin_stream(self, status: int, content_type: str,
                     headers: Optional[Dict[str, str]] = None) -> None:
        h = self._h
        # A stream writer must never block forever on a stalled client:
        # with no socket timeout, a peer that stops reading (TCP buffers
        # full) would pin this handler thread inside wfile.write and its
        # watcher would never be released. timeout -> OSError subclass ->
        # write_chunk returns False -> the loop cleans up.
        try:
            h.connection.settimeout(30.0)
        except OSError:
            pass
        h.send_response(status)
        h.send_header("Content-Type", content_type)
        h.send_header("Transfer-Encoding", "chunked")
        for k, v in self.extra_headers.items():
            h.send_header(k, v)
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        self._streaming = True

    def write_chunk(self, data: bytes) -> bool:
        try:
            w = self._h.wfile
            w.write(f"{len(data):x}\r\n".encode())
            w.write(data)
            w.write(b"\r\n")
            w.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False

    def end_stream(self) -> None:
        try:
            self._h.wfile.write(b"0\r\n\r\n")
            self._h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    # -- connection takeover (binary upgrade endpoints) -----------------------

    def hijack(self):
        """Take over the raw connection for a non-HTTP framed protocol
        (the batchframe channel's 101 upgrade): returns (rfile, wfile)
        positioned right after this request's body. The caller owns the
        socket until it returns from its handler; the server then closes
        the connection (keep-alive re-parse of binary frames as HTTP
        would be garbage)."""
        self._streaming = True      # handler loop closes the conn after
        return self._h.rfile, self._h.wfile

    def client_gone(self) -> bool:
        """True once the peer closed its half of the connection — the
        CloseNotify analogue that lets long-polls release their watcher
        (reference client.go:571-576)."""
        try:
            sock = self._h.connection
            r, _, _ = select.select([sock], [], [], 0)
            if not r:
                return False
            data = sock.recv(1, socket.MSG_PEEK)
            return len(data) == 0
        except (OSError, ValueError):
            return True


Route = Tuple[str, bool, Callable[[Ctx, str], None]]


class Router:
    """Longest-prefix-wins routing. Handlers get (ctx, suffix) where suffix
    is the path remainder after the matched prefix."""

    def __init__(self) -> None:
        self._routes: List[Route] = []

    def add(self, prefix: str, fn: Callable[[Ctx, str], None],
            exact: bool = False) -> None:
        self._routes.append((prefix, exact, fn))
        self._routes.sort(key=lambda r: len(r[0]), reverse=True)

    def dispatch(self, ctx: Ctx) -> bool:
        for prefix, exact, fn in self._routes:
            if exact:
                if ctx.path == prefix:
                    fn(ctx, "")
                    return True
            elif ctx.path == prefix or ctx.path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/"):
                fn(ctx, ctx.path[len(prefix):])
                return True
        return False


class HttpServer:
    """A ThreadingHTTPServer bound to a Router; daemon threads so watches
    never block shutdown."""

    def __init__(self, host: str, port: int, router: Router,
                 server_version: str = "etcd-tpu",
                 cors: Optional[set] = None, tls_context=None) -> None:
        self.router = router
        # CORS origin whitelist ("*" = any); None disables CORS handling
        # (reference pkg/cors/cors.go CORSInfo + CORSHandler).
        self.cors = set(cors) if cors else None

        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version_str = server_version

            def log_message(self, fmt, *args):  # silence stderr chatter
                pass

            def setup(self):
                # TLS handshakes run here, in the per-connection handler
                # thread — never in the accept loop, where a slow client
                # would head-of-line block every other connection.
                if outer._tls:
                    self.request.do_handshake()
                super().setup()

            def _run(self, method: str) -> None:
                try:
                    parts = urlsplit(self.path)
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    ctx = Ctx(self, method, unquote(parts.path),
                              parse_qs(parts.query, keep_blank_values=True),
                              body)
                    if outer.cors is not None:
                        # reference CORSHandler.ServeHTTP: header on every
                        # allowed-origin response; OPTIONS answered 200.
                        if "*" in outer.cors:
                            allow = "*"
                        else:
                            origin = self.headers.get("Origin", "")
                            allow = origin if origin in outer.cors else None
                        if allow is not None:
                            ctx.extra_headers = {
                                "Access-Control-Allow-Methods":
                                    "POST, GET, OPTIONS, PUT, DELETE",
                                "Access-Control-Allow-Origin": allow,
                                "Access-Control-Allow-Headers":
                                    "accept, content-type",
                            }
                        if method == "OPTIONS":
                            ctx.send(200)
                            return
                    if not outer.router.dispatch(ctx):
                        ctx.send(404, b"404 page not found\n")
                    if ctx._streaming:
                        self.close_connection = True
                except (BrokenPipeError, ConnectionResetError):
                    self.close_connection = True
                except Exception as e:  # pragma: no cover - last resort
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass
                    self.close_connection = True

            def do_GET(self):
                self._run("GET")

            def do_PUT(self):
                self._run("PUT")

            def do_POST(self):
                self._run("POST")

            def do_DELETE(self):
                self._run("DELETE")

            def do_HEAD(self):
                self._run("HEAD")

            def do_OPTIONS(self):
                self._run("OPTIONS")

        class _Server(ThreadingHTTPServer):
            """Tracks live connections so stop() can sever keep-alive
            sockets: shutdown() alone only closes the LISTENING socket,
            leaving handler threads serving old connections — a stopped
            member would otherwise keep answering peers as a zombie."""
            daemon_threads = True
            # socketserver's default listen backlog of 5 resets connections
            # under concurrent client bursts (reference etcd serves 256+
            # concurrent clients in its benchmarks).
            request_queue_size = 128

            def __init__(self, addr, handler):
                self._conns: set = set()
                self._conns_lock = threading.Lock()
                super().__init__(addr, handler)

            def process_request(self, request, client_address):
                with self._conns_lock:
                    self._conns.add(request)
                super().process_request(request, client_address)

            def shutdown_request(self, request):
                with self._conns_lock:
                    self._conns.discard(request)
                super().shutdown_request(request)

            def close_all_connections(self):
                with self._conns_lock:
                    conns = list(self._conns)
                for sock in conns:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        self._httpd = _Server((host, port), _Handler)
        self._scheme = "https" if tls_context is not None else "http"
        self._tls = tls_context is not None
        if tls_context is not None:
            # TLS listener (reference pkg/transport NewTLSListener,
            # listener.go:60-80): wrap the accept socket; per-connection
            # handshakes happen in the handler threads.
            self._httpd.socket = tls_context.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{self._scheme}://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True, name="etcd-http")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.close_all_connections()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
