"""/v2/security HTTP surface + request auth gating.

Behavioral equivalent of reference etcdserver/etcdhttp/client_security.go:
Basic-auth extraction, hasRootAccess (all /v2/security and mutating
/v2/members calls need the root role once security is on,
client_security.go:28-70), hasKeyPrefixAccess with guest fallback for
unauthenticated requests (client_security.go:72-120), the
users/roles/enable handler trio (client_security.go:135-420), and the
security capability gate: the endpoints answer 400 until the cluster
version reaches 2.1.0 (capability.go:16-58, rolling-upgrade safety).
Security errors answer 400 (http.go:55-57); missing credentials answer
401 "Insufficient credentials" (client_security.go:122-125).
"""
from __future__ import annotations

import base64
import json
import logging
from typing import List, Optional, Tuple

from etcd_tpu import version as ver
from etcd_tpu.etcdhttp.web import Ctx, Router
from etcd_tpu.server.security import (GUEST_ROLE, ROOT_ROLE, Role,
                                      SecurityError, SecurityStore)

log = logging.getLogger("etcdhttp")

SECURITY_PREFIX = "/v2/security"


def basic_auth(ctx: Ctx) -> Optional[Tuple[str, str]]:
    h = ctx.headers.get("Authorization", "")
    if not h.startswith("Basic "):
        return None
    try:
        raw = base64.b64decode(h[6:]).decode()
        user, _, pw = raw.partition(":")
        return user, pw
    except Exception:
        return None


class SecurityHandler:
    """Auth gate + /v2/security routes for one member's client listener."""

    def __init__(self, server) -> None:
        self.server = server
        self.sec = SecurityStore(server)

    # -- capability gate (reference capability.go) --------------------------

    def _capable(self, ctx: Ctx) -> bool:
        cv = self.server.cluster_version() or "2.0.0"
        if ver.parse(cv) >= (2, 1, 0):
            return True
        ctx.send_json(400, {"message":
                            "Not capable of accessing security feature "
                            "during rolling upgrades."})
        return False

    # -- access checks ------------------------------------------------------

    def enabled(self) -> bool:
        return self.sec.enabled()

    def has_root_access(self, ctx: Ctx) -> bool:
        """reference hasRootAccess client_security.go:34-70."""
        if not self.enabled():
            return True
        cred = basic_auth(ctx)
        if cred is None:
            return False
        username, password = cred
        try:
            user = self.sec.get_user(username)
        except SecurityError:
            return False
        if not user.check_password(password):
            log.info("security: wrong password for user %s", username)
            return False
        if ROOT_ROLE in user.roles:
            return True
        log.info("security: user %s does not have the %s role", username,
                 ROOT_ROLE)
        return False

    def has_write_root_access(self, ctx: Ctx) -> bool:
        if ctx.method in ("GET", "HEAD"):
            return True
        return self.has_root_access(ctx)

    def has_key_prefix_access(self, ctx: Ctx, key: str,
                              recursive: bool) -> bool:
        """reference hasKeyPrefixAccess client_security.go:72-104."""
        if not self.enabled():
            return True
        cred = basic_auth(ctx)
        write = ctx.method not in ("GET", "HEAD")
        if cred is None:
            return self._has_guest_access(key, write)
        username, password = cred
        try:
            user = self.sec.get_user(username)
        except SecurityError:
            log.info("security: no such user: %s", username)
            return False
        if not user.check_password(password):
            log.info("security: incorrect password for user: %s", username)
            return False
        # Grant if ANY role grants. (The reference returns the verdict of
        # the first resolvable role, client_security.go:92-99 — a known
        # upstream defect that strands multi-role users on their
        # alphabetically-first role; we check them all.)
        for role_name in user.roles:
            try:
                role = self.sec.get_role(role_name)
            except SecurityError:
                continue
            ok = (role.has_recursive_access(key, write) if recursive
                  else role.has_key_access(key, write))
            if ok:
                return True
        log.info("security: invalid access for user %s on key %s",
                 username, key)
        return False

    def _has_guest_access(self, key: str, write: bool) -> bool:
        try:
            role = self.sec.get_role(GUEST_ROLE)
        except SecurityError:
            return False
        return role.has_key_access(key, write)

    def check_key_access(self, ctx: Ctx, r) -> None:
        """The ClientAPI /v2/keys gate (reference client.go:135-137).
        Raises 401 as an API error when access is denied."""
        from etcd_tpu import errors
        from etcd_tpu.server.cluster import STORE_KEYS_PREFIX
        key = r.path
        if key.startswith(STORE_KEYS_PREFIX):
            key = key[len(STORE_KEYS_PREFIX):]
        key = key or "/"  # GET /v2/keys/ normalizes to the bare prefix
        if not self.has_key_prefix_access(ctx, key, r.recursive):
            raise errors.EtcdError(errors.ECODE_UNAUTHORIZED,
                                   cause="Insufficient credentials")

    def check_members_access(self, ctx: Ctx) -> bool:
        """Mutating /v2/members calls need root once security is on
        (reference client.go:184-187 hasWriteRootAccess)."""
        return self.has_write_root_access(ctx)

    # -- routes -------------------------------------------------------------

    def install(self, router: Router) -> None:
        router.add(SECURITY_PREFIX + "/roles", self.handle_roles)
        router.add(SECURITY_PREFIX + "/users", self.handle_users)
        router.add(SECURITY_PREFIX + "/enable", self.handle_enable,
                   exact=True)

    def _headers(self):
        return {"X-Etcd-Cluster-ID": f"{self.server.cluster.cluster_id:x}"}

    def _no_auth(self, ctx: Ctx) -> None:
        ctx.send_json(401, {"message": "Insufficient credentials"})

    def _error(self, ctx: Ctx, e: Exception) -> None:
        if isinstance(e, SecurityError):
            ctx.send_json(400, {"message": str(e)})
        else:
            ctx.send_json(500, {"message": "Internal Server Error"})

    # /v2/security/roles[/name]
    def handle_roles(self, ctx: Ctx, suffix: str) -> None:
        if not self._capable(ctx):
            return
        name = suffix.strip("/")
        if not name:
            if ctx.method != "GET":
                ctx.send(405, b"Method Not Allowed",
                         headers={"Allow": "GET"})
                return
            if not self.has_root_access(ctx):
                return self._no_auth(ctx)
            try:
                roles = self.sec.all_roles()
            except Exception as e:
                return self._error(ctx, e)
            ctx.send_json(200, {"roles": roles}, self._headers())
            return
        if "/" in name:
            ctx.send_json(400, {"message": "Invalid path"})
            return
        if ctx.method not in ("GET", "PUT", "DELETE"):
            ctx.send(405, b"Method Not Allowed",
                     headers={"Allow": "GET, PUT, DELETE"})
            return
        if not self.has_root_access(ctx):
            return self._no_auth(ctx)
        try:
            if ctx.method == "GET":
                role = self.sec.get_role(name)
                ctx.send_json(200, role.to_dict(), self._headers())
            elif ctx.method == "PUT":
                try:
                    body = json.loads(ctx.body or b"{}")
                except ValueError:
                    ctx.send_json(400,
                                  {"message": "Invalid JSON in request body."})
                    return
                if body.get("role") != name:
                    ctx.send_json(400, {"message":
                                        "Role JSON name does not match the "
                                        "name in the URL"})
                    return
                role, created = self.sec.create_or_update_role(
                    name, body.get("permissions"), body.get("grant"),
                    body.get("revoke"))
                ctx.send_json(201 if created else 200, role.to_dict(),
                              self._headers())
            else:
                self.sec.delete_role(name)
                ctx.send(200, b"", headers=self._headers())
        except Exception as e:
            self._error(ctx, e)

    # /v2/security/users[/name]
    def handle_users(self, ctx: Ctx, suffix: str) -> None:
        if not self._capable(ctx):
            return
        name = suffix.strip("/")
        if not name:
            if ctx.method != "GET":
                ctx.send(405, b"Method Not Allowed",
                         headers={"Allow": "GET"})
                return
            if not self.has_root_access(ctx):
                return self._no_auth(ctx)
            try:
                users = self.sec.all_users()
            except Exception as e:
                return self._error(ctx, e)
            ctx.send_json(200, {"users": users}, self._headers())
            return
        if "/" in name:
            ctx.send_json(400, {"message": "Invalid path"})
            return
        if ctx.method not in ("GET", "PUT", "DELETE"):
            ctx.send(405, b"Method Not Allowed",
                     headers={"Allow": "GET, PUT, DELETE"})
            return
        if not self.has_root_access(ctx):
            return self._no_auth(ctx)
        try:
            if ctx.method == "GET":
                u = self.sec.get_user(name)
                ctx.send_json(200, u.to_dict(with_password=False),
                              self._headers())
            elif ctx.method == "PUT":
                try:
                    body = json.loads(ctx.body or b"{}")
                except ValueError:
                    ctx.send_json(400,
                                  {"message": "Invalid JSON in request body."})
                    return
                if body.get("user") != name:
                    ctx.send_json(400, {"message":
                                        "User JSON name does not match the "
                                        "name in the URL"})
                    return
                u, created = self.sec.create_or_update_user(
                    name, body.get("password", ""), body.get("roles"),
                    body.get("grant"), body.get("revoke"))
                ctx.send_json(201 if created else 200,
                              u.to_dict(with_password=False), self._headers())
            else:
                self.sec.delete_user(name)
                ctx.send(200, b"", headers=self._headers())
        except Exception as e:
            self._error(ctx, e)

    # /v2/security/enable
    def handle_enable(self, ctx: Ctx, suffix: str) -> None:
        if not self._capable(ctx):
            return
        if ctx.method == "GET":
            ctx.send_json(200, {"enabled": self.enabled()}, self._headers())
            return
        if ctx.method not in ("PUT", "DELETE"):
            ctx.send(405, b"Method Not Allowed",
                     headers={"Allow": "GET, PUT, DELETE"})
            return
        if not self.has_root_access(ctx):
            return self._no_auth(ctx)
        try:
            if ctx.method == "PUT":
                self.sec.enable()
            else:
                self.sec.disable()
            ctx.send(200, b"", headers=self._headers())
        except Exception as e:
            self._error(ctx, e)
