"""Multi-tenant HTTP surface for the batched MultiNode engine: the full
/v2/keys matrix served per consensus group from ONE kernel.

Routes (the multi-tenant re-framing of reference etcdserver/etcdhttp —
each tenant group gets the same client API one etcd cluster exposes):

    /tenants/{g}/v2/keys/...   full v2 keys CRUD/CAS/CAD/watch (reuses
                               ClientAPI via a per-tenant server adapter)
    /tenants/{g}/batch         POST a coalesced batch of writes served by
                               MultiEngine.do_many — the ingress tier's
                               upstream surface (server/ingress.py); one
                               HTTP request fans into one deep P_MULTI
                               log entry and N in-slot results
    /tenants/{g}/batchframe    POST + Upgrade: etcd-batchframe -> 101,
                               then the persistent binary flush channel
                               (server/batchframe.py): length-prefixed
                               request/response frames, pipelined up to
                               the ingress flush window, submitted via
                               MultiEngine.submit_many in frame order
                               and collected off-thread so the staging
                               queue never drains between flushes
    /tenants/{g}/status        group consensus status (leader, term,
                               commit, applied, active slots)
    /tenants/{g}/conf          POST {"op": "add"|"remove", "slot": n} —
                               membership change through the group's own
                               consensus (reference /v2/members semantics)
    /engine/status             engine-wide summary
    /health, /version          liveness + version (reference client.go)
"""
from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict

from etcd_tpu import errors, version
from etcd_tpu.etcdhttp.client import ClientAPI
from etcd_tpu.etcdhttp.web import Ctx, HttpServer, Router


class _TenantCluster:
    """Just enough cluster surface for ClientAPI._headers."""

    def __init__(self, g: int) -> None:
        self.cluster_id = g


class _BatchSlotCtx:
    """Ctx facade scoping one batch slot's auth check to the credentials
    the ingress forwarded for THAT slot. The outer connection belongs to
    the ingress process, not the client — evaluating every slot against
    it would collapse all coalesced writers into one anonymous identity
    and make per-user ACLs unenforceable through the ingress."""

    __slots__ = ("method", "headers")

    def __init__(self, method: str, auth) -> None:
        self.method = method
        # No credentials -> empty headers: the slot is evaluated as the
        # anonymous guest, never as the carrying ingress connection.
        self.headers = {"Authorization": auth} if auth else {}


class _TenantServer:
    """Adapts one engine group to the `server` interface ClientAPI drives
    (do/store/clock/stopped/commit_index/term), so the entire keys path —
    parsing, CAS/CAD, long-poll + stream watch — is shared verbatim with
    the single-cluster server (etcdhttp/client.py)."""

    def __init__(self, engine, g: int) -> None:
        self._engine = engine
        self._g = g
        self.cluster = _TenantCluster(g)
        self.clock = time.time

    def cluster_version(self) -> str:
        # All tenants of one engine run the binary's version — there is no
        # per-tenant rolling upgrade, so the security capability gate
        # (reference capability.go) is always open.
        return version.VERSION

    def do(self, r):
        return self._engine.do(self._g, r)

    @property
    def store(self):
        return self._engine.store(self._g)

    @property
    def stopped(self) -> bool:
        return self._engine._stop_ev.is_set()

    @property
    def commit_index(self) -> int:
        return int(self._engine.h_commit[self._g].max())

    @property
    def term(self) -> int:
        return int(self._engine.h_term[self._g].max())


class TenantAPI:
    """Router glue: dispatches /tenants/{g}/... to per-tenant ClientAPIs.

    `admin_credentials` is an optional ("user", "password") pair; when set,
    every pool-wide lifecycle verb (POST /tenants, PUT/DELETE /tenants/{g})
    requires matching HTTP basic auth — the engine-operator analogue of the
    reference's root gate on /v2/members (client.go:184-187). Independent
    of it, DELETE on a tenant whose OWN auth is enabled always requires
    that tenant's root credentials: destroying an authenticated tenant's
    keyspace is strictly stronger than shrinking its quorum, which is
    already root-gated via /tenants/{g}/conf."""

    def __init__(self, engine, admin_credentials=None) -> None:
        self.engine = engine
        self.admin_credentials = admin_credentials
        # Caches keyed by the engine's per-slot lifecycle generation: a
        # slot removed + recreated (via HTTP here, the engine API
        # directly, or another frontend) must never be served through the
        # previous generation's SecurityHandler/store adapters.
        self._apis: Dict[int, tuple] = {}   # g -> (gen, ClientAPI)
        self._secs: Dict[int, tuple] = {}   # g -> (gen, SecurityHandler)

    def install(self, router: Router) -> None:
        router.add("/tenants", self.handle_tenants_root, exact=True)
        router.add("/tenants/", self.handle_tenants)
        router.add("/engine/status", self.handle_engine_status)
        router.add("/metrics", self.handle_metrics)
        router.add("/debug/flight", self.handle_debug_flight)
        router.add("/debug/traces", self.handle_debug_traces)
        router.add("/health", self.handle_health)
        router.add("/version", self.handle_version)

    def handle_tenants_root(self, ctx: Ctx, suffix: str) -> None:
        """GET /tenants lists provisioned tenants; POST /tenants
        provisions one at the lowest free pool slot (optional body
        {"peers": n}) — the runtime CreateGroup of reference
        raft/multinode.go:181-218."""
        if ctx.method == "GET":
            ctx.send_json(200, {"tenants": self.engine.tenants(),
                                "pool": self.engine.cfg.groups})
            return
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed",
                     headers={"Allow": "GET, POST"})
            return
        if not self._lifecycle_ok(ctx):
            ctx.send_json(401, {"message": "Insufficient credentials"})
            return
        self._create(ctx, None)

    def _create(self, ctx: Ctx, g) -> None:
        try:
            body = json.loads(ctx.body.decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            n = body.get("peers")
            if n is not None:
                n = int(n)
            gid = self.engine.create_tenant(g, n)
        except errors.EtcdError as e:
            ctx.send(e.status_code, e.to_json().encode() + b"\n",
                     "application/json")
            return
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            ctx.send_json(400, {"message": f"bad create body: {e}"})
            return
        # Creation always assigns slots 0..n-1 (deterministic — no racy
        # re-read of the live mask here).
        n = n or self.engine.cfg.initial_peers or self.engine.cfg.peers
        ctx.send_json(201, {"tenant": gid, "active_slots": list(range(n))})

    def _api(self, g: int) -> ClientAPI:
        gen = int(self.engine.tenant_gen[g])
        hit = self._apis.get(g)
        if hit is not None and hit[0] == gen:
            return hit[1]
        # Per-tenant auth: each tenant gets its own SecurityHandler
        # whose users/roles/enabled flag live under /2/security/* of
        # the TENANT's OWN replicated keyspace (the security.go:66-68
        # doer seam bound to this group's consensus) — tenants enable
        # and administer auth independently of each other.
        from etcd_tpu.etcdhttp.client_security import SecurityHandler
        srv = _TenantServer(self.engine, g)
        sec = SecurityHandler(srv)
        api = ClientAPI(srv, security=sec)
        self._secs[g] = (gen, sec)
        self._apis[g] = (gen, api)
        return api

    def _sec(self, g: int):
        self._api(g)
        return self._secs[g][1]

    def _lifecycle_ok(self, ctx: Ctx, g=None) -> bool:
        """Gate for pool lifecycle verbs (create/remove). Two principals
        may act: the ENGINE OPERATOR (when the frontend was configured
        with admin credentials) anywhere in the pool, and — for verbs
        aimed at a live tenant — that tenant's OWN root (when the tenant
        enabled auth). Without configured admin credentials, lifecycle is
        open EXCEPT against tenants that enabled auth, which always
        require their root (deleting an authenticated tenant's keyspace
        is strictly stronger than the already-root-gated quorum shrink
        on /tenants/{g}/conf)."""
        from etcd_tpu.etcdhttp.client_security import basic_auth
        if self.admin_credentials is not None:
            if basic_auth(ctx) == tuple(self.admin_credentials):
                return True
            if g is not None and self.engine.tenant_active(g):
                sec = self._sec(g)
                return sec.enabled() and sec.has_root_access(ctx)
            return False
        if g is not None and self.engine.tenant_active(g):
            return self._sec(g).check_members_access(ctx)
        return True

    def handle_tenants(self, ctx: Ctx, suffix: str) -> None:
        parts = suffix.split("/", 1)
        rest = parts[1] if len(parts) > 1 else ""
        try:
            g = int(parts[0])
            if not 0 <= g < self.engine.cfg.groups:
                raise ValueError
        except ValueError:
            ctx.send_json(404, {"message": f"no such tenant {parts[0]!r}"})
            return
        # Lifecycle verbs on the bare /tenants/{g} path.
        if rest == "":
            if ctx.method == "PUT":
                if not self._lifecycle_ok(ctx, g):
                    ctx.send_json(401,
                                  {"message": "Insufficient credentials"})
                    return
                self._create(ctx, g)
            elif ctx.method == "DELETE":
                if not self._lifecycle_ok(ctx, g):
                    ctx.send_json(401,
                                  {"message": "Insufficient credentials"})
                    return
                try:
                    self.engine.remove_tenant(g)
                except errors.EtcdError as e:
                    ctx.send(e.status_code, e.to_json().encode() + b"\n",
                             "application/json")
                    return
                # No cache pop needed: remove_tenant bumped the slot's
                # lifecycle generation, so the next _api(g) discards the
                # stale handlers (popping here would race a concurrent
                # request's freshly-rebuilt entry).
                ctx.send_json(200, {"removed": g})
            elif ctx.method == "GET":
                if self.engine.tenant_active(g):
                    ctx.send_json(200, self.engine.status(g))
                else:
                    ctx.send_json(404, {"message": f"no such tenant {g}"})
            else:
                ctx.send(405, b"Method Not Allowed",
                         headers={"Allow": "GET, PUT, DELETE"})
            return
        if not self.engine.tenant_active(g):
            ctx.send_json(404, {"message": f"tenant {g} not provisioned"})
            return
        if rest == "v2/keys" or rest.startswith("v2/keys/"):
            self._api(g).handle_keys(ctx, rest[len("v2/keys"):])
        elif rest == "v2/security" or rest.startswith("v2/security/"):
            self._handle_security(ctx, g, rest[len("v2/security"):])
        elif rest.startswith("v2/stats/"):
            self._handle_stats(ctx, g, rest[len("v2/stats/"):])
        elif rest == "status":
            ctx.send_json(200, self.engine.status(g))
        elif rest == "conf":
            self._handle_conf(ctx, g)
        elif rest == "batch":
            self._handle_batch(ctx, g)
        elif rest == "batchframe":
            self._handle_batchframe(ctx, g)
        else:
            ctx.send_json(404, {"message": f"unknown tenant path {rest!r}"})

    def _handle_batch(self, ctx: Ctx, g: int) -> None:
        """POST /tenants/{g}/batch — the coalesced write surface the
        ingress tier (server/ingress.py) ships its flush windows through.
        Body: {"reqs": [{"method", "path", "value", "ttl", "dir",
        "recursive", "prevValue", "prevIndex", "prevExist", "refresh",
        "auth"}, ...]} (or a bare list); "auth" is the slot's client's
        Authorization header value, forwarded so per-user ACLs survive
        coalescing. The whole batch rides MultiEngine.do_many — one lock
        acquisition, one deep P_MULTI log entry per max_ents*batch_max
        window — and every request's outcome comes back IN-SLOT:
        {"results": [{"status": s, "event": {...}} | {"status": s,
        "error": {...}}, ...]}, aligned with the request array. A failed
        CAS or auth denial occupies its slot; it never fails the batch."""
        from etcd_tpu.etcdhttp.client import trim_prefix
        from etcd_tpu.server.cluster import STORE_KEYS_PREFIX
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "POST"})
            return
        try:
            body = json.loads(ctx.body.decode() or "{}")
            raw = body if isinstance(body, list) else body.get("reqs")
            if not isinstance(raw, list):
                raise ValueError('body must be {"reqs": [...]} or a list')
            if not raw:
                ctx.send_json(200, {"results": []})
                return
            reqs, auths = [], []
            for d in raw:
                reqs.append(self._parse_batch_item(d))
                a = d.get("auth")
                if a is not None and not isinstance(a, str):
                    raise ValueError('"auth" must be a string')
                auths.append(a)
        except errors.EtcdError as e:
            ctx.send(e.status_code, e.to_json().encode() + b"\n",
                     "application/json")
            return
        except (TypeError, ValueError, KeyError,
                json.JSONDecodeError) as e:
            ctx.send_json(400, {"message": f"bad batch body: {e}"})
            return
        # Per-request auth against the TENANT's own security handler,
        # each slot under ITS client's forwarded credentials ("auth"
        # field; slots without one fall back to the carrying request's):
        # a denied slot carries its 401 downstream, its batch-mates
        # still commit (the demux contract).
        sec = self._sec(g)
        results: list = [None] * len(reqs)
        admitted, admitted_idx = [], []
        for i, r in enumerate(reqs):
            slot_ctx = _BatchSlotCtx(ctx.method, auths[i]) \
                if auths[i] else ctx
            try:
                sec.check_key_access(slot_ctx, r)
            except errors.EtcdError as e:
                results[i] = e
                continue
            admitted.append(r)
            admitted_idx.append(i)
        if admitted:
            for i, res in zip(admitted_idx,
                              self.engine.do_many(g, admitted)):
                results[i] = res
        out = []
        for res in results:
            if isinstance(res, errors.EtcdError):
                if res.cause.startswith(STORE_KEYS_PREFIX):
                    res.cause = res.cause[len(STORE_KEYS_PREFIX):]
                out.append({"status": res.status_code,
                            "error": json.loads(res.to_json())})
            else:
                d = res.to_dict()
                created = (d.get("action") == "create"
                           or (d.get("action") == "set"
                               and d.get("prevNode") is None))
                out.append({"status": 201 if created else 200,
                            "event": trim_prefix(d)})
        ctx.send_json(200, {"results": out},
                      {"X-Etcd-Index":
                       str(self.engine.store(g).current_index)})

    def _handle_batchframe(self, ctx: Ctx, g: int) -> None:
        """POST /tenants/{g}/batchframe + Upgrade: etcd-batchframe — the
        ingress tier's persistent binary flush channel. After the 101
        this connection's handler thread becomes the frame READER: it
        parses each request frame (one walcodec-packed P_MULTI blob per
        flush), runs per-slot auth, and stages the flush through
        MultiEngine.submit_many WITHOUT waiting for commit — so a
        pipelined ingress window keeps frames flowing while earlier
        flushes are still in their fsync rounds. A per-channel COLLECTOR
        thread gathers each flush's results in submission order and
        writes one response frame per flush, each slot carrying the
        final client-facing body so the ingress fan-back does no JSON
        work. Frame-order submission preserves the lane's FIFO; the
        fsync-gated ack invariant is untouched because collect_many only
        yields results the ack path released."""
        from etcd_tpu.server import batchframe
        if (ctx.method != "POST"
                or ctx.headers.get("Upgrade", "").lower()
                != batchframe.UPGRADE_NAME):
            ctx.send_json(426, {"message": "batchframe requires POST + "
                                           "Upgrade: etcd-batchframe"},
                          {"Upgrade": batchframe.UPGRADE_NAME})
            return
        rfile, wfile = ctx.hijack()
        try:
            wfile.write(batchframe.handshake_response())
            wfile.flush()
        except OSError:
            return
        jobs: queue.Queue = queue.Queue()
        dead = threading.Event()
        collector = threading.Thread(
            target=self._batchframe_collector, args=(g, jobs, wfile, dead),
            daemon=True, name=f"batchframe-collect{g}")
        collector.start()
        try:
            while not dead.is_set():
                frame = batchframe.read_request_frame(rfile)
                if frame is None:
                    break
                jobs.put(self._batchframe_submit(g, *frame))
        except OSError:
            pass
        finally:
            jobs.put(None)
            collector.join(timeout=30)

    def _batchframe_submit(self, g: int, flush_id: int, auth_json: bytes,
                           payload: bytes) -> tuple:
        """Parse + auth-check + stage one request frame (reader thread,
        non-blocking). Returns the collector's job: either a staged
        flush or a frame-level error every rider of the flush gets."""
        from etcd_tpu.server.engine import _unpack_multi
        try:
            if not payload:
                raise ValueError("empty payload")
            blobs = _unpack_multi(payload)
            auths = (json.loads(auth_json.decode()) if auth_json
                     else [None] * len(blobs))
            if not isinstance(auths, list) or len(auths) != len(blobs):
                raise ValueError("auth list does not match slot count")
            reqs = [self._parse_batch_item(json.loads(b)) for b in blobs]
        except errors.EtcdError as e:
            return (flush_id, None, None, None,
                    (e.status_code, e.to_json().encode() + b"\n"))
        except Exception as e:  # noqa: BLE001 — channel input, fail the flush
            body = json.dumps(
                {"message": f"bad batchframe payload: {e}"}).encode()
            return (flush_id, None, None, None, (400, body + b"\n"))
        sec = self._sec(g)
        results: list = [None] * len(reqs)
        admitted, admitted_idx = [], []
        for i, r in enumerate(reqs):
            try:
                sec.check_key_access(_BatchSlotCtx("POST", auths[i]), r)
            except errors.EtcdError as e:
                results[i] = e
                continue
            admitted.append(r)
            admitted_idx.append(i)
        queues = self.engine.submit_many(g, admitted) if admitted else []
        return (flush_id, results, admitted_idx, queues, None)

    def _batchframe_collector(self, g: int, jobs: queue.Queue, wfile,
                              dead: threading.Event) -> None:
        """Per-channel collector: block on each staged flush's results in
        submission order and write its response frame. Responses demux by
        flush id on the ingress side, so ordering here is a convenience,
        not a contract."""
        from etcd_tpu.etcdhttp.client import trim_prefix
        from etcd_tpu.server import batchframe
        from etcd_tpu.server.cluster import STORE_KEYS_PREFIX
        broken = False
        while True:
            job = jobs.get()
            if job is None:
                return
            flush_id, results, admitted_idx, queues, err = job
            if broken:
                # Channel already gone: the responses have nowhere to
                # go (the ingress demux 503s the in-flight ids), but
                # every staged flush must still be COLLECTED — its
                # submit_many registered waiters and counted pending
                # proposals, and only collect_many releases both. Skip
                # it and the engine reports phantom pending proposals
                # forever (the bench's inter-leg drain barrier hangs on
                # exactly that gauge after the SIGKILL leg).
                if queues:
                    self.engine.collect_many(g, queues)
                continue
            if err is not None:
                frame = batchframe.pack_error_frame(flush_id, err[0],
                                                    err[1])
            else:
                if queues:
                    for i, res in zip(admitted_idx,
                                      self.engine.collect_many(g, queues)):
                        results[i] = res
                slots = []
                for res in results:
                    if isinstance(res, errors.EtcdError):
                        if res.cause.startswith(STORE_KEYS_PREFIX):
                            res.cause = res.cause[len(STORE_KEYS_PREFIX):]
                        slots.append((res.status_code,
                                      res.to_json().encode() + b"\n"))
                    else:
                        d = res.to_dict()
                        created = (d.get("action") == "create"
                                   or (d.get("action") == "set"
                                       and d.get("prevNode") is None))
                        slots.append((201 if created else 200,
                                      json.dumps(trim_prefix(d)).encode()
                                      + b"\n"))
                frame = batchframe.pack_response_frame(flush_id, slots)
            try:
                wfile.write(frame)
                wfile.flush()
            except OSError:
                # Channel gone: the reader unblocks on EOF/ sever; every
                # un-responded flush 503s ingress-side (its demux fails
                # exactly the in-flight ids — never a retry). Keep
                # draining so later staged flushes get collected.
                dead.set()
                broken = True

    def _parse_batch_item(self, d: dict):
        """One batch item -> Request (the JSON twin of ClientAPI's
        parseKeyRequest form fields; TTLs resolve against this server's
        clock exactly as the per-request path does)."""
        import posixpath
        from etcd_tpu.server.cluster import STORE_KEYS_PREFIX
        from etcd_tpu.server.request import Request
        if not isinstance(d, dict):
            raise ValueError("batch item must be an object")
        method = d.get("method", "PUT")
        if method not in ("PUT", "POST", "DELETE"):
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"bad batch method {method!r}")
        suffix = d.get("path", "")
        if not isinstance(suffix, str):
            raise ValueError("path must be a string")
        p = posixpath.normpath(STORE_KEYS_PREFIX + "/" + suffix.lstrip("/"))
        if p != STORE_KEYS_PREFIX and \
                not p.startswith(STORE_KEYS_PREFIX + "/"):
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"invalid key path {suffix!r}")
        expiration = None
        ttl = d.get("ttl")
        if ttl is not None:
            ttl = int(ttl)
            if ttl < 0:
                raise errors.EtcdError(errors.ECODE_TTL_NAN,
                                       cause='invalid value for "ttl"')
            if ttl > 0:
                expiration = time.time() + ttl
        prev_exist = d.get("prevExist")
        if prev_exist is not None:
            prev_exist = bool(prev_exist)
        return Request(
            method=method, path=p, val=str(d.get("value", "")),
            dir=bool(d.get("dir", False)),
            recursive=bool(d.get("recursive", False)),
            prev_value=str(d.get("prevValue", "")),
            prev_index=int(d.get("prevIndex", 0)),
            prev_exist=prev_exist, expiration=expiration,
            refresh=bool(d.get("refresh", False)))

    def _handle_security(self, ctx: Ctx, g: int, sub: str) -> None:
        """Per-tenant /v2/security/{roles,users,enable} (reference
        client_security.go routes, one instance per tenant group)."""
        sec = self._sec(g)
        if sub == "/enable":
            sec.handle_enable(ctx, "")
        elif sub == "/roles" or sub.startswith("/roles/"):
            sec.handle_roles(ctx, sub[len("/roles"):])
        elif sub == "/users" or sub.startswith("/users/"):
            sec.handle_users(ctx, sub[len("/users"):])
        else:
            ctx.send_json(404, {"message": f"unknown security path {sub!r}"})

    def _handle_stats(self, ctx: Ctx, g: int, which: str) -> None:
        """Per-tenant /v2/stats/{store,self,leader} (reference stats/
        payloads; self/leader report the tenant's consensus view from the
        engine — there is no per-tenant network transport to meter)."""
        eng = self.engine
        if which == "store":
            ctx.send_json(200, eng.store(g).json_stats())
            return
        lead = eng.leader_slot(g)
        st = eng.status(g)
        if which == "self":
            ctx.send_json(200, {
                "name": f"tenant{g}",
                "id": f"{g:x}",
                "state": ("StateLeader" if lead == 0 else "StateFollower"),
                "leaderInfo": {"leader": f"{lead:x}" if lead >= 0 else ""},
                "raftTerm": st["term"],
                "raftIndex": st["commit"],
                "appliedIndex": st["applied"],
            })
        elif which == "leader":
            if lead < 0:
                # Mid-election: the reference answers 403 from non-leaders
                # rather than fabricating a leader id.
                ctx.send_json(403, {"message": "not current leader"})
                return
            followers = {f"{s:x}": {"counts": {"fail": 0, "success":
                                               st["applied"]},
                         "latency": {}}
                         for s in st["active_slots"] if s != lead}
            ctx.send_json(200, {"leader": f"{lead:x}",
                                "followers": followers})
        else:
            ctx.send_json(404, {"message": f"unknown stats path {which!r}"})

    def _handle_conf(self, ctx: Ctx, g: int) -> None:
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "POST"})
            return
        # Membership mutation needs root once the TENANT's security is on
        # (reference /v2/members root gate, client.go:184-187) — without
        # this, an unauthenticated client could shrink an authenticated
        # tenant's quorum.
        if not self._sec(g).check_members_access(ctx):
            ctx.send_json(401, {"message": "Insufficient credentials"})
            return
        try:
            d = json.loads(ctx.body.decode() or "{}")
            slots = self.engine.conf_change(g, d["op"], int(d["slot"]))
        except errors.EtcdError as e:
            ctx.send(e.status_code, e.to_json().encode() + b"\n",
                     "application/json")
            return
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            ctx.send_json(400, {"message": f"bad conf body: {e}"})
            return
        ctx.send_json(200, {"group": g, "active_slots": slots})

    def handle_engine_status(self, ctx: Ctx, suffix: str) -> None:
        eng = self.engine
        leaders = sum(1 for g in range(eng.cfg.groups)
                      if eng.leader_slot(g) >= 0)
        out = {
            "groups": eng.cfg.groups,
            "tenants_active": len(eng.tenants()),
            "peers": eng.cfg.peers,
            "round": eng.round_no,
            "round_ms_ewma": round(eng.round_ms_ewma, 3),
            "groups_with_leader": leaders,
            "applied_total": int(eng.applied.sum()),
            "acked_requests": eng.acked_requests,
            "pending_payloads": len(eng.payloads),
        }
        # Multi-host engines expose their catch-up counters too.
        for k in ("pulls_sent", "payloads_pulled", "pay_frames_dropped",
                  "snaps_sent", "snaps_installed"):
            v = getattr(eng, k, None)
            if v is not None:
                out[k] = v
        ctx.send_json(200, out)

    def handle_metrics(self, ctx: Ctx, suffix: str) -> None:
        """GET /metrics — Prometheus text exposition of every registered
        series (reference etcdserver metrics.go + pkg/metrics): the
        proposal reference metrics, per-compartment histograms and
        gauges (round loop, WAL writer shards, applier shards, ack
        gate), and process stats."""
        from etcd_tpu.utils.metrics import REGISTRY, fd_usage
        used, limit = fd_usage()
        extra = [
            "# HELP process_open_fds Number of open file descriptors.",
            "# TYPE process_open_fds gauge",
            f"process_open_fds {float(used)}",
            "# HELP process_max_fds Maximum number of open file "
            "descriptors.",
            "# TYPE process_max_fds gauge",
            f"process_max_fds {float(limit)}",
            "",
        ]
        body = (REGISTRY.expose() + "\n".join(extra)).encode()
        ctx.send(200, body, "text/plain; version=0.0.4")

    def handle_debug_flight(self, ctx: Ctx, suffix: str) -> None:
        """GET /debug/flight — the round flight recorder as Chrome
        trace-event JSON (load in chrome://tracing / Perfetto). POST
        dumps the same snapshot to <data_dir>/diagnostics/ on disk."""
        obs = getattr(self.engine, "obs", None)
        if obs is None:
            ctx.send_json(404, {"message": "engine has no flight "
                                           "recorder"})
            return
        if ctx.method == "POST":
            path = self.engine.dump_flight("http")
            ctx.send_json(200, {"dumped": path})
            return
        ctx.send_json(200, obs.flight.to_trace_events())

    def handle_debug_traces(self, ctx: Ctx, suffix: str) -> None:
        """GET /debug/traces — sampled end-to-end proposal spans (stage
        -> relative seconds per request id); empty unless
        ETCD_TPU_TRACE_EVERY is set."""
        obs = getattr(self.engine, "obs", None)
        if obs is None:
            ctx.send_json(404, {"message": "engine has no tracer"})
            return
        ctx.send_json(200, obs.tracer.dump())

    def handle_health(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"health": "true"})

    def handle_version(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"releaseVersion": version.VERSION})


class EngineHttp:
    """A listening HTTP front for a MultiEngine."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 cors=None, tls_context=None,
                 admin_credentials=None) -> None:
        self.engine = engine
        router = Router()
        self.api = TenantAPI(engine, admin_credentials=admin_credentials)
        self.api.install(router)
        self.http = HttpServer(host, port, router, cors=cors,
                               tls_context=tls_context)

    @property
    def url(self) -> str:
        return self.http.url

    def start(self) -> None:
        self.http.start()

    def stop(self) -> None:
        self.http.stop()
