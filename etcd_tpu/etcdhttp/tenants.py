"""Multi-tenant HTTP surface for the batched MultiNode engine: the full
/v2/keys matrix served per consensus group from ONE kernel.

Routes (the multi-tenant re-framing of reference etcdserver/etcdhttp —
each tenant group gets the same client API one etcd cluster exposes):

    /tenants/{g}/v2/keys/...   full v2 keys CRUD/CAS/CAD/watch (reuses
                               ClientAPI via a per-tenant server adapter)
    /tenants/{g}/status        group consensus status (leader, term,
                               commit, applied, active slots)
    /tenants/{g}/conf          POST {"op": "add"|"remove", "slot": n} —
                               membership change through the group's own
                               consensus (reference /v2/members semantics)
    /engine/status             engine-wide summary
    /health, /version          liveness + version (reference client.go)
"""
from __future__ import annotations

import json
import time
from typing import Dict

from etcd_tpu import errors, version
from etcd_tpu.etcdhttp.client import ClientAPI
from etcd_tpu.etcdhttp.web import Ctx, HttpServer, Router


class _TenantCluster:
    """Just enough cluster surface for ClientAPI._headers."""

    def __init__(self, g: int) -> None:
        self.cluster_id = g


class _TenantServer:
    """Adapts one engine group to the `server` interface ClientAPI drives
    (do/store/clock/stopped/commit_index/term), so the entire keys path —
    parsing, CAS/CAD, long-poll + stream watch — is shared verbatim with
    the single-cluster server (etcdhttp/client.py)."""

    def __init__(self, engine, g: int) -> None:
        self._engine = engine
        self._g = g
        self.cluster = _TenantCluster(g)
        self.clock = time.time

    def do(self, r):
        return self._engine.do(self._g, r)

    @property
    def store(self):
        return self._engine.store(self._g)

    @property
    def stopped(self) -> bool:
        return self._engine._stop_ev.is_set()

    @property
    def commit_index(self) -> int:
        return int(self._engine.h_commit[self._g].max())

    @property
    def term(self) -> int:
        return int(self._engine.h_term[self._g].max())


class TenantAPI:
    """Router glue: dispatches /tenants/{g}/... to per-tenant ClientAPIs."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self._apis: Dict[int, ClientAPI] = {}

    def install(self, router: Router) -> None:
        router.add("/tenants/", self.handle_tenants)
        router.add("/engine/status", self.handle_engine_status)
        router.add("/health", self.handle_health)
        router.add("/version", self.handle_version)

    def _api(self, g: int) -> ClientAPI:
        api = self._apis.get(g)
        if api is None:
            api = self._apis[g] = ClientAPI(_TenantServer(self.engine, g))
        return api

    def handle_tenants(self, ctx: Ctx, suffix: str) -> None:
        parts = suffix.split("/", 1)
        rest = parts[1] if len(parts) > 1 else ""
        try:
            g = int(parts[0])
            if not 0 <= g < self.engine.cfg.groups:
                raise ValueError
        except ValueError:
            ctx.send_json(404, {"message": f"no such tenant {parts[0]!r}"})
            return
        if rest == "v2/keys" or rest.startswith("v2/keys/"):
            self._api(g).handle_keys(ctx, rest[len("v2/keys"):])
        elif rest == "status":
            ctx.send_json(200, self.engine.status(g))
        elif rest == "conf":
            self._handle_conf(ctx, g)
        else:
            ctx.send_json(404, {"message": f"unknown tenant path {rest!r}"})

    def _handle_conf(self, ctx: Ctx, g: int) -> None:
        if ctx.method != "POST":
            ctx.send(405, b"Method Not Allowed", headers={"Allow": "POST"})
            return
        try:
            d = json.loads(ctx.body.decode() or "{}")
            slots = self.engine.conf_change(g, d["op"], int(d["slot"]))
        except errors.EtcdError as e:
            ctx.send(e.status_code, e.to_json().encode() + b"\n",
                     "application/json")
            return
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            ctx.send_json(400, {"message": f"bad conf body: {e}"})
            return
        ctx.send_json(200, {"group": g, "active_slots": slots})

    def handle_engine_status(self, ctx: Ctx, suffix: str) -> None:
        eng = self.engine
        leaders = sum(1 for g in range(eng.cfg.groups)
                      if eng.leader_slot(g) >= 0)
        ctx.send_json(200, {
            "groups": eng.cfg.groups,
            "peers": eng.cfg.peers,
            "round": eng.round_no,
            "round_ms_ewma": round(eng.round_ms_ewma, 3),
            "groups_with_leader": leaders,
            "applied_total": int(eng.applied.sum()),
            "acked_requests": eng.acked_requests,
            "pending_payloads": len(eng.payloads),
        })

    def handle_health(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"health": "true"})

    def handle_version(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"releaseVersion": version.VERSION})


class EngineHttp:
    """A listening HTTP front for a MultiEngine."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 cors=None, tls_context=None) -> None:
        self.engine = engine
        router = Router()
        self.api = TenantAPI(engine)
        self.api.install(router)
        self.http = HttpServer(host, port, router, cors=cors,
                               tls_context=tls_context)

    @property
    def url(self) -> str:
        return self.http.url

    def start(self) -> None:
        self.http.start()

    def stop(self) -> None:
        self.http.stop()
