"""The public v2 HTTP API.

Behavioral equivalent of reference etcdserver/etcdhttp/client.go: the full
/v2/keys matrix (CRUD, CAS/CAD, in-order POST, TTL, long-poll + streaming
watch — parseKeyRequest client.go:390-534, writeKeyEvent client.go:536-551,
handleKeyWatch client.go:553-597), /v2/members admin (client.go:180-286),
/v2/machines, /v2/stats/{self,leader,store}, /version and /health, with the
X-Etcd-Cluster-ID / X-Etcd-Index / X-Raft-Index / X-Raft-Term header
contract and the numeric-error JSON bodies of error/error.go.
"""
from __future__ import annotations

import json
import posixpath
from typing import Dict, Optional

from etcd_tpu import errors, version as ver
from etcd_tpu.utils import metrics
from etcd_tpu.server.cluster import Member, STORE_KEYS_PREFIX
from etcd_tpu.server.request import (METHOD_DELETE, METHOD_GET, METHOD_POST,
                                     METHOD_PUT, Request)
from etcd_tpu.etcdhttp.web import Ctx, Router
from etcd_tpu.store.event import Event

KEYS_PREFIX = "/v2/keys"
MEMBERS_PREFIX = "/v2/members"
MACHINES_PREFIX = "/v2/machines"
STATS_PREFIX = "/v2/stats"

_BOOL_FIELDS = ("recursive", "sorted", "quorum", "wait", "stream", "dir",
                "refresh", "noValueOnSuccess")

# Actions whose successful response is 201 Created (reference
# store/event.go IsCreated: create, or set with prevExist=false).
_CREATED_ACTIONS = {"create"}


def _parse_bool(ctx: Ctx, field: str) -> bool:
    raw = ctx.value(field, "")
    if raw in ("", "false"):
        return False
    if raw == "true":
        return True
    raise errors.EtcdError(errors.ECODE_INVALID_FIELD,
                           cause=f'invalid value for "{field}"')


def trim_prefix(d: dict, prefix: str = STORE_KEYS_PREFIX) -> dict:
    """Strip the internal keys prefix from every node key in a response body
    (reference trimEventPrefix / trimNodeExternPrefix client.go:600-625)."""
    def trim_node(n: dict) -> dict:
        n = dict(n)
        k = n.get("key", "")
        if k.startswith(prefix):
            n["key"] = k[len(prefix):] or "/"
        if n.get("nodes") is not None:
            n["nodes"] = [trim_node(c) for c in n["nodes"]]
        return n

    d = dict(d)
    for field in ("node", "prevNode"):
        if d.get(field) is not None:
            d[field] = trim_node(d[field])
    return d


class ClientAPI:
    """Routes for one EtcdServer's client listener. `security` is wired in by
    the security module when auth is enabled (hasKeyPrefixAccess gate)."""

    def __init__(self, server, security=None) -> None:
        self.server = server
        self.security = security

    # -- routing --------------------------------------------------------------

    def install(self, router: Router) -> None:
        router.add(KEYS_PREFIX, self.handle_keys)
        router.add(MEMBERS_PREFIX, self.handle_members)
        router.add(MACHINES_PREFIX, self.handle_machines, exact=True)
        router.add(STATS_PREFIX + "/self", self.handle_stats_self, exact=True)
        router.add(STATS_PREFIX + "/leader", self.handle_stats_leader,
                   exact=True)
        router.add(STATS_PREFIX + "/store", self.handle_stats_store,
                   exact=True)
        router.add("/version", self.handle_version, exact=True)
        router.add("/health", self.handle_health, exact=True)
        router.add("/metrics", self.handle_metrics, exact=True)
        router.add("/debug/vars", self.handle_debug_vars, exact=True)

    # -- shared helpers -------------------------------------------------------

    def _headers(self, etcd_index: Optional[int] = None) -> Dict[str, str]:
        s = self.server
        h = {"X-Etcd-Cluster-ID": f"{s.cluster.cluster_id:x}"}
        if etcd_index is not None:
            h["X-Etcd-Index"] = str(etcd_index)
            h["X-Raft-Index"] = str(s.commit_index)
            h["X-Raft-Term"] = str(s.term)
        return h

    def _error(self, ctx: Ctx, err: errors.EtcdError) -> None:
        if not err.index:
            err.index = self.server.store.current_index
        # The internal store prefix must not leak into user-visible causes
        # (reference trimErrorPrefix, client.go:142,622-626).
        if err.cause.startswith(STORE_KEYS_PREFIX):
            err.cause = err.cause[len(STORE_KEYS_PREFIX):]
        ctx.send(err.status_code, err.to_json().encode() + b"\n",
                 "application/json", self._headers(err.index))

    # -- /v2/keys -------------------------------------------------------------

    def handle_keys(self, ctx: Ctx, suffix: str) -> None:
        if ctx.method not in ("GET", "PUT", "POST", "DELETE", "HEAD"):
            ctx.send(405, b"Method Not Allowed",
                     headers={"Allow": "GET, PUT, POST, DELETE, HEAD"})
            return
        try:
            r = self._parse_key_request(ctx, suffix)
            no_value = _parse_bool(ctx, "noValueOnSuccess")
            if self.security is not None:
                self.security.check_key_access(ctx, r)
            result = self.server.do(r)
        except errors.EtcdError as e:
            self._error(ctx, e)
            return
        if isinstance(result, Event):
            self._write_key_event(ctx, result, no_value=no_value)
        else:  # a Watcher from store.watch
            self._handle_watch(ctx, r, result)

    def _parse_key_request(self, ctx: Ctx, suffix: str) -> Request:
        """reference parseKeyRequest client.go:390-534."""
        method = "GET" if ctx.method == "HEAD" else ctx.method
        if method not in (METHOD_GET, METHOD_PUT, METHOD_POST, METHOD_DELETE):
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"bad method {method}")
        p = posixpath.normpath(STORE_KEYS_PREFIX + "/" + suffix.lstrip("/"))
        if p != STORE_KEYS_PREFIX and \
                not p.startswith(STORE_KEYS_PREFIX + "/"):
            # ".." segments must not escape the keys namespace into the
            # internal /0 cluster-metadata tree.
            raise errors.EtcdError(errors.ECODE_INVALID_FORM,
                                   cause=f"invalid key path {suffix!r}")
        flags = {f: _parse_bool(ctx, f) for f in _BOOL_FIELDS}

        if ctx.has("prevValue") and ctx.value("prevValue") == "":
            raise errors.EtcdError(errors.ECODE_PREV_VALUE_REQUIRED,
                                   cause='"prevValue" cannot be empty')
        prev_value = ctx.value("prevValue", "")

        prev_index = 0
        if ctx.value("prevIndex"):
            try:
                prev_index = int(ctx.value("prevIndex"))
                if prev_index < 0:
                    raise ValueError
            except ValueError:
                raise errors.EtcdError(errors.ECODE_INDEX_NAN,
                                       cause='invalid value for "prevIndex"')

        prev_exist: Optional[bool] = None
        if ctx.has("prevExist"):
            raw = ctx.value("prevExist")
            if raw not in ("true", "false"):
                raise errors.EtcdError(errors.ECODE_INVALID_FIELD,
                                       cause='invalid value for "prevExist"')
            prev_exist = raw == "true"

        since = 0
        if ctx.value("waitIndex"):
            try:
                since = int(ctx.value("waitIndex"))
                if since < 0:
                    raise ValueError
            except ValueError:
                raise errors.EtcdError(errors.ECODE_INDEX_NAN,
                                       cause='invalid value for "waitIndex"')

        expiration: Optional[float] = None
        if ctx.value("ttl"):
            try:
                ttl = int(ctx.value("ttl"))
                if ttl < 0:
                    raise ValueError
            except ValueError:
                raise errors.EtcdError(errors.ECODE_TTL_NAN,
                                       cause='invalid value for "ttl"')
            if ttl > 0:
                expiration = self.server.clock() + ttl

        if flags["wait"] and flags["quorum"]:
            raise errors.EtcdError(
                errors.ECODE_INVALID_FIELD,
                cause='"quorum" is incompatible with "wait"')
        if flags["stream"] and not flags["wait"]:
            raise errors.EtcdError(
                errors.ECODE_INVALID_FIELD,
                cause='"stream" requires "wait"')
        if flags["refresh"]:
            if ctx.has("value"):
                raise errors.EtcdError(
                    errors.ECODE_REFRESH_VALUE,
                    cause="A value was provided on a refresh")
            if expiration is None:
                raise errors.EtcdError(
                    errors.ECODE_REFRESH_TTL_REQUIRED,
                    cause="No TTL value set")

        return Request(
            method=method, path=p, val=ctx.value("value", ""),
            dir=flags["dir"], prev_value=prev_value, prev_index=prev_index,
            prev_exist=prev_exist, expiration=expiration,
            wait=flags["wait"], since=since, recursive=flags["recursive"],
            sorted=flags["sorted"], quorum=flags["quorum"],
            stream=flags["stream"], refresh=flags["refresh"])

    def _write_key_event(self, ctx: Ctx, e: Event,
                         no_value: bool = False) -> None:
        """reference writeKeyEvent client.go:536-551."""
        # IsCreated (reference store/event.go:48-58): an explicit create, or
        # a set that made a new node (no prevNode), answers 201.
        created = (e.action in _CREATED_ACTIONS or
                   (e.action == "set" and e.prev_node is None))
        status = 201 if created else 200
        d = e.to_dict()
        if no_value and e.action in ("set", "update", "create",
                                     "compareAndSwap", "compareAndDelete"):
            # noValueOnSuccess strips the payload echo (reference
            # writeKeyEvent noValueOnSuccess handling).
            d.pop("node", None)
            d.pop("prevNode", None)
        body = json.dumps(trim_prefix(d)).encode() + b"\n"
        ctx.send(status, body, "application/json",
                 self._headers(e.etcd_index))

    def _handle_watch(self, ctx: Ctx, r: Request, watcher) -> None:
        """Long-poll or chunked stream (reference handleKeyWatch
        client.go:553-597). The watcher is released on client disconnect."""
        headers = self._headers(getattr(watcher, "start_index",
                                        self.server.store.current_index))
        try:
            if not r.stream:
                while True:
                    e = watcher.next_event(timeout=0.5)
                    if e is not None:
                        body = (json.dumps(trim_prefix(e.to_dict())).encode()
                                + b"\n")
                        ctx.send(200, body, "application/json", headers)
                        return
                    if watcher.removed or ctx.client_gone() or \
                            self.server.stopped:
                        ctx.send(200, b"", "application/json", headers)
                        return
            else:
                ctx.begin_stream(200, "application/json", headers)
                while True:
                    e = watcher.next_event(timeout=0.5)
                    if e is not None:
                        data = (json.dumps(trim_prefix(e.to_dict())).encode()
                                + b"\n")
                        if not ctx.write_chunk(data):
                            return
                    elif watcher.removed or ctx.client_gone() or \
                            self.server.stopped:
                        ctx.end_stream()
                        return
        finally:
            watcher.remove()

    # -- /v2/members ----------------------------------------------------------

    def handle_members(self, ctx: Ctx, suffix: str) -> None:
        s = self.server
        h = self._headers()
        # Mutations need root once security is on (reference client.go:184-187
        # hasWriteRootAccess).
        if (self.security is not None and
                not self.security.check_members_access(ctx)):
            ctx.send_json(401, {"message": "Insufficient credentials"}, h)
            return
        try:
            if ctx.method == "GET" and suffix in ("", "/"):
                body = {"members": [self._member_dict(m)
                                    for m in s.cluster.members()]}
                ctx.send_json(200, body, h)
            elif ctx.method == "POST" and suffix in ("", "/"):
                req = self._parse_member_body(ctx)
                m = Member.new(req.get("name", ""), req["peerURLs"],
                               s.cluster.token)
                s.add_member(m)
                ctx.send_json(201, self._member_dict(m), h)
            elif ctx.method == "DELETE" and suffix.startswith("/"):
                mid = self._parse_member_id(suffix)
                if s.cluster.is_id_removed(mid):
                    ctx.send(410, b"Member permanently removed\n",
                             headers=h)
                    return
                s.remove_member(mid)
                ctx.send(204, headers=h)
            elif ctx.method == "PUT" and suffix.startswith("/"):
                mid = self._parse_member_id(suffix)
                req = self._parse_member_body(ctx)
                old = s.cluster.member(mid)
                m = Member(id=mid, name=old.name if old else "",
                           peer_urls=tuple(req["peerURLs"]),
                           client_urls=old.client_urls if old else ())
                s.update_member(m)
                ctx.send(204, headers=h)
            else:
                ctx.send(405, b"Method Not Allowed",
                         headers={"Allow": "GET, POST, DELETE, PUT"})
        except errors.EtcdError as e:
            code = 500 if e.code in (errors.ECODE_RAFT_INTERNAL,
                                     errors.ECODE_LEADER_ELECT) else 409
            if e.code == errors.ECODE_KEY_NOT_FOUND:
                code = 404
            ctx.send_json(code, {"message": e.cause or e.message}, h)
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            ctx.send_json(400, {"message": f"bad member request: {e}"}, h)

    @staticmethod
    def _member_dict(m: Member) -> dict:
        return {"id": f"{m.id:x}", "name": m.name,
                "peerURLs": list(m.peer_urls),
                "clientURLs": list(m.client_urls)}

    @staticmethod
    def _parse_member_body(ctx: Ctx) -> dict:
        d = json.loads(ctx.body.decode() or "{}")
        urls = d.get("peerURLs")
        if not urls or not isinstance(urls, list):
            raise ValueError("peerURLs required")
        for u in urls:
            if not (u.startswith("http://") or u.startswith("https://")):
                raise ValueError(f"invalid peer URL {u!r}")
        return d

    @staticmethod
    def _parse_member_id(suffix: str) -> int:
        return int(suffix.strip("/"), 16)

    # -- misc surfaces --------------------------------------------------------

    def handle_machines(self, ctx: Ctx, suffix: str) -> None:
        urls = self.server.cluster.client_urls()
        ctx.send(200, ", ".join(urls).encode(), "text/plain",
                 self._headers())

    def handle_stats_self(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, self.server.stats.to_dict(), self._headers())

    def handle_stats_leader(self, ctx: Ctx, suffix: str) -> None:
        s = self.server
        if not s.is_leader():
            e = errors.EtcdError(errors.ECODE_RAFT_INTERNAL,
                                 cause="not current leader")
            ctx.send(403, e.to_json().encode() + b"\n", "application/json",
                     self._headers())
            return
        ctx.send_json(200, s.lstats.to_dict(), self._headers())

    def handle_stats_store(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, self.server.store.stats.to_dict(),
                      self._headers())

    def handle_version(self, ctx: Ctx, suffix: str) -> None:
        ctx.send_json(200, {"etcdserver": ver.VERSION,
                            "etcdcluster": self.server.cluster_version()})

    def handle_health(self, ctx: Ctx, suffix: str) -> None:
        healthy = (self.server.leader_id != 0 and not self.server.stopped
                   and not getattr(self.server, "_fatal", False))
        ctx.send_json(200 if healthy else 503,
                      {"health": "true" if healthy else "false"})

    def handle_metrics(self, ctx: Ctx, suffix: str) -> None:
        """Prometheus text exposition (reference client.go:53,102 wiring
        prometheus.Handler(); metric set per */metrics.go)."""
        used, _ = metrics.fd_usage()
        metrics.file_descriptors_used.set(used)
        ctx.send(200, metrics.REGISTRY.expose().encode(),
                 "text/plain; version=0.0.4")

    def handle_debug_vars(self, ctx: Ctx, suffix: str) -> None:
        """expvar-style JSON (reference client.go:317-331 serveVars:
        file_descriptor_limit + live raft.status)."""
        _, limit = metrics.fd_usage()
        st = self.server.raft_status()
        ctx.send_json(200, {"file_descriptor_limit": limit,
                            "raft.status": st})
