"""etcd_tpu: a TPU-native distributed consistent key-value framework.

Re-imagines etcd (reference at /root/reference) for multi-tenant operation:
thousands of co-hosted Raft groups stepped as one batched, data-parallel
consensus kernel on TPU (JAX/XLA/Pallas), with etcd's layering — WAL
durability, snapshots, v2 store (TTL/CAS/watch), HTTP API, membership,
proxy, discovery, CLI — preserved around it.
"""

__version__ = "0.1.0"
MIN_CLUSTER_VERSION = "2.0.0"
