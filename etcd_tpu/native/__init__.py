"""Native (C) hot paths with pure-Python fallbacks.

`./build` compiles walcodec.c into this package; everything here works
without it (the Python fallbacks are the reference implementations and
tests assert byte-identical behavior — tests/test_native.py).
"""
from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

_HDR = struct.Struct("<IIQ")

try:
    from etcd_tpu.native.walcodec import (encode_records as _c_encode,
                                          scan_records as _c_scan)
    HAVE_NATIVE = True
except ImportError:
    _c_encode = _c_scan = None
    HAVE_NATIVE = False


def _py_encode_records(records, crc: int) -> Tuple[bytes, int]:
    out = []
    for rtype, payload in records:
        crc = zlib.crc32(payload, crc) & 0xFFFFFFFF
        out.append(_HDR.pack(rtype, crc, len(payload)))
        out.append(payload)
    return b"".join(out), crc


def _py_scan_records(data: bytes, crc: int
                     ) -> Tuple[List[Tuple[int, bytes]], int, int]:
    out = []
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        rtype, rcrc, ln = _HDR.unpack_from(data, off)
        if off + _HDR.size + ln > n:
            break  # torn tail
        payload = data[off + _HDR.size: off + _HDR.size + ln]
        c = zlib.crc32(payload, crc) & 0xFFFFFFFF
        if c != rcrc:
            break  # bit flip: stop at the last good record
        crc = c
        out.append((rtype, payload))
        off += _HDR.size + ln
    return out, crc, off


def encode_records(records, crc: int) -> Tuple[bytes, int]:
    """Frame + chain-CRC a batch of (type, payload) records; returns
    (buffer, new_crc). One call per fsync batch."""
    if _c_encode is not None:
        return _c_encode(list(records), crc)
    return _py_encode_records(records, crc)


def scan_records(data: bytes, crc: int
                 ) -> Tuple[List[Tuple[int, bytes]], int, int]:
    """Decode + CRC-verify records from `data` starting at chain value
    `crc`; returns (records, new_crc, bytes_consumed). Stops cleanly at a
    torn tail or a checksum mismatch."""
    if _c_scan is not None:
        return _c_scan(data, crc)
    return _py_scan_records(data, crc)
